"""Informer layer: the shared watch cache and its indexed listers, the
CachedClient read path (equivalence with direct store lists, escape
hatch, rv barrier), relist-and-resume on history-ring gaps, _DelayQueue
workqueue semantics, and the headline benchmark — the informer-backed
reconcile path must issue >=10x fewer store scans and sweep a converged
256-pod/64-gang fleet >=3x faster than GROVE_INFORMER=0
(tools/bench_reconcile.py is the same harness)."""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from grove_tpu.api import Pod, PodClique, constants as c, new_meta
from grove_tpu.api.core import PodPhase, PodSpec
from grove_tpu.api.meta import Condition, OwnerReference, set_condition
from grove_tpu.api.serde import to_dict
from grove_tpu.runtime.controller import Request, _DelayQueue
from grove_tpu.runtime.informer import (
    CachedClient,
    Informer,
    InformerSet,
    LocalStoreSource,
)
from grove_tpu.store.client import Client
from grove_tpu.store.store import Store

from tools.bench_reconcile import run_once


@pytest.fixture
def cached():
    store = Store()
    client = CachedClient(Client(store), InformerSet(store=store))
    return store, client


def _pod(name, ns="default", labels=None, owner=None, phase=None):
    p = Pod(meta=new_meta(name, namespace=ns, labels=labels),
            spec=PodSpec(tpu_chips=1))
    if owner:
        p.meta.owner_references = [OwnerReference(
            kind=owner[0], name=owner[1], uid=owner[2] if len(owner) > 2
            else "u-" + owner[1])]
    if phase is not None:
        p.status.phase = phase
    return p


# ---- cache tracking + list equivalence ---------------------------------

def test_informer_tracks_store_mutations(cached):
    store, client = cached
    client.create(_pod("a", labels={"role": "w"}))
    assert [p.meta.name for p in client.list(Pod)] == ["a"]
    live = client.get(Pod, "a")
    live.status.node_name = "h0"
    client.update_status(live)
    assert client.list(Pod)[0].status.node_name == "h0"
    client.delete(Pod, "a")
    assert client.list(Pod) == []
    inf = client.informers.get("Pod")
    assert inf.rv == store.current_rv()


def test_cached_list_matches_direct_list(cached):
    store, client = cached
    direct = Client(store)
    for i in range(12):
        client.create(_pod(
            f"p{i}", ns="default" if i % 3 else "other",
            labels={"g": str(i % 2), "b": str(i % 4)},
            phase=PodPhase.RUNNING if i % 2 else PodPhase.PENDING))
    cases = [
        dict(namespace=None),
        dict(namespace="default"),
        dict(namespace="other"),
        dict(namespace="default", selector={"g": "1"}),
        dict(namespace=None, selector={"g": "0", "b": "2"}),
        dict(namespace=None, selector={"g": "0", "b": "1"}),
        dict(namespace=None, selector={"missing": "x"}),
        dict(namespace=None, fields={"phase": "Running"}),
        dict(namespace="default", selector={"g": "1"},
             fields={"phase": "Running,Pending"}),
    ]
    for kw in cases:
        want = [(o.meta.namespace, o.meta.name, o.meta.resource_version)
                for o in direct.list(Pod, **kw)]
        got = [(o.meta.namespace, o.meta.name, o.meta.resource_version)
               for o in client.list(Pod, **kw)]
        assert got == want, kw


def test_by_owner_and_by_label_indexes(cached):
    store, client = cached
    owners = {f"q{j}": client.create(PodClique(meta=new_meta(f"q{j}")))
              for j in range(2)}
    for i in range(6):
        parent = owners[f"q{i % 2}"]
        client.create(_pod(f"p{i}", labels={c.LABEL_PCLQ_NAME: f"q{i % 2}"},
                           owner=("PodClique", f"q{i % 2}",
                                  parent.meta.uid)))
    lister = client.lister(Pod)
    owned = lister.by_owner("default", ("PodClique", "q1"))
    assert [p.meta.name for p in owned] == ["p1", "p3", "p5"]
    ref = OwnerReference(kind="PodClique", name="q0")
    assert [p.meta.name for p in lister.by_owner("default", ref)] == \
        ["p0", "p2", "p4"]
    assert lister.by_owner("other", ("PodClique", "q0")) == []
    # by_label mirrors the selector list; the index follows deletes.
    assert [p.meta.name
            for p in lister.by_label({c.LABEL_PCLQ_NAME: "q0"})] == \
        ["p0", "p2", "p4"]
    client.delete(Pod, "p3")
    assert [p.meta.name
            for p in lister.by_owner("default", ("PodClique", "q1"))] == \
        ["p1", "p5"]


def test_cached_objects_are_shared_until_version_moves(cached):
    store, client = cached
    client.create(_pod("a"))
    first = client.list(Pod)[0]
    assert client.list(Pod)[0] is first  # shared, zero-copy reads
    live = client.get(Pod, "a")
    live.status.node_name = "h1"
    client.update_status(live)
    third = client.list(Pod)[0]
    assert third is not first
    assert first.status.node_name == ""  # old snapshot untouched


# ---- relist-and-resume + escape hatch ----------------------------------

def test_relist_on_history_ring_gap(cached):
    store, client = cached
    client.create(_pod("keeper", labels={"g": "0"}))
    client.list(Pod)  # seed
    inf = client.informers.get("Pod")
    relists0 = inf.relists
    store._history = type(store._history)(maxlen=4)  # shrink the ring
    for i in range(8):  # churn far past the ring
        client.create(_pod(f"n{i}", labels={"g": "1"}))
    names = [p.meta.name for p in client.list(Pod)]
    assert names == sorted(["keeper"] + [f"n{i}" for i in range(8)])
    assert inf.relists == relists0 + 1  # gap -> one reseed, not a crash
    # Indexes rebuilt by the relist, not left stale.
    assert len(client.list(Pod, selector={"g": "1"})) == 8


def test_informer_escape_hatch_restores_direct_reads(cached):
    store, client = cached
    client.create(_pod("a"))
    client.list(Pod)
    scans0 = store.list_scans
    client.list(Pod)
    assert store.list_scans == scans0  # cached: no store scan
    os.environ["GROVE_INFORMER"] = "0"
    try:
        assert [p.meta.name for p in client.list(Pod)] == ["a"]
        assert store.list_scans == scans0 + 1  # direct scan again
    finally:
        os.environ.pop("GROVE_INFORMER", None)


def test_push_fed_informer_rv_barrier():
    """wait_for_rv blocks until a pushed event lands (the wire-informer
    read-your-own-write barrier)."""

    class PushOnly:
        can_pull = False

        def relist(self, kind_cls):
            return 0, []

    inf = Informer(Pod, PushOnly())
    inf.relist_now("seed")
    assert not inf.wait_for_rv(5, timeout=0.05)
    t = threading.Timer(0.05, lambda: inf.apply_event(
        5, "ADDED", _pod("late")))
    t.start()
    try:
        assert inf.wait_for_rv(5, timeout=2.0)
        assert inf.lister().get("late") is not None
    finally:
        t.cancel()


def test_informer_metrics_exported(cached):
    store, client = cached
    client.create(_pod("a"))
    client.list(Pod)
    from grove_tpu.runtime.metrics import GLOBAL_METRICS
    text = GLOBAL_METRICS.render()
    assert 'grove_informer_cache_objects{kind="Pod"}' in text
    assert 'grove_informer_relists_total{kind="Pod",reason="seed"}' in text
    assert 'grove_informer_cache_reads_total{kind="Pod"}' in text
    assert "grove_informer_event_lag_seconds_bucket" in text


def test_create_refuses_orphan_of_deleted_owner(cached):
    """The cascade-race guard: a create landing after its controller
    owner's cascade delete is rejected (under the same store lock the
    cascade ran under) instead of leaking a permanently unowned
    object."""
    from grove_tpu.runtime.errors import NotFoundError

    store, client = cached
    pclq = client.create(PodClique(meta=new_meta("q")))
    client.delete(PodClique, "q")
    with pytest.raises(NotFoundError):
        client.create(_pod("q-0", owner=("PodClique", "q",
                                         pclq.meta.uid)))
    # Same name, different incarnation: the stale uid is equally gone.
    client.create(PodClique(meta=new_meta("q")))
    with pytest.raises(NotFoundError):
        client.create(_pod("q-0", owner=("PodClique", "q",
                                         pclq.meta.uid)))
    assert client.list(Pod) == []


# ---- _DelayQueue workqueue semantics -----------------------------------

def test_delay_queue_duplicate_enqueue_collapses():
    q = _DelayQueue("t")
    r = Request("default", "x")
    q.add(r)
    q.add(r)
    q.add(r)
    assert q.get(timeout=0.5) == r
    assert q.get(timeout=0.05) is None  # delivered once
    q.done(r)
    assert q.get(timeout=0.05) is None  # not re-armed: never marked dirty


def test_delay_queue_dirty_rearm_via_done():
    q = _DelayQueue("t")
    r = Request("default", "x")
    q.add(r)
    assert q.get(timeout=0.5) == r
    q.add(r)  # re-added WHILE processing -> dirty
    assert q.get(timeout=0.05) is None  # not delivered until done()
    q.done(r)
    assert q.get(timeout=0.5) == r  # re-armed exactly once
    q.done(r)
    assert q.get(timeout=0.05) is None


def test_delay_queue_backoff_delay_honored():
    q = _DelayQueue("t")
    r = Request("default", "x")
    t0 = time.time()
    q.add(r, delay=0.25)
    assert q.get(timeout=0.05) is None  # still serving its backoff
    got = q.get(timeout=2.0)
    assert got == r
    assert time.time() - t0 >= 0.24


def test_delay_queue_watch_event_accelerates_backoff():
    q = _DelayQueue("t")
    r = Request("default", "x")
    q.add(r, delay=30.0)  # deep backoff
    q.add(r)              # watch event: ready now
    t0 = time.time()
    assert q.get(timeout=1.0) == r
    assert time.time() - t0 < 0.5


# ---- reconcile equivalence + the pinned benchmark ----------------------

_VOLATILE_KEYS = {"uid", "resource_version", "creation_timestamp",
                  "deletion_timestamp", "last_transition_time",
                  "heartbeat_time", "first_seen", "last_seen", "count",
                  "message"}


def _scrub(x):
    if isinstance(x, dict):
        return {k: _scrub(v) for k, v in x.items()
                if k not in _VOLATILE_KEYS}
    if isinstance(x, list):
        return [_scrub(v) for v in x]
    return x


def _dump_store(store: Store) -> dict:
    out = {}
    for kind, objs in store._objects.items():
        for (ns, name), obj in objs.items():
            entry = {
                "labels": dict(obj.meta.labels),
                "finalizers": list(obj.meta.finalizers),
                "owners": sorted((r.kind, r.name)
                                 for r in obj.meta.owner_references),
            }
            if kind == "Secret":
                entry["data_keys"] = sorted(obj.data)  # token is random
            else:
                if hasattr(obj, "spec"):
                    entry["spec"] = _scrub(to_dict(obj.spec))
                if hasattr(obj, "status"):
                    entry["status"] = _scrub(to_dict(obj.status))
            out[f"{kind}/{ns}/{name}"] = entry
    return _scrub(out)


def _drive_sequence(informer: bool) -> dict:
    """One deterministic event sequence through the real reconcilers
    (single-threaded driver, no kubelet/scheduler): deploy, readiness,
    pod loss + self-heal, template edit + pod-level rolling update.
    Returns the scrubbed final store state."""
    from grove_tpu.api import PodCliqueSet
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.api.core import ContainerSpec
    from grove_tpu.api.podcliqueset import (
        PodCliqueSetSpec,
        PodCliqueSetTemplate,
        PodCliqueTemplate,
    )
    from grove_tpu.controllers.podclique import PodCliqueReconciler
    from grove_tpu.controllers.podcliqueset import PodCliqueSetReconciler
    from grove_tpu.controllers.podgang import PodGangReconciler
    from grove_tpu.controllers.scalinggroup import ScalingGroupReconciler
    from grove_tpu.scheduler.registry import build_registry
    from tools.bench_reconcile import drive_until_settled

    prev = os.environ.get("GROVE_INFORMER")
    os.environ["GROVE_INFORMER"] = "1" if informer else "0"
    try:
        store = Store()
        base = Client(store)
        client = CachedClient(base, InformerSet(store=store))
        registry = build_registry(OperatorConfiguration(), base)
        recs = {
            "PodCliqueSet": PodCliqueSetReconciler(client),
            "PodCliqueScalingGroup": ScalingGroupReconciler(client),
            "PodClique": PodCliqueReconciler(client, registry),
            "PodGang": PodGangReconciler(client, registry),
        }
        sink: list[float] = []

        def settle():
            drive_until_settled(store, recs, sink)

        def mark_all_ready():
            for pod in base.list(Pod, namespace=None):
                live = base.get(Pod, pod.meta.name, pod.meta.namespace)
                live.status.phase = PodPhase.RUNNING
                live.status.conditions = set_condition(
                    live.status.conditions,
                    Condition(type=c.COND_READY, status="True",
                              reason="test"))
                base.update_status(live)

        base.create(PodCliqueSet(
            meta=new_meta("eq"),
            spec=PodCliqueSetSpec(
                replicas=2,
                template=PodCliqueSetTemplate(cliques=[PodCliqueTemplate(
                    name="w", replicas=2, min_available=1,
                    tpu_chips_per_pod=1,
                    container=ContainerSpec(argv=["x"]))]))))
        settle()
        mark_all_ready()
        settle()
        # Pod loss: self-heal recreates the index.
        victim = sorted(o.meta.name
                        for o in base.list(Pod, namespace=None))[0]
        base.delete(Pod, victim)
        settle()
        mark_all_ready()
        settle()
        # Template edit -> pod-level rolling update; drive it to the end
        # by granting readiness between rounds (no kubelet here).
        live = base.get(PodCliqueSet, "eq")
        live.spec.template.cliques[0].container.argv = ["y"]
        base.update(live)
        for _ in range(24):
            settle()
            mark_all_ready()
            target = base.get(PodCliqueSet, "eq").status.generation_hash
            pods = base.list(Pod, namespace=None)
            if pods and all(
                    p.meta.labels.get(c.LABEL_POD_TEMPLATE_HASH) == target
                    for p in pods) \
                    and base.get(PodCliqueSet,
                                 "eq").status.rolling_update is None:
                break
        settle()
        return _dump_store(store)
    finally:
        if prev is None:
            os.environ.pop("GROVE_INFORMER", None)
        else:
            os.environ["GROVE_INFORMER"] = prev


def test_reconcile_outcomes_identical_between_read_paths():
    """The property the informer must hold: the same event sequence
    through the cached and direct read paths converges to the same
    final store state (modulo uids/rvs/timestamps)."""
    with_informer = _drive_sequence(informer=True)
    direct = _drive_sequence(informer=False)
    assert with_informer == direct


def test_informer_reconcile_256_pinned():
    """The acceptance benchmark: on a 256-pod / 64-gang fleet the
    informer-backed path issues >=10x fewer Store.list scans over the
    whole run and sweeps the converged fleet >=3x faster end-to-end
    than GROVE_INFORMER=0 (steady-state reconcile is the recurring
    cost at fleet scale; bench_reconcile is the same harness).
    Best-of-N per mode to shrug off CI noise."""

    def measure(reps):
        steady = {True: [], False: []}
        scans = {}
        for _ in range(reps):
            for informer in (True, False):
                r = run_once(256, informer)
                assert r["pods"] == 256 and r["gangs"] == 64, r
                steady[informer].append(r["steady_wall_s"])
                scans[informer] = r["list_scans"]
        fast, slow = min(steady[True]), min(steady[False])
        assert fast > 0
        return slow / fast, scans

    speedup, scans = measure(2)
    if speedup < 3.0:
        # One retry with more reps: a loaded CI host can land a pause
        # in every run of a short first batch; a genuine regression
        # stays below the bar either way.
        speedup, scans = measure(4)
    assert scans[False] >= 10 * scans[True], scans
    assert speedup >= 3.0, f"steady sweep only {speedup:.1f}x faster"


def test_bench_reconcile_emits_nonzero_rows():
    """The bench tool's row is well-formed and nonzero — the first real
    numbers for the reconcile-p50 metric (make bench-reconcile appends
    these to bench-history/)."""
    from tools import bench_reconcile
    row = bench_reconcile.bench_fleet(16, reps=1)
    assert row["metric"] == "reconcile_p50_ms"
    assert row["value"] > 0
    assert row["p99_ms"] >= row["value"]
    assert row["steady_wall_ms"] > 0
    assert row["store_list_scans"] > 0
    assert row["pods"] == 16
