"""Store durability (etcd analog): WAL + snapshot round-trips, compaction,
and the restart e2e — a rebooted cluster resumes from disk and heals
orphaned workload pods."""

from __future__ import annotations

import json
import sys
import time

import pytest

from grove_tpu.api import Node, Pod, PodClique, PodCliqueSet, constants as c, \
    new_meta
from grove_tpu.api.core import ContainerSpec, PodPhase
from grove_tpu.api.podcliqueset import (
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
)
from grove_tpu.cluster import new_cluster
from grove_tpu.runtime.errors import NotFoundError
from grove_tpu.store.store import Store
from grove_tpu.topology.fleet import FleetSpec, SliceSpec, build_node

from test_e2e_simple import wait_for

from timing import scaled


def pcs(name="web"):
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
            cliques=[PodCliqueTemplate(
                name="w", replicas=2, tpu_chips_per_pod=4,
                container=ContainerSpec(argv=["sleep", "inf"]))])))


def test_store_roundtrip(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    created = s1.create(pcs())
    node = build_node("v5e", "2x2", "s0", 0)
    s1.create(node)
    live = s1.get(PodCliqueSet, "web")
    live.spec.replicas = 3
    updated = s1.update(live)
    n = s1.get(Node, node.meta.name)
    n.status.heartbeat_time = 42.0
    s1.update_status(n)
    s1.delete(Node, node.meta.name)

    s2 = Store(state_dir=d)
    back = s2.get(PodCliqueSet, "web")
    assert back.spec.replicas == 3
    assert back.meta.uid == created.meta.uid
    assert back.meta.generation == updated.meta.generation
    assert back.meta.resource_version == updated.meta.resource_version
    with pytest.raises(NotFoundError):
        s2.get(Node, node.meta.name)
    # rv counter resumes past the loaded maximum: new writes never reuse
    # versions, and optimistic concurrency against loaded objects works.
    again = s2.get(PodCliqueSet, "web")
    again.spec.replicas = 4
    newer = s2.update(again)
    assert newer.meta.resource_version > updated.meta.resource_version


def test_finalizer_marking_survives(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    obj = pcs("fin")
    obj.meta.finalizers = ["grove.io/test"]
    s1.create(obj)
    s1.delete(PodCliqueSet, "fin")
    s2 = Store(state_dir=d)
    back = s2.get(PodCliqueSet, "fin")
    assert back.meta.deletion_timestamp is not None
    # clearing the finalizer completes the delete post-restart
    back.meta.finalizers = []
    s2.update(back)
    with pytest.raises(NotFoundError):
        s2.get(PodCliqueSet, "fin")


def test_compaction_truncates_wal(tmp_path):
    d = tmp_path / "state"
    s1 = Store(state_dir=str(d))
    s1._persister.compact_every = 20
    for i in range(15):
        s1.create(pcs(f"p{i:02d}"))
    # 15 puts + the leading version-header record
    assert len((d / "wal.jsonl").read_text().splitlines()) == 16
    for i in range(15):
        live = s1.get(PodCliqueSet, f"p{i:02d}")
        live.spec.replicas = 2
        s1.update(live)  # crosses the threshold -> compaction
    # Compaction rotates on the write path but writes the snapshot in
    # a background thread (grove_tpu/ha's in-operation compactor):
    # wait it out before asserting on-disk state.
    s1._persister.join_compaction()
    assert (d / "snapshot.json").exists()
    wal_lines = (d / "wal.jsonl").read_text().splitlines()
    assert len(wal_lines) < 15
    s2 = Store(state_dir=str(d))
    assert len(s2.list(PodCliqueSet)) == 15
    assert all(o.spec.replicas == 2 for o in s2.list(PodCliqueSet))


def test_torn_wal_tail_ignored(tmp_path):
    d = tmp_path / "state"
    s1 = Store(state_dir=str(d))
    s1.create(pcs("ok"))
    with open(d / "wal.jsonl", "a") as f:
        f.write('{"op": "put", "kind": "PodCliqueSet", "da')  # torn
    s2 = Store(state_dir=str(d))
    assert [o.meta.name for o in s2.list(PodCliqueSet)] == ["ok"]


def test_cluster_restart_resumes_and_reconciles(tmp_path):
    """Reboot e2e: PCS survives, fleet re-creation is idempotent, and
    the controllers resume managing the loaded objects."""
    d = str(tmp_path / "state")
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                        count=1)])
    sel = {c.LABEL_PCS_NAME: "web"}

    cl1 = new_cluster(fleet=fleet, state_dir=d)
    with cl1:
        cl1.client.create(pcs())
        wait_for(lambda: len([p for p in cl1.client.list(Pod, selector=sel)
                              if p.status.phase == PodPhase.RUNNING]) == 2,
                 timeout=15.0, desc="pods running before reboot")

    cl2 = new_cluster(fleet=fleet, state_dir=d)  # same fleet flag: reboot
    with cl2:
        assert cl2.client.get(PodCliqueSet, "web").spec.replicas == 1
        assert len(cl2.client.list(PodClique, selector=sel)) == 1
        wait_for(lambda: len([p for p in cl2.client.list(Pod, selector=sel)
                              if p.status.phase == PodPhase.RUNNING]) == 2,
                 timeout=15.0, desc="pods running after reboot")
        # controllers are live against loaded state: scaling still works
        live = cl2.client.get(PodCliqueSet, "web")
        live.spec.replicas = 2
        cl2.client.update(live)
        wait_for(lambda: len(cl2.client.list(Pod, selector=sel)) == 4,
                 timeout=15.0, desc="scale-up after reboot")


def test_restart_heals_orphaned_processes(tmp_path):
    """Real-process reboot: pods persist but their processes die with the
    agent; the restarted kubelet fails orphans and self-heal respawns
    them (fresh uid, fresh process)."""
    from grove_tpu.agent.process import ProcessKubelet

    d = str(tmp_path / "state")
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="2x4",
                                        count=1)], fake=False)
    sel = {c.LABEL_PCS_NAME: "proc"}
    spec = PodCliqueSet(
        meta=new_meta("proc"),
        spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
            cliques=[PodCliqueTemplate(
                name="w", replicas=1, tpu_chips_per_pod=4,
                container=ContainerSpec(
                    argv=[sys.executable, "-c",
                          "import time; time.sleep(300)"]))])))

    cl1 = new_cluster(fleet=fleet, fake_kubelet=False, state_dir=d)
    cl1.manager.add_runnable(ProcessKubelet(cl1.client,
                                            workdir=str(tmp_path)))
    with cl1:
        cl1.client.create(spec)
        wait_for(lambda: [p for p in cl1.client.list(Pod, selector=sel)
                          if p.status.phase == PodPhase.RUNNING],
                 timeout=15.0, desc="process pod running")
        old_uid = cl1.client.list(Pod, selector=sel)[0].meta.uid
    # cl1 exit kills the kubelet's processes; pods persist as RUNNING.

    cl2 = new_cluster(fleet=fleet, fake_kubelet=False, state_dir=d)
    cl2.manager.add_runnable(ProcessKubelet(cl2.client,
                                            workdir=str(tmp_path)))
    with cl2:
        def healed():
            pods = [p for p in cl2.client.list(Pod, selector=sel)
                    if p.status.phase == PodPhase.RUNNING]
            return pods and all(p.meta.uid != old_uid for p in pods)
        wait_for(healed, timeout=20.0,
                 desc="orphan failed and replacement running")


# ---- schema versioning / migrations (CRD-upgrader analog) --------------

def test_v1_state_upgrades_and_compacts_on_load(tmp_path):
    """Pre-versioning state (no "version" key) loads through the v1
    migration and the dir is atomically rewritten at STATE_VERSION
    before any new append."""
    import json
    from grove_tpu.store.persist import STATE_VERSION, StatePersister

    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("mig-a"))
    s1.create(pcs("mig-b"))
    # strip the version stamp to simulate a v1 layout
    s1._persister.compact(
        [o for objs in s1._objects.values() for o in objs.values()],
        rv=s1.current_rv())
    snap = json.load(open(f"{d}/snapshot.json"))
    del snap["version"]
    json.dump(snap, open(f"{d}/snapshot.json", "w"))

    s2 = Store(state_dir=d)
    assert {o.meta.name for o in s2.list(PodCliqueSet)} == {"mig-a", "mig-b"}
    upgraded = json.load(open(f"{d}/snapshot.json"))
    assert upgraded["version"] == STATE_VERSION
    assert open(f"{d}/wal.jsonl").read() == ""  # truncated by compact

    p = StatePersister(d)  # fresh load at current version: no rewrite
    objs, rv, _epoch = p.load()
    assert len(objs) == 2 and rv == s1.current_rv()


def test_migration_chain_rewrites_objects(tmp_path, monkeypatch):
    """A registered migration transforms (or drops) objects on load."""
    import json
    from grove_tpu.store import persist

    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("keepme"))
    s1.create(pcs("dropme"))
    s1._persister.compact(
        [o for objs in s1._objects.values() for o in objs.values()],
        rv=s1.current_rv())
    snap = json.load(open(f"{d}/snapshot.json"))
    snap["version"] = 2  # pretend current is 3 with a 2->3 migration

    def migrate_2_to_3(kind, data):
        if data["meta"]["name"] == "dropme":
            return None
        data["meta"]["labels"]["migrated"] = "yes"
        return kind, data

    json.dump(snap, open(f"{d}/snapshot.json", "w"))
    monkeypatch.setattr(persist, "STATE_VERSION", 3)
    monkeypatch.setitem(persist.MIGRATIONS, 2, migrate_2_to_3)

    s2 = Store(state_dir=d)
    objs = s2.list(PodCliqueSet)
    assert [o.meta.name for o in objs] == ["keepme"]
    assert objs[0].meta.labels["migrated"] == "yes"


def test_future_state_version_refuses_to_load(tmp_path):
    import json
    import pytest
    from grove_tpu.store.persist import StateVersionError

    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("future"))
    s1._persister.compact(
        [o for objs in s1._objects.values() for o in objs.values()],
        rv=s1.current_rv())
    snap = json.load(open(f"{d}/snapshot.json"))
    snap["version"] = 99
    json.dump(snap, open(f"{d}/snapshot.json", "w"))
    with pytest.raises(StateVersionError, match="newer build"):
        Store(state_dir=d)


def test_wal_only_dir_carries_version_header(tmp_path):
    """A WAL with no snapshot still refuses to load in an older build:
    every fresh WAL leads with a version record (the review's rollback-
    corruption scenario)."""
    import json
    from grove_tpu.store import persist

    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("hdr"))
    first = open(f"{d}/wal.jsonl").readline()
    assert json.loads(first) == {"op": "version",
                                 "v": persist.STATE_VERSION}

    # an "older build" (smaller STATE_VERSION) must refuse this WAL
    import pytest
    from unittest import mock
    with mock.patch.object(persist, "STATE_VERSION",
                           persist.STATE_VERSION - 1):
        with pytest.raises(persist.StateVersionError, match="newer"):
            Store(state_dir=d)


def test_torn_wal_tail_truncated_so_appends_stay_parseable(tmp_path):
    """A torn tail is physically truncated on load; the next append must
    not merge into the partial record (which would silently drop every
    subsequent record at the NEXT restart)."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("torn-a"))
    with open(f"{d}/wal.jsonl", "a") as f:
        f.write('{"op": "put", "kind": "PodCl')  # torn mid-append

    s2 = Store(state_dir=d)                      # load truncates the tear
    s2.create(pcs("torn-b"))                     # append after the tear

    s3 = Store(state_dir=d)                      # and NOTHING is lost
    assert {o.meta.name for o in s3.list(PodCliqueSet)} == \
        {"torn-a", "torn-b"}


def test_delete_records_follow_key_migrations(tmp_path, monkeypatch):
    """A kind-renaming migration must rewrite delete-record KEYS too, or
    replayed deletes miss the migrated puts and resurrect objects."""
    import json
    from grove_tpu.store import persist

    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("ghost"))
    s1.delete(PodCliqueSet, "ghost")
    del s1  # WAL: header, put ghost, (finalizer update), delete ghost

    # pretend current is 3 and migration 2->3 renames the kind
    monkeypatch.setattr(persist, "STATE_VERSION", 3)
    monkeypatch.setitem(
        persist.MIGRATIONS, 2,
        lambda kind, data: ("PodCliqueSet", data))  # same shape
    monkeypatch.setitem(
        persist.KEY_MIGRATIONS, 2,
        lambda kind, ns, name: ("PodCliqueSet", ns, name))

    s2 = Store(state_dir=d)
    assert s2.list(PodCliqueSet) == [], \
        "deleted object resurrected across migration"


def test_wal_lost_trailing_newline_repaired(tmp_path):
    """A final record whose JSON is complete but whose newline was torn
    off must be re-terminated on load — otherwise the next append
    concatenates onto it and the merged line silently loses BOTH records
    at the following restart."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("nl-a"))
    with open(f"{d}/wal.jsonl", "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 1)             # chop ONLY the newline

    s2 = Store(state_dir=d)                  # load repairs the tail
    s2.create(pcs("nl-b"))                   # append lands on its own line

    s3 = Store(state_dir=d)
    assert {o.meta.name for o in s3.list(PodCliqueSet)} == {"nl-a", "nl-b"}


# ---- in-operation (background) compaction + crash safety ----------------
# The compactor rotates the live WAL under the store lock (cheap) and
# writes the snapshot in a background thread (expensive); load() must
# reconstruct EXACT state from any crash point in that pipeline
# (docs/design/ha.md).

def _state_digest(store):
    from grove_tpu.api.serde import to_dict
    return {(kind, ns, name): to_dict(o)
            for kind, objs in store._objects.items()
            for (ns, name), o in objs.items()}


def _churn(store, n=30):
    for i in range(n):
        store.create(pcs(f"bg-{i:03d}"))
    for i in range(0, n, 3):
        live = store.get(PodCliqueSet, f"bg-{i:03d}")
        live.spec.replicas = 2
        store.update(live)
    for i in range(0, n, 5):
        store.delete(PodCliqueSet, f"bg-{i:03d}")


def test_background_compaction_rotates_and_folds(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1._persister.compact_every = 20
    _churn(s1)
    s1._persister.join_compaction()
    assert (tmp_path / "state" / "snapshot.json").exists()
    assert not (tmp_path / "state" / "wal.compacting.jsonl").exists()
    want = _state_digest(s1)
    s2 = Store(state_dir=d)
    assert _state_digest(s2) == want
    assert s2.current_rv() == s1.current_rv()


def test_crash_between_rotation_and_snapshot(tmp_path):
    """Crash point 1: the WAL was rotated to the segment but the
    snapshot write never finished — load must replay old snapshot +
    segment + fresh WAL, in that order."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    _churn(s1, n=12)
    # Rotate by hand (exactly what maybe_compact does under the lock)
    # and DON'T run the background half — the crash.
    s1._persister._rotate_wal(s1.current_rv())
    s1.create(pcs("post-rotate"))            # fresh WAL gets appends
    want = _state_digest(s1)
    seg = tmp_path / "state" / "wal.compacting.jsonl"
    assert seg.exists()
    s2 = Store(state_dir=d)
    assert _state_digest(s2) == want
    assert not seg.exists(), "load folds the leftover segment"
    # and the fold is durable: a third load from snapshot alone agrees
    s3 = Store(state_dir=d)
    assert _state_digest(s3) == want


def test_crash_between_snapshot_and_segment_unlink(tmp_path):
    """Crash point 2: the snapshot landed but the folded segment was
    never unlinked — replaying it would regress objects to pre-snapshot
    versions, so load must SKIP it (footer rv <= snapshot rv)."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    _churn(s1, n=12)
    p = s1._persister
    view = [o for objs in s1._objects.values() for o in objs.values()]
    rv = s1.current_rv()
    p._rotate_wal(rv)
    p._write_snapshot(view, rv, 0)           # background half, then CRASH
    want = _state_digest(s1)                 # (before the unlink)
    assert (tmp_path / "state" / "wal.compacting.jsonl").exists()
    s2 = Store(state_dir=d)
    assert _state_digest(s2) == want
    assert not (tmp_path / "state" / "wal.compacting.jsonl").exists()


def test_kill9_mid_compaction_reconstructs_exact_state(tmp_path):
    """The genuine article: a child process churning writes with an
    aggressive compaction threshold is SIGKILLed mid-run; replaying
    snapshot(+segment)+WAL must reconstruct a state containing every
    create the child CONFIRMED durable (its manifest) — whatever
    instant the kill hit the rotate/write/unlink pipeline."""
    import os
    import signal
    import subprocess
    import sys as _sys
    import textwrap

    d = str(tmp_path / "state")
    manifest = str(tmp_path / "manifest")
    child = textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        from grove_tpu.api import PodCliqueSet, new_meta
        from grove_tpu.api.core import ContainerSpec
        from grove_tpu.api.podcliqueset import (PodCliqueSetSpec,
            PodCliqueSetTemplate, PodCliqueTemplate)
        from grove_tpu.store.store import Store

        s = Store(state_dir={d!r})
        s._persister.compact_every = 15      # compact constantly
        m = open({manifest!r}, "a")
        for i in range(10000):
            name = f"kill-{{i:05d}}"
            s.create(PodCliqueSet(
                meta=new_meta(name),
                spec=PodCliqueSetSpec(replicas=1,
                    template=PodCliqueSetTemplate(cliques=[
                        PodCliqueTemplate(name="w", replicas=1,
                            tpu_chips_per_pod=0,
                            container=ContainerSpec(
                                argv=["sleep", "inf"]))]))))
            # the WAL append flushed before create returned: durable
            m.write(name + "\\n")
            m.flush()
    """)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.Popen([_sys.executable, "-c", child], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    # Let it churn through several compaction cycles, then kill -9.
    deadline = time.time() + scaled(30)
    while time.time() < deadline:
        try:
            with open(manifest) as f:
                if sum(1 for _ in f) >= 60:
                    break
        except OSError:
            pass
        time.sleep(0.02)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)

    confirmed = [ln.strip() for ln in open(manifest) if ln.strip()]
    assert len(confirmed) >= 60, "child never reached the churn phase"
    s2 = Store(state_dir=d)
    loaded = {o.meta.name for o in s2.list(PodCliqueSet)}
    missing = [n for n in confirmed if n not in loaded]
    assert not missing, (
        f"{len(missing)} durably-confirmed creates lost after kill -9 "
        f"mid-compaction (first: {missing[:3]})")
    # and the dir is fully usable: writes + another load still work
    s2.create(pcs("post-crash"))
    s3 = Store(state_dir=d)
    assert "post-crash" in {o.meta.name for o in s3.list(PodCliqueSet)}
