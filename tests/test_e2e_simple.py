"""End-to-end: PodCliqueSet → gated pods → gang placement → Ready.

The driver-config-1 equivalent of the reference's samples/simple/
simple1.yaml on a kind cluster (SURVEY.md §7 stage 3), running against
the in-process control plane with a fake (KWOK-analog) TPU fleet.
"""

import time

import pytest

from grove_tpu.api import (
    Pod,
    PodClique,
    PodCliqueSet,
    PodGang,
    constants as c,
    new_meta,
)
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.meta import is_condition_true
from grove_tpu.api.podcliqueset import (
    HeadlessServiceConfig,
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
    TopologyConstraint,
)
from grove_tpu.cluster import new_cluster
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from timing import TIME_SCALE, settle


def wait_for(predicate, timeout=10.0, interval=0.05, desc="condition"):
    """Poll ``predicate`` until true or ``timeout * TIME_SCALE`` wall
    seconds pass. Deadlines here are flake guards, not latency
    assertions — scaling them (tests/timing.py) costs nothing on a
    fast box and stops a CPU-share-throttled one from failing tests
    whose condition was still honestly on its way."""
    deadline = time.time() + timeout * TIME_SCALE
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc} "
                         f"(deadline {timeout}s x{TIME_SCALE:g})")


def simple_pcs(name="simple1", replicas=1, pods=3, chips=4):
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplate(
                cliques=[PodCliqueTemplate(
                    name="workers",
                    replicas=pods,
                    min_available=pods,
                    container=ContainerSpec(argv=["sleep", "inf"]),
                    tpu_chips_per_pod=chips,
                )],
                headless_service=HeadlessServiceConfig(),
                topology=TopologyConstraint(pack_level="slice", required=True),
            ),
        ),
    )


@pytest.fixture
def cluster():
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                        count=2)])  # 2 slices x 4 hosts
    cl = new_cluster(fleet=fleet)
    with cl:
        yield cl


def test_simple_pcs_reaches_ready(cluster):
    client = cluster.client
    client.create(simple_pcs())

    def all_ready():
        pods = client.list(Pod, selector={c.LABEL_PCS_NAME: "simple1"})
        return len(pods) == 3 and all(
            is_condition_true(p.status.conditions, c.COND_READY) for p in pods)

    wait_for(all_ready, desc="3 ready pods")

    # Gang landed slice-atomically: all pods on hosts of one slice.
    pods = client.list(Pod, selector={c.LABEL_PCS_NAME: "simple1"})
    slices = {p.status.node_name.rsplit("-w", 1)[0] for p in pods}
    assert len(slices) == 1, f"gang split across slices: {slices}"

    # Gates were removed (not bypassed).
    assert all(not p.spec.scheduling_gates for p in pods)

    # Env contract on every pod.
    env = pods[0].spec.container.env
    assert env[c.ENV_PCS_NAME] == "simple1"
    assert env[c.ENV_TPU_WORKER_HOSTNAMES].count(",") == 2
    assert {p.spec.container.env[c.ENV_TPU_WORKER_ID] for p in pods} == \
        {"0", "1", "2"}

    # PodGang went Running; PCLQ and PCS statuses aggregated.
    wait_for(lambda: client.get(PodGang, "simple1-0").status.phase.value
             == "Running", desc="gang Running")
    wait_for(lambda: client.get(
        PodClique, "simple1-0-workers").status.ready_replicas == 3,
        desc="pclq status")
    wait_for(lambda: client.get(
        PodCliqueSet, "simple1").status.available_replicas == 1,
        desc="pcs Available")


def test_gang_does_not_fit_stays_pending(cluster):
    """A gang needing more chips than any slice holds must never be
    partially placed (slice atomicity)."""
    client = cluster.client
    client.create(simple_pcs(name="toobig", pods=5, chips=4))  # 20 chips > 16

    settle(1.0)
    pods = client.list(Pod, selector={c.LABEL_PCS_NAME: "toobig"})
    assert len(pods) == 5
    assert all(not p.status.node_name for p in pods), "partial placement!"
    gang = client.get(PodGang, "toobig-0")
    assert not is_condition_true(gang.status.conditions, c.COND_SCHEDULED)


def test_two_replicas_spread_over_slices(cluster):
    """PCS replicas (multislice DP) spread across slices over DCN."""
    client = cluster.client
    client.create(simple_pcs(name="spread", replicas=2, pods=2, chips=4))

    def both_placed():
        pods = client.list(Pod, selector={c.LABEL_PCS_NAME: "spread"})
        return len(pods) == 4 and all(p.status.node_name for p in pods)

    wait_for(both_placed, desc="all pods placed")
    pods = client.list(Pod, selector={c.LABEL_PCS_NAME: "spread"})
    by_replica = {}
    for p in pods:
        r = p.meta.labels[c.LABEL_PCS_REPLICA]
        by_replica.setdefault(r, set()).add(
            p.status.node_name.rsplit("-w", 1)[0])
    assert all(len(s) == 1 for s in by_replica.values())
    assert by_replica["0"] != by_replica["1"], "replicas packed onto one slice"


def test_non_default_namespace(cluster):
    """The whole pipeline (controllers, scheduler, agents, autoscaler) is
    namespace-agnostic: a PCS in 'prod' reaches Ready and stays isolated
    from 'default'."""
    client = cluster.client
    pcs = simple_pcs(name="nsapp")
    pcs.meta.namespace = "prod"
    client.create(pcs)

    def ready():
        pods = client.list(Pod, "prod", selector={c.LABEL_PCS_NAME: "nsapp"})
        return len(pods) == 3 and all(
            is_condition_true(p.status.conditions, c.COND_READY) for p in pods)

    wait_for(ready, desc="prod-namespace pods ready")
    wait_for(lambda: client.get(
        PodCliqueSet, "nsapp", "prod").status.available_replicas == 1,
        desc="prod PCS available")
    assert client.list(Pod, "default",
                       selector={c.LABEL_PCS_NAME: "nsapp"}) == []

    # Same-named PCS in another namespace: identical child names must not
    # collide anywhere (gang gating, scheduler maps, agents).
    twin = simple_pcs(name="nsapp", pods=2, chips=4)
    client.create(twin)
    wait_for(lambda: client.get(
        PodCliqueSet, "nsapp", "default").status.available_replicas == 1,
        desc="default twin available")
    assert client.get(PodCliqueSet, "nsapp",
                      "prod").status.available_replicas == 1


def test_pcs_delete_cascades(cluster):
    client = cluster.client
    client.create(simple_pcs(name="gone"))
    wait_for(lambda: len(client.list(Pod, selector={
        c.LABEL_PCS_NAME: "gone"})) == 3, desc="pods created")
    client.delete(PodCliqueSet, "gone")
    wait_for(lambda: not client.list(Pod, selector={
        c.LABEL_PCS_NAME: "gone"}), desc="pods cascaded away")
    wait_for(lambda: not client.list(PodGang, selector={
        c.LABEL_PCS_NAME: "gone"}), desc="gangs cascaded away")
