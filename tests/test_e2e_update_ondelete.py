"""OnDelete update-strategy e2e (reference
operator/e2e/tests/update/ondelete_test.go + proposal 291): a template
edit under OnDelete does ONLY bookkeeping — no pod is touched until the
user deletes it, and each user-deleted pod is recreated at the NEW
template while untouched pods keep running the old one."""

from __future__ import annotations

import time

import pytest

from grove_tpu.api import Pod, PodCliqueSet, constants as c
from grove_tpu.api.meta import is_condition_true
from grove_tpu.api.podcliqueset import UpdateStrategy, UpdateStrategyType
from grove_tpu.cluster import new_cluster
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import simple_pcs, wait_for

from timing import settle


@pytest.fixture
def cluster():
    cl = new_cluster(fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="4x4", count=2)]))
    with cl:
        yield cl


def _ready_pods(client, name):
    return [p for p in client.list(Pod, selector={c.LABEL_PCS_NAME: name})
            if is_condition_true(p.status.conditions, c.COND_READY)]


def _on_delete_pcs(name, replicas=2):
    pcs = simple_pcs(name=name, replicas=replicas, pods=2, chips=4)
    pcs.spec.update_strategy = UpdateStrategy(
        type=UpdateStrategyType.ON_DELETE)
    return pcs


def test_template_edit_touches_nothing(cluster):
    client = cluster.client
    client.create(_on_delete_pcs("od"))
    wait_for(lambda: len(_ready_pods(client, "od")) == 4, desc="ready")
    before = {p.meta.name: p.meta.uid
              for p in client.list(Pod, selector={c.LABEL_PCS_NAME: "od"})}
    old_hash = client.get(PodCliqueSet, "od").status.generation_hash

    live = client.get(PodCliqueSet, "od")
    live.spec.template.cliques[0].container.env["VERSION"] = "v2"
    client.update(live)

    # bookkeeping appears (hash moved, progress tracked, zero updated)...
    def bookkeeping():
        s = client.get(PodCliqueSet, "od")
        return (s.status.generation_hash != old_hash
                and s.status.rolling_update is not None
                and s.status.updated_replicas == 0)
    wait_for(bookkeeping, desc="OnDelete bookkeeping")

    # ...and stays that way: no pod is deleted or recreated
    settle(1.0)
    after = {p.meta.name: p.meta.uid
             for p in client.list(Pod, selector={c.LABEL_PCS_NAME: "od"})}
    assert after == before, "OnDelete must not touch pods on its own"
    assert all(p.spec.container.env.get("VERSION") != "v2"
               for p in client.list(Pod,
                                    selector={c.LABEL_PCS_NAME: "od"}))


def test_user_deletion_drives_the_rollout(cluster):
    client = cluster.client
    client.create(_on_delete_pcs("odroll"))
    wait_for(lambda: len(_ready_pods(client, "odroll")) == 4, desc="ready")
    live = client.get(PodCliqueSet, "odroll")
    live.spec.template.cliques[0].container.env["VERSION"] = "v2"
    client.update(live)
    new_hash_seen = lambda: client.get(  # noqa: E731
        PodCliqueSet, "odroll").status.rolling_update is not None
    wait_for(new_hash_seen, desc="update registered")
    target = client.get(PodCliqueSet,
                        "odroll").status.rolling_update.target_hash

    # user deletes replica 0's pods only
    r0 = [p for p in client.list(Pod, selector={c.LABEL_PCS_NAME: "odroll"})
          if p.meta.labels[c.LABEL_PCS_REPLICA] == "0"]
    for p in r0:
        client.delete(Pod, p.meta.name)

    def replica0_updated():
        pods = _ready_pods(client, "odroll")
        r0_pods = [p for p in pods
                   if p.meta.labels[c.LABEL_PCS_REPLICA] == "0"]
        r1_pods = [p for p in pods
                   if p.meta.labels[c.LABEL_PCS_REPLICA] == "1"]
        return (len(r0_pods) == 2 and len(r1_pods) == 2
                and all(p.meta.labels[c.LABEL_POD_TEMPLATE_HASH] == target
                        for p in r0_pods)
                and all(p.spec.container.env.get("VERSION") == "v2"
                        for p in r0_pods)
                and all(p.meta.labels[c.LABEL_POD_TEMPLATE_HASH] != target
                        for p in r1_pods))
    wait_for(replica0_updated, timeout=20.0,
             desc="replica 0 recreated at new template, replica 1 untouched")

    # partial progress is visible
    wait_for(lambda: client.get(
        PodCliqueSet, "odroll").status.updated_replicas == 1,
        desc="updated_replicas == 1")

    # finishing the rollout by hand completes the bookkeeping
    for p in [p for p in client.list(Pod,
                                     selector={c.LABEL_PCS_NAME: "odroll"})
              if p.meta.labels[c.LABEL_PCS_REPLICA] == "1"]:
        client.delete(Pod, p.meta.name)

    def done():
        s = client.get(PodCliqueSet, "odroll")
        pods = _ready_pods(client, "odroll")
        return (s.status.rolling_update is None
                and s.status.updated_replicas == 2
                and len(pods) == 4
                and all(p.meta.labels[c.LABEL_POD_TEMPLATE_HASH] == target
                        for p in pods))
    wait_for(done, timeout=20.0, desc="rollout complete after user deletes")
