"""ClusterTopology controller: startup pre-sync, backend sync, custom
hierarchies driving placement, drift detection."""

import time

import pytest

from grove_tpu.api import ClusterTopology, Pod, constants as c
from grove_tpu.api.clustertopology import TopologyLevel
from grove_tpu.cluster import new_cluster
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import simple_pcs, wait_for

from timing import settle


@pytest.fixture
def cluster():
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                        count=2)])
    cl = new_cluster(fleet=fleet)
    with cl:
        yield cl


def test_default_ct_created_and_synced(cluster):
    client = cluster.client

    def synced():
        ct = client.get(ClusterTopology, "default")
        return "gang" in ct.status.synced_backends
    wait_for(synced, desc="default CT synced to gang backend")
    ct = client.get(ClusterTopology, "default")
    assert [lvl.domain for lvl in ct.spec.levels] == [
        "pool", "superblock", "slice", "host"]
    assert not ct.status.drift_detected


def test_custom_level_labels_drive_placement(cluster):
    """Re-point the 'slice' level at a custom node label: gangs must pack
    by the new domain."""
    client = cluster.client
    # Tag both slices' nodes with one custom zone so a 5-host gang (which
    # cannot fit a single 4-host slice) becomes packable under the custom
    # hierarchy.
    from grove_tpu.api import Node
    for node in client.list(Node):
        node.meta.labels["example.com/zone"] = "z1"
        client.update(node)
    ct = client.get(ClusterTopology, "default")
    ct.spec.levels = [TopologyLevel("pool", c.NODE_LABEL_POOL),
                      TopologyLevel("slice", "example.com/zone"),
                      TopologyLevel("host", c.NODE_LABEL_HOST)]
    client.update(ct)

    def resynced():
        return client.get(ClusterTopology,
                          "default").status.synced_backends == ["gang"]
    wait_for(resynced, desc="CT resynced")
    settle(0.3)  # let the backend pick up the new hierarchy

    client.create(simple_pcs(name="wide", pods=5, chips=4))  # 20 chips
    wait_for(lambda: all(
        p.status.node_name for p in client.list(
            Pod, selector={c.LABEL_PCS_NAME: "wide"})) and len(client.list(
            Pod, selector={c.LABEL_PCS_NAME: "wide"})) == 5,
        timeout=10.0, desc="gang placed across the custom domain")


def test_externally_managed_drift_detection(cluster):
    client = cluster.client
    wait_for(lambda: client.get(ClusterTopology,
                                "default").status.synced_backends,
             desc="initial sync")
    ct = client.get(ClusterTopology, "default")
    ct.spec.externally_managed = True
    ct.spec.levels = [TopologyLevel("slice", "some.other/label")]
    client.update(ct)

    def drifted():
        live = client.get(ClusterTopology, "default")
        return live.status.drift_detected
    wait_for(drifted, desc="drift detected (backend view not overwritten)")


def test_ct_label_key_syntax_validated():
    """W5 depth: node_label keys must be k8s-qualified ([prefix/]name);
    domains must be DNS-label-like (constraints reference them)."""
    from grove_tpu.admission.validation import validate_clustertopology
    from grove_tpu.api.clustertopology import (ClusterTopologySpec,
                                               TopologyLevel)
    from grove_tpu.api import ClusterTopology, new_meta

    def ct(levels):
        return ClusterTopology(meta=new_meta("x"),
                               spec=ClusterTopologySpec(levels=levels))

    ok = ct([TopologyLevel("slice", "cloud.google.com/gke-tpu-topology"),
             TopologyLevel("host", "kubernetes.io/hostname")])
    assert not validate_clustertopology(ok)
    bad_key = ct([TopologyLevel("slice", "Bad Prefix!/x")])
    assert any("DNS subdomain" in e
               for e in validate_clustertopology(bad_key))
    bad_name = ct([TopologyLevel("slice", "example.com/bad name")])
    assert any("qualified label name" in e
               for e in validate_clustertopology(bad_name))
    bad_domain = ct([TopologyLevel("Not A Domain", "example.com/ok")])
    assert any("DNS-label-like" in e
               for e in validate_clustertopology(bad_domain))
