"""Autoscaler flap control: downscale stabilization (k8s HPA analog).
Round-1 gap: raw ceil(value/target) with no damping — a noisy
queue-depth signal would thrash PCSG replicas, and each flap is a gang
create/destroy on a TPU slice.
"""

from __future__ import annotations

import time

from grove_tpu.api import PodCliqueScalingGroup, new_meta
from grove_tpu.api.podcliqueset import AutoScalingConfig
from grove_tpu.api.scalinggroup import PodCliqueScalingGroupSpec
from grove_tpu.autoscale import Autoscaler, MetricsRegistry
from grove_tpu.store.client import Client
from grove_tpu.store.store import Store

from timing import SETTLE_SCALE, settle


def make_scaler(stabilization: float):
    client = Client(Store())
    metrics = MetricsRegistry()
    # The stabilization window is REAL wall time inside the scaler, and
    # the tests sleep settle()-scaled fractions of it to land on either
    # side of the boundary — scale the window by the same factor so the
    # before/after ratios hold at any GROVE_TEST_TIME_SCALE.
    scaler = Autoscaler(client, metrics,
                        scale_down_stabilization=stabilization
                        * SETTLE_SCALE)
    pcsg = PodCliqueScalingGroup(
        meta=new_meta("sg"),
        spec=PodCliqueScalingGroupSpec(
            clique_names=["w"], replicas=1, min_available=1,
            auto_scaling=AutoScalingConfig(
                min_replicas=1, max_replicas=5,
                metric="queue_depth", target_value=10.0)))
    client.create(pcsg)
    return client, metrics, scaler


def replicas(client):
    return client.get(PodCliqueScalingGroup, "sg").spec.replicas


def test_scale_up_is_immediate():
    client, metrics, scaler = make_scaler(stabilization=300.0)
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 45.0)
    scaler._pass()
    assert replicas(client) == 5


def test_scale_down_waits_out_the_window():
    client, metrics, scaler = make_scaler(stabilization=0.5)
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 45.0)
    scaler._pass()
    assert replicas(client) == 5

    # Signal drops — but the window still remembers the spike.
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 5.0)
    scaler._pass()
    assert replicas(client) == 5, "must not shrink inside the window"

    # After the window drains, the low signal wins.
    settle(0.6)
    scaler._pass()
    assert replicas(client) == 1


def test_noisy_signal_does_not_flap():
    """Alternating 45/5 readings: replicas ratchet to the max and stay
    there for the whole noisy phase — zero down-scaling flaps."""
    client, metrics, scaler = make_scaler(stabilization=5.0)
    seen = set()
    for i in range(10):
        metrics.set("PodCliqueScalingGroup", "sg", "queue_depth",
                    45.0 if i % 2 == 0 else 5.0)
        scaler._pass()
        seen.add(replicas(client))
    assert seen == {5}, f"replicas flapped: {seen}"


def test_spike_during_drain_resets_the_window():
    client, metrics, scaler = make_scaler(stabilization=0.5)
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 45.0)
    scaler._pass()
    settle(0.3)
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 45.0)
    scaler._pass()
    settle(0.3)
    # 0.6s since the FIRST spike, only 0.3 since the second → hold.
    metrics.set("PodCliqueScalingGroup", "sg", "queue_depth", 5.0)
    scaler._pass()
    assert replicas(client) == 5
