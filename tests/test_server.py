"""HTTP API server: apply/list/get/delete, health, metrics."""

import pytest

from grove_tpu.cluster import new_cluster
from grove_tpu.server import ApiServer
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import wait_for

MANIFEST = """
kind: PodCliqueSet
metadata: {name: websvc}
spec:
  replicas: 1
  template:
    cliques:
      - {name: w, replicas: 2, tpu_chips_per_pod: 4}
"""


OPERATOR_TOKEN = "test-operator-token"


@pytest.fixture
def server(monkeypatch):
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api.config import OperatorConfiguration
    cfg = OperatorConfiguration()
    cfg.server_auth.tokens[OPERATOR_TOKEN] = OPERATOR_ACTOR
    # The CLI verbs under test pick the credential up from the env, the
    # way a real operator shell would.
    monkeypatch.setenv("GROVE_API_TOKEN", OPERATOR_TOKEN)
    cl = new_cluster(config=cfg, fleet=FleetSpec(
        slices=[SliceSpec(generation="v5e", topology="4x4", count=1)]))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        yield f"http://127.0.0.1:{srv.port}", cl
        srv.stop()


def _req(url, method="GET", body=None, content_type="application/yaml",
         token=None):
    """Thin shim over the CLI's shared _http helper (one copy of the
    request/decode logic for client verbs and tests alike)."""
    from grove_tpu.cli import _http
    scheme_host, _, rest = url.removeprefix("http://").partition("/")
    return _http(f"http://{scheme_host}", f"/{rest}", method=method,
                 body=body.encode() if body else None,
                 content_type=content_type, token=token)


def test_apply_watch_delete_over_http(server):
    base, cl = server
    status, out = _req(f"{base}/apply", "POST", MANIFEST)
    assert status == 200 and out[0]["action"] == "created"

    def available():
        s, body = _req(f"{base}/api/PodCliqueSet/websvc")
        return s == 200 and body["status"]["available_replicas"] == 1
    wait_for(available, desc="available over http")

    status, pods = _req(f"{base}/api/Pod?l.grove.tpu/podcliqueset=websvc")
    assert status == 200 and len(pods) == 2
    assert pods[0]["status"]["node_name"]

    # idempotent re-apply = update
    status, out = _req(f"{base}/apply", "POST", MANIFEST)
    assert status == 200 and out[0]["action"] == "updated"

    status, _ = _req(f"{base}/api/PodCliqueSet/websvc", "DELETE")
    assert status == 200
    wait_for(lambda: _req(f"{base}/api/Pod")[1] == [], desc="pods gone")


def test_grovectl_client_verbs(server, tmp_path, capsys):
    """grovectl apply/get/delete drive a remote serve daemon."""
    from grove_tpu.cli import main
    base, _ = server
    manifest = tmp_path / "svc.yaml"
    manifest.write_text(MANIFEST)

    assert main(["apply", "-f", str(manifest), "--server", base]) == 0
    assert "PodCliqueSet/websvc created" in capsys.readouterr().out

    wait_for(lambda: (main(["get", "PodCliqueSet", "websvc",
                            "--server", base]) == 0
                      and '"available_replicas": 1'
                      in capsys.readouterr().out),
             desc="available via grovectl get")

    # describe: identity + status + conditions table (kubectl describe
    # analog), driven over the same wire verbs.
    assert main(["describe", "PodCliqueSet", "websvc",
                 "--server", base]) == 0
    out = capsys.readouterr().out
    assert "Name:       websvc" in out
    assert "Kind:       PodCliqueSet" in out
    assert "available_replicas: 1" in out
    assert "Conditions:" in out and "AGE" in out
    assert main(["describe", "PodCliqueSet", "nope", "--server", base]) == 1
    capsys.readouterr()

    # -o table: the kind's printcolumns (kubectl-get analog).
    assert main(["get", "PodCliqueSet", "-o", "table",
                 "--server", base]) == 0
    out = capsys.readouterr().out
    assert out.splitlines()[0].split() == [
        "NAME", "REPLICAS", "AVAILABLE", "UPDATED", "AGE"]
    assert "websvc" in out
    assert main(["get", "Pod", "-o", "table", "--server", base]) == 0
    out = capsys.readouterr().out
    assert "PHASE" in out and "NODE" in out and "websvc-0-w-0" in out
    # -l label selector (kubectl -l analog) narrows the list.
    assert main(["get", "Pod", "-o", "table",
                 "-l", "grove.tpu/podcliqueset=websvc",
                 "--server", base]) == 0
    assert "websvc-0-w-0" in capsys.readouterr().out
    assert main(["get", "Pod", "-o", "table",
                 "-l", "grove.tpu/podcliqueset=nope",
                 "--server", base]) == 0
    out = capsys.readouterr().out
    assert "websvc-0-w-0" not in out
    assert main(["get", "Pod", "-l", "malformed", "--server", base]) == 1
    # name+selector and conflicting values are rejected (kubectl parity)
    assert main(["get", "Pod", "websvc-0-w-0", "-l", "a=b",
                 "--server", base]) == 1
    assert main(["get", "Pod", "-l", "app=web,app=db",
                 "--server", base]) == 1
    capsys.readouterr()

    assert main(["delete", "PodCliqueSet", "websvc", "--server", base]) == 0
    assert "deleted" in capsys.readouterr().out
    assert main(["get", "PodCliqueSet", "websvc", "--server", base]) == 1


def test_pod_logs_endpoint(tmp_path):
    """GET /logs/<ns>/<pod> serves real-process pod output."""
    import sys
    from grove_tpu.agent.process import ProcessKubelet
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api.config import OperatorConfiguration
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="2x4",
                                        count=1)], fake=False)
    cfg = OperatorConfiguration()
    cfg.server_auth.tokens[OPERATOR_TOKEN] = OPERATOR_ACTOR
    cl = new_cluster(config=cfg, fleet=fleet, fake_kubelet=False)
    cl.manager.add_runnable(ProcessKubelet(cl.client,
                                           log_dir=str(tmp_path)))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            _req(f"{base}/apply", "POST", token=OPERATOR_TOKEN, body=f"""
kind: PodCliqueSet
metadata: {{name: logsvc}}
spec:
  template:
    cliques:
      - name: w
        replicas: 1
        tpu_chips_per_pod: 4
        container:
          argv: ["{sys.executable}", "-c",
                 "print('hello from the pod'); import time; time.sleep(60)"]
""")
            def has_log():
                s, body = _req(f"{base}/logs/default/logsvc-0-w-0?tail=5")
                return s == 200 and "hello from the pod" in body
            wait_for(has_log, timeout=20.0, desc="pod log over http")
            # fake/unknown pod -> 404 with a hint
            s, err = _req(f"{base}/logs/default/ghost-0")
            assert s == 404 and "no logs" in err["error"]
        finally:
            srv.stop()


def test_ragged_admit_prompts():
    """Per-lane prompt lengths through the engine admission path."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from grove_tpu.models import llama
    from grove_tpu.serving.engine import DecodeEngine
    cfg = dataclasses.replace(llama.CONFIGS["test-tiny"], dtype=jnp.float32,
                              max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    short = jax.random.randint(jax.random.PRNGKey(4), (1, 5), 0,
                               cfg.vocab_size)

    # Reference: batch-1 engine with the exact prompt.
    eng_a = DecodeEngine(cfg, params, batch=1)
    eng_a.admit_prompts(short)
    seq_a = [int(np.asarray(eng_a._tokens)[0])]

    # Ragged: same prompt padded into a 2-lane batch with lengths.
    padded = jnp.concatenate(
        [short, jnp.zeros((1, 7), jnp.int32)], axis=1)
    batch2 = jnp.concatenate([padded, padded], axis=0)
    eng_b = DecodeEngine(cfg, params, batch=2)
    eng_b.admit_prompts(batch2, lengths=jnp.array([5, 12]))
    assert int(np.asarray(eng_b._tokens)[0]) == seq_a[0]
    for _ in range(4):
        eng_a.step(); eng_b.step()
        seq_a.append(int(np.asarray(eng_a._tokens)[0]))
        assert int(np.asarray(eng_b._tokens)[0]) == seq_a[-1]


def test_health_metrics_and_errors(server):
    base, _ = server
    status, health = _req(f"{base}/healthz")
    assert status == 200 and health["started"]
    status, text = _req(f"{base}/metrics")
    assert status == 200 and "grove_reconcile_total" in text
    status, err = _req(f"{base}/api/NopeKind")
    assert status == 404 and "kinds" in err
    status, err = _req(f"{base}/api/Pod/ghost")
    assert status == 404
    status, err = _req(f"{base}/apply", "POST", "kind: Bad\nmetadata: {name: x}")
    assert status == 400
    # admission rejection surfaces as 400 with the reason
    bad = MANIFEST.replace("replicas: 2", "replicas: 2\n        min_available: 9")
    status, err = _req(f"{base}/apply", "POST",
                       bad.replace("websvc", "broken"))
    assert status == 400 and "min_available" in err["error"]

def test_debug_placement_endpoint(server):
    """GET /debug/placement/<ns>/<name> serves the raw diagnosis (and
    the HttpClient twin decodes it); an unknown gang is 404. Unlike
    /debug/traces this is plain status data — no profiling gate."""
    base, cl = server
    from grove_tpu.api import Pod, PodGang, constants as c
    from grove_tpu.api.core import ContainerSpec, PodSpec
    from grove_tpu.api.meta import new_meta
    from grove_tpu.api.podcliqueset import TopologyConstraint
    from grove_tpu.api.podgang import PodGangSpec, PodGroup
    from grove_tpu.store.httpclient import HttpClient
    pods = ["stuck-p-0", "stuck-p-1"]
    # 8 chips/pod: no 16-chip slice host set can seat 2x8 on 4-chip
    # hosts -> permanent diagnosis.
    cl.client.create(PodGang(
        meta=new_meta("stuck"),
        spec=PodGangSpec(
            groups=[PodGroup(name="g", pod_names=pods, min_replicas=2)],
            topology=TopologyConstraint(pack_level="slice",
                                        required=True))))
    for pn in pods:
        cl.client.create(Pod(
            meta=new_meta(pn, labels={c.LABEL_PODGANG_NAME: "stuck"}),
            spec=PodSpec(tpu_chips=8,
                         container=ContainerSpec(argv=["x"]))))
    wait_for(lambda: cl.client.get(
        PodGang, "stuck").status.last_diagnosis is not None,
        desc="diagnosis recorded")
    status, data = _req(f"{base}/debug/placement/default/stuck")
    assert status == 200
    assert data["name"] == "stuck" and data["scheduled"] is False
    assert data["diagnosis"]["reason"]
    assert data["diagnosis"]["domains"]
    # Wire twin returns the identical shape.
    http = HttpClient(base, token=OPERATOR_TOKEN)
    assert http.debug_placement("stuck") == data
    status, _ = _req(f"{base}/debug/placement/default/ghost")
    assert status == 404


def test_debug_endpoints_profiling_gate_and_auth():
    """/debug/profile, /debug/stacks, and /debug/traces share one gate:
    404 while profiling is disabled (the endpoints 'don't exist',
    pprof-style), served when enabled — and behind the reads-token auth
    when the config requires it."""
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api.config import OperatorConfiguration
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="2x2",
                                        count=1)])
    paths = ("/debug/profile?seconds=0.05", "/debug/stacks",
             "/debug/traces")

    # Default config: profiling disabled → every surface 404s.
    cl = new_cluster(fleet=fleet)
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            for path in paths:
                s, err = _req(f"{base}{path}", token="")
                assert s == 404, path
                assert "profiling" in err["error"], path
        finally:
            srv.stop()

    # Enabled + reads requiring a token: anonymous 401, authed 200.
    cfg = OperatorConfiguration()
    cfg.profiling.enabled = True
    cfg.server_auth.tokens[OPERATOR_TOKEN] = OPERATOR_ACTOR
    cfg.server_auth.require_token_for_reads = True
    cl = new_cluster(config=cfg, fleet=fleet)
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            for path in paths:
                s, _ = _req(f"{base}{path}", token="")
                assert s == 401, path
            s, prof = _req(f"{base}/debug/profile?seconds=0.05&format=top",
                           token=OPERATOR_TOKEN)
            assert s == 200 and "top" in prof
            s, stacks = _req(f"{base}/debug/stacks", token=OPERATOR_TOKEN)
            assert s == 200 and "thread" in stacks
            s, traces = _req(f"{base}/debug/traces", token=OPERATOR_TOKEN)
            assert s == 200
            assert set(traces) == {"spans", "milestones", "starts"}
            # ?trace_id= filters server-side.
            s, none = _req(f"{base}/debug/traces?trace_id=deadbeef",
                           token=OPERATOR_TOKEN)
            assert s == 200 and none["spans"] == []
        finally:
            srv.stop()


def test_grovectl_cordon_drain_uncordon(server, capsys):
    """kubectl node-ops parity over the wire: cordon marks the node
    unschedulable, --drain fails its pods (gang self-heal reschedules
    them onto remaining capacity), uncordon restores it."""
    import time
    from grove_tpu.api import Node, Pod, constants as c
    from grove_tpu.cli import main
    base, cl = server
    # One 4x4 slice = 4 hosts; a 2-pod gang leaves spare hosts to
    # reschedule onto after the drain.
    _req(f"{base}/apply", "POST", MANIFEST)
    sel = {c.LABEL_PCS_NAME: "websvc"}
    wait_for(lambda: len([p for p in cl.client.list(Pod, selector=sel)
                          if p.status.node_name]) == 2, desc="placed")
    victim = next(p.status.node_name
                  for p in cl.client.list(Pod, selector=sel)
                  if p.status.node_name)

    assert main(["cordon", victim, "--drain", "--server", base]) == 0
    out = capsys.readouterr().out
    assert f"Node/{victim} cordoned" in out and "drained" in out
    assert cl.client.get(Node, victim).spec.unschedulable

    def rescheduled():
        pods = [p for p in cl.client.list(Pod, selector=sel)
                if p.status.node_name and p.meta.deletion_timestamp is None
                and p.status.phase.value == "Running"]
        return (len(pods) == 2
                and all(p.status.node_name != victim for p in pods))
    wait_for(rescheduled, timeout=15.0,
             desc="drained pods rescheduled off the node")

    assert main(["uncordon", victim, "--server", base]) == 0
    assert "uncordoned" in capsys.readouterr().out
    assert not cl.client.get(Node, victim).spec.unschedulable


def test_field_selector_filters_server_side(server):
    """?f.<field>=v1,v2 (fieldSelector analog): the server filters on
    status fields BEFORE serializing — the agent-fleet poll pattern."""
    base, cl = server
    _req(f"{base}/apply", "POST", MANIFEST)
    # Wait for RUNNING, not just scheduled: scheduling (node bind) and
    # the kubelet's Pending→Running flip are separate async loops, and
    # the phase assertions below must not race the window between them.
    wait_for(lambda: (lambda pods: len(pods) == 2 and all(
        p["status"]["node_name"] and p["status"]["phase"] == "Running"
        for p in pods))(_req(f"{base}/api/Pod")[1]), desc="running")
    _, pods = _req(f"{base}/api/Pod")
    node0 = pods[0]["status"]["node_name"]
    s, only0 = _req(f"{base}/api/Pod?f.node_name={node0}")
    assert s == 200
    assert only0 and all(p["status"]["node_name"] == node0 for p in only0)
    # OR values + no matches
    s, both = _req(f"{base}/api/Pod?f.node_name="
                   f"{node0},{pods[1]['status']['node_name']}")
    assert len(both) == len(pods)
    s, none = _req(f"{base}/api/Pod?f.phase=Pending")
    assert s == 200 and none == []
    # enum field matches by wire value
    s, running = _req(f"{base}/api/Pod?f.phase=Running")
    assert len(running) == 2
    # Unknown/typo'd field names fail loudly (kube's "field selector
    # not supported" analog) — matches_fields compares a missing attr
    # as '', so silently returning [] would make an agent with a
    # misspelled selector quietly stop seeing all its pods.
    s, err = _req(f"{base}/api/Pod?f.nodename={node0}")
    assert s == 400
    assert "nodename" in err["error"] and "node_name" in err["error"]
    # Kinds without a status reject any field selector the same way.
    s, err = _req(f"{base}/api/Service?f.phase=Running")
    assert s == 400


def test_apply_dry_run_admits_without_committing(server, tmp_path, capsys):
    """?dry_run=1 (kubectl apply --dry-run=server analog): full
    admission runs — defaulting, validation, authorization against live
    state — and NOTHING commits."""
    from grove_tpu.cli import main
    base, cl = server
    from grove_tpu.api import PodCliqueSet

    s, out = _req(f"{base}/apply?dry_run=1", "POST", MANIFEST,
                  token=OPERATOR_TOKEN)
    assert s == 200 and out[0]["action"] == "would-create"
    assert cl.client.list(PodCliqueSet) == []          # nothing committed

    # Validation failures surface per object.
    bad = MANIFEST.replace("tpu_chips_per_pod: 4", "tpu_chips_per_pod: 3")
    s, out = _req(f"{base}/apply?dry_run=1", "POST", bad,
                  token=OPERATOR_TOKEN)
    assert s == 200 and out[0]["action"] == "invalid"
    assert "power of two" in out[0]["error"]

    # Against a live object it reports would-update.
    _req(f"{base}/apply", "POST", MANIFEST, token=OPERATOR_TOKEN)
    s, out = _req(f"{base}/apply?dry_run=1", "POST", MANIFEST,
                  token=OPERATOR_TOKEN)
    assert out[0]["action"] == "would-update"

    # grovectl --dry-run plumbs through.
    manifest = tmp_path / "m.yaml"
    manifest.write_text(MANIFEST)
    assert main(["apply", "-f", str(manifest), "--dry-run",
                 "--server", base]) == 0
    assert "would-update" in capsys.readouterr().out


def test_grovectl_scale_verb(server, capsys):
    """kubectl scale analog: replicas patched over the wire, reconciled
    to pods."""
    import time as _t
    from grove_tpu.api import Pod, constants as c
    from grove_tpu.cli import main
    base, cl = server
    _req(f"{base}/apply", "POST", MANIFEST)
    sel = {c.LABEL_PCS_NAME: "websvc"}
    wait_for(lambda: len(cl.client.list(Pod, selector=sel)) == 2,
             desc="base pods")
    assert main(["scale", "PodCliqueSet", "websvc", "--replicas", "2",
                 "--server", base]) == 0
    assert "scaled to 2" in capsys.readouterr().out
    wait_for(lambda: len(cl.client.list(Pod, selector=sel)) == 4,
             desc="scaled out")
    assert main(["scale", "PodCliqueSet", "ghost", "--replicas", "2",
                 "--server", base]) == 1
    capsys.readouterr()


def test_grovectl_top_nodes(server, capsys):
    """kubectl-top-style chip allocation: per-node used/free from live
    placements with the per-slice rollup."""
    from grove_tpu.api import Pod, constants as c
    from grove_tpu.cli import main
    base, cl = server
    _req(f"{base}/apply", "POST", MANIFEST)
    sel = {c.LABEL_PCS_NAME: "websvc"}
    wait_for(lambda: all(p.status.node_name for p in cl.client.list(
        Pod, selector=sel)) and len(cl.client.list(Pod, selector=sel)) == 2,
        desc="placed")
    assert main(["top", "nodes", "--server", base]) == 0
    out = capsys.readouterr().out
    assert out.splitlines()[0].split() == [
        "NODE", "SLICE", "CHIPS", "USED", "FREE", "STATE"]
    # 2 pods x 4 chips on a 16-chip slice: rollup shows 8 used, 8 free.
    assert "SLICE" in out
    rollup = [ln for ln in out.splitlines() if ln.startswith("pool-0-slice")]
    assert any(ln.split()[-3:] == ["16", "8", "8"] for ln in rollup), out

    # A node that goes NotReady (allocatable 0) while its pods are still
    # live must not print negative FREE or skew the slice rollup — the
    # maintenance view falls back to the spec'd hardware count.
    from grove_tpu.api import Node
    victim = next(p.status.node_name
                  for p in cl.client.list(Pod, selector=sel))
    node = cl.client.get(Node, victim)
    node.status.ready = False
    node.status.allocatable_chips = 0
    cl.client.update_status(node)
    assert main(["top", "nodes", "--server", base]) == 0
    out = capsys.readouterr().out
    victim_row = next(ln for ln in out.splitlines()
                      if ln.startswith(victim))
    assert "NotReady" in victim_row
    assert not any(f.startswith("-") for f in victim_row.split()), out


def test_metrics_push_batched_samples(server):
    """POST /metrics/push with a samples[] batch: one POST carries the
    whole engine SLO digest, each sample naming its aggregation mode;
    malformed batches reject atomically."""
    import json

    base, cl = server
    body = json.dumps({
        "kind": "PodCliqueScalingGroup", "name": "sg",
        "reporter": "engine-0",
        "samples": [
            {"metric": "queue_depth", "value": 4.0, "agg": "sum"},
            {"metric": "ttft_p99_ms", "value": 350.0, "agg": "max"},
            {"metric": "kv_utilization", "value": 0.5, "agg": "avg"},
        ]})
    status, out = _req(f"{base}/metrics/push", "POST", body,
                       content_type="application/json")
    assert status == 200 and out["accepted"] == 3
    assert cl.metrics.get("PodCliqueScalingGroup", "sg",
                          "ttft_p99_ms") == 350.0
    # A second reporter: latency maxes, load sums.
    body2 = body.replace("engine-0", "engine-1").replace("350.0", "250.0")
    status, _ = _req(f"{base}/metrics/push", "POST", body2,
                     content_type="application/json")
    assert status == 200
    assert cl.metrics.get("PodCliqueScalingGroup", "sg",
                          "ttft_p99_ms") == 350.0
    assert cl.metrics.get("PodCliqueScalingGroup", "sg",
                          "queue_depth") == 8.0
    # Bad agg mode: 400, and NOTHING from the batch lands (atomic).
    bad = json.dumps({
        "kind": "PodCliqueScalingGroup", "name": "sg",
        "reporter": "engine-2",
        "samples": [
            {"metric": "queue_depth", "value": 9.0},
            {"metric": "ttft_p99_ms", "value": 1.0, "agg": "median"},
        ]})
    status, err = _req(f"{base}/metrics/push", "POST", bad,
                       content_type="application/json")
    assert status == 400 and "median" in err["error"]
    # Non-dict samples (a bare string iterates characterwise) must be
    # a clean 400, not an AttributeError escaping the handler.
    for bad_samples in (["oops"], "abc"):
        status, err = _req(
            f"{base}/metrics/push", "POST",
            json.dumps({"kind": "PodCliqueScalingGroup", "name": "sg",
                        "samples": bad_samples}),
            content_type="application/json")
        assert status == 400, bad_samples
        assert "sample must be an object" in err["error"]
    assert cl.metrics.get("PodCliqueScalingGroup", "sg",
                          "queue_depth") == 8.0  # unchanged
    # The legacy single-sample shape still works.
    single = json.dumps({"kind": "PodCliqueScalingGroup", "name": "sg",
                         "metric": "queue_depth", "value": 2.0,
                         "reporter": "engine-0"})
    status, out = _req(f"{base}/metrics/push", "POST", single,
                       content_type="application/json")
    assert status == 200 and out["accepted"] == 1


def test_debug_serving_endpoint(server):
    """GET /debug/serving/<ns>/<name>: the ServingObserver's aggregated
    SLO state for one scope, with the HttpClient twin decoding the
    identical payload; unknown scopes 404."""
    import json

    from grove_tpu.api import PodCliqueScalingGroup, new_meta
    from grove_tpu.api.podcliqueset import AutoScalingConfig
    from grove_tpu.api.scalinggroup import PodCliqueScalingGroupSpec
    from grove_tpu.runtime.servingwatch import serving_observer_for
    from grove_tpu.store.httpclient import HttpClient

    base, cl = server
    cl.client.create(PodCliqueScalingGroup(
        meta=new_meta("websg"),
        spec=PodCliqueScalingGroupSpec(
            clique_names=["w"], replicas=1, min_available=1,
            auto_scaling=AutoScalingConfig(
                min_replicas=1, max_replicas=3,
                metric="ttft_p99_ms", target_value=300.0))))
    body = json.dumps({
        "kind": "PodCliqueScalingGroup", "name": "websg",
        "reporter": "engine-0",
        "samples": [{"metric": "ttft_p99_ms", "value": 450.0,
                     "agg": "max"},
                    {"metric": "kv_utilization", "value": 0.25,
                     "agg": "avg"}]})
    status, _ = _req(f"{base}/metrics/push", "POST", body,
                     content_type="application/json")
    assert status == 200
    obs = serving_observer_for(cl.manager.store)
    assert obs is not None
    obs.sweep()
    status, data = _req(f"{base}/debug/serving/default/websg")
    assert status == 200
    scope = data["scopes"][0]
    assert scope["metrics"]["ttft_p99_ms"]["value"] == 450.0
    assert scope["slo"]["breached"] is True
    assert scope["kv_headroom"] == 0.75
    # Wire twin returns the identical shape (modulo the render clock).
    http = HttpClient(base, token=OPERATOR_TOKEN)
    twin = http.debug_serving("websg")
    assert twin["scopes"] == data["scopes"]
    status, _ = _req(f"{base}/debug/serving/default/ghost")
    assert status == 404


def test_grovectl_serving_status(server, capsys):
    """`grovectl serving-status` renders the scope and exits 1 on an
    SLO breach, 0 once the signal is healthy (scripts alert on it)."""
    import json

    from grove_tpu.api import PodCliqueScalingGroup, new_meta
    from grove_tpu.api.podcliqueset import AutoScalingConfig
    from grove_tpu.api.scalinggroup import PodCliqueScalingGroupSpec
    from grove_tpu.cli import main
    from grove_tpu.runtime.servingwatch import serving_observer_for

    base, cl = server
    cl.client.create(PodCliqueScalingGroup(
        meta=new_meta("clisg"),
        spec=PodCliqueScalingGroupSpec(
            clique_names=["w"], replicas=1, min_available=1,
            auto_scaling=AutoScalingConfig(
                min_replicas=1, max_replicas=3,
                metric="ttft_p99_ms", target_value=300.0))))

    def push(ttft):
        body = json.dumps({
            "kind": "PodCliqueScalingGroup", "name": "clisg",
            "reporter": "engine-0",
            "samples": [{"metric": "ttft_p99_ms", "value": ttft,
                         "agg": "max"}]})
        status, _ = _req(f"{base}/metrics/push", "POST", body,
                         content_type="application/json")
        assert status == 200

    obs = serving_observer_for(cl.manager.store)
    push(450.0)
    obs.sweep()
    assert main(["serving-status", "clisg", "--server", base]) == 1
    out = capsys.readouterr().out
    assert "BREACHED" in out and "ttft_p99_ms" in out
    push(100.0)
    obs.sweep()
    assert main(["serving-status", "clisg", "--server", base]) == 0
    assert "[ok]" in capsys.readouterr().out
    # Unknown scope: error on stderr, exit 1.
    assert main(["serving-status", "nope", "--server", base]) == 1
    assert "error" in capsys.readouterr().err
