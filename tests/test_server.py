"""HTTP API server: apply/list/get/delete, health, metrics."""

import json
import urllib.request

import pytest

from grove_tpu.cluster import new_cluster
from grove_tpu.server import ApiServer
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import wait_for

MANIFEST = """
kind: PodCliqueSet
metadata: {name: websvc}
spec:
  replicas: 1
  template:
    cliques:
      - {name: w, replicas: 2, tpu_chips_per_pod: 4}
"""


@pytest.fixture
def server():
    cl = new_cluster(fleet=FleetSpec(
        slices=[SliceSpec(generation="v5e", topology="4x4", count=1)]))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        yield f"http://127.0.0.1:{srv.port}", cl
        srv.stop()


def _req(url, method="GET", body=None, content_type="application/yaml"):
    req = urllib.request.Request(url, method=method,
                                 data=body.encode() if body else None,
                                 headers={"Content-Type": content_type})
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read() or b"null") \
                if "json" in resp.headers.get("Content-Type", "") \
                else resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_apply_watch_delete_over_http(server):
    base, cl = server
    status, out = _req(f"{base}/apply", "POST", MANIFEST)
    assert status == 200 and out[0]["action"] == "created"

    def available():
        s, body = _req(f"{base}/api/PodCliqueSet/websvc")
        return s == 200 and body["status"]["available_replicas"] == 1
    wait_for(available, desc="available over http")

    status, pods = _req(f"{base}/api/Pod?l.grove.tpu/podcliqueset=websvc")
    assert status == 200 and len(pods) == 2
    assert pods[0]["status"]["node_name"]

    # idempotent re-apply = update
    status, out = _req(f"{base}/apply", "POST", MANIFEST)
    assert status == 200 and out[0]["action"] == "updated"

    status, _ = _req(f"{base}/api/PodCliqueSet/websvc", "DELETE")
    assert status == 200
    wait_for(lambda: _req(f"{base}/api/Pod")[1] == [], desc="pods gone")


def test_health_metrics_and_errors(server):
    base, _ = server
    status, health = _req(f"{base}/healthz")
    assert status == 200 and health["started"]
    status, text = _req(f"{base}/metrics")
    assert status == 200 and "grove_reconcile_total" in text
    status, err = _req(f"{base}/api/NopeKind")
    assert status == 404 and "kinds" in err
    status, err = _req(f"{base}/api/Pod/ghost")
    assert status == 404
    status, err = _req(f"{base}/apply", "POST", "kind: Bad\nmetadata: {name: x}")
    assert status == 400
    # admission rejection surfaces as 400 with the reason
    bad = MANIFEST.replace("replicas: 2", "replicas: 2\n        min_available: 9")
    status, err = _req(f"{base}/apply", "POST",
                       bad.replace("websvc", "broken"))
    assert status == 400 and "min_available" in err["error"]