"""Scale + soak: the reference's scale-test ladder shrunk to CI size
(Test_ScaleTest_1000 → 300 pods here; the full 1000 runs via
`python -m grove_tpu.scale --pods 1000`)."""

import time

import numpy as np

from grove_tpu.api import Pod, PodGang, constants as c
from grove_tpu.scale.runner import ScaleConfig, run_scale_test


def test_scale_300_pods_within_budget():
    res = run_scale_test(ScaleConfig(pods=300, cliques=3,
                                     deploy_timeout=120.0,
                                     steady_touches=30))
    assert res["deploy_pods_created_s"] < 30
    assert res["deploy_pods_ready_s"] < 90
    assert res["deploy_available_s"] < 90
    # Steady state is measured under a STIMULUS (annotation touches on
    # pods, reference scale_test.go:216-240): the touches are spread
    # round-robin over the cliques, so every clique must see its own
    # reconcile ripple — coalesced by the workqueue dirty-set to ~one
    # reconcile per owning clique — and each reconcile must stay cheap.
    assert res["steady_touches"] == 30
    assert res["steady_touched_cliques"] == 3
    assert all(v >= 1 for v in res["steady_per_clique_reconciles"].values())
    assert res["steady_reconciles"] >= 3
    # The p95 bound itself is asserted INSIDE run_scale_test (env-
    # tunable, remote/pod-count scaled); here just require a sane
    # non-zero measurement so a broken timer can't pass silently.
    assert res["steady_p95_ms"] > 0
    # Delete request returns fast; cascade completes.
    assert res["delete_request_s"] < 1.0
    assert res["delete_cascade_s"] < 30


def test_scale_remote_agents_smoke():
    """CI-size wire-mode run: pod readiness driven by real agent
    PROCESSES over the HTTP API (watch + batched status writes +
    heartbeats) instead of the in-process fake kubelet. Keeps the
    --remote-agents path — watch feed, PATCH status, /batch status —
    from regressing silently between the big out-of-band runs."""
    res = run_scale_test(ScaleConfig(pods=48, cliques=2,
                                     deploy_timeout=60.0,
                                     steady_touches=10,
                                     remote_agents=2))
    assert res["remote_agents"] == 2
    assert res["deploy_pods_ready_s"] < 60
    assert res["steady_touched_cliques"] == 2
    assert all(v >= 1 for v in res["steady_per_clique_reconciles"].values())


def test_soak_scale_cycles():
    """Repeated scale out/in (reference soak_test.go): the system must
    converge every cycle without leaking pods or gangs."""
    from grove_tpu.cluster import new_cluster
    from grove_tpu.topology.fleet import FleetSpec, SliceSpec
    from test_e2e_simple import wait_for
    from test_availability import _ready_pods
    from grove_tpu.api import PodCliqueSet, new_meta
    from grove_tpu.api.core import ContainerSpec
    from grove_tpu.api.podcliqueset import (
        AutoScalingConfig, PodCliqueSetSpec, PodCliqueSetTemplate,
        PodCliqueTemplate, ScalingGroupConfig)

    from grove_tpu.api.config import OperatorConfiguration
    fleet = FleetSpec(slices=[SliceSpec(topology="4x4", count=4)])
    cfg = OperatorConfiguration()
    # Fast scale-in cycles are the point of the soak; flap control is
    # covered by test_autoscale_damping.
    cfg.autoscaler.scale_down_stabilization_seconds = 0.5
    cfg.autoscaler.sync_period_seconds = 0.3
    with new_cluster(config=cfg, fleet=fleet) as cl:
        client = cl.client
        client.create(PodCliqueSet(
            meta=new_meta("soak"),
            spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
                cliques=[PodCliqueTemplate(
                    name="w", replicas=2, tpu_chips_per_pod=4,
                    container=ContainerSpec(argv=["sleep", "inf"]))],
                scaling_groups=[ScalingGroupConfig(
                    name="m", clique_names=["w"], replicas=1, min_available=1,
                    auto_scaling=AutoScalingConfig(
                        min_replicas=1, max_replicas=4,
                        metric="queue_depth", target_value=10.0))],
            ))))
        wait_for(lambda: len(_ready_pods(client, "soak")) == 2, desc="base")
        for cycle in range(3):
            cl.metrics.set("PodCliqueScalingGroup", "soak-0-m",
                           "queue_depth", 40.0)   # -> 4 replicas
            wait_for(lambda: len(_ready_pods(client, "soak")) == 8,
                     timeout=20.0, desc=f"cycle {cycle} out")
            cl.metrics.set("PodCliqueScalingGroup", "soak-0-m",
                           "queue_depth", 0.1)    # -> 1 replica
            wait_for(lambda: len(_ready_pods(client, "soak")) == 2,
                     timeout=20.0, desc=f"cycle {cycle} in")
        # No leaked gangs after the churn.
        wait_for(lambda: {g.meta.name for g in client.list(
            PodGang, selector={c.LABEL_PCS_NAME: "soak"})} == {"soak-0"},
            desc="gangs pruned")
        # No leaked pods.
        assert len(client.list(Pod, selector={c.LABEL_PCS_NAME: "soak"})) == 2


def test_scale_dashboard_renders(tmp_path):
    """tools/scale_dashboard.py: history JSONL → markdown with per-run
    deltas and the 20% regression verdict."""
    import json
    import sys
    sys.path.insert(0, "tools")
    try:
        import scale_dashboard
    finally:
        sys.path.pop(0)
    hist = tmp_path / "h.jsonl"
    rows = [
        {"label": "r1", "ts": 1.0, "pods": 100, "deploy_pods_ready_s": 10.0,
         "deploy_pods_created_s": 1.0, "deploy_pods_scheduled_s": 5.0,
         "steady_reconciles_per_s": 0.0, "delete_cascade_s": 0.1},
        {"label": "r2", "ts": 2.0, "pods": 100, "deploy_pods_ready_s": 13.0,
         "deploy_pods_created_s": 1.0, "deploy_pods_scheduled_s": 5.0,
         "steady_reconciles_per_s": 0.0, "delete_cascade_s": 0.1},
        "not json",
    ]
    hist.write_text("\n".join(
        r if isinstance(r, str) else json.dumps(r) for r in rows) + "\n")
    runs = scale_dashboard.load_runs([str(hist)])
    assert len(runs) == 2
    report = scale_dashboard.render(runs)
    assert "## 100 pods" in report and "REGRESSION" in report  # 13 > 10*1.2
    assert "| r1 |" in report and "best" in report and "+30%" in report
    assert scale_dashboard.sparkline([1.0, 1.0]) == "▁▁"
    out = tmp_path / "d.md"
    assert scale_dashboard.main([str(hist), "-o", str(out)]) == 0
    assert out.read_text() == report


def test_bench_dashboard_renders(tmp_path):
    """tools/bench_dashboard.py: success table + failure timeline."""
    import json
    import sys
    sys.path.insert(0, "tools")
    try:
        import bench_dashboard
    finally:
        sys.path.pop(0)
    hist = tmp_path / "b.jsonl"
    hist.write_text("\n".join(json.dumps(r) for r in [
        {"ts": "2026-07-29T12:00:00", "git": "abc", "value": 2107.9,
         "metric": "llama1b_decode_tokens_per_sec_per_chip", "batch": 8,
         "quant": "int8", "vs_baseline": 0.95, "vs_engine_bare": 1.002,
         "hbm_util": 0.372, "prefill_tok_s": 30000.0},
        {"ts": "2026-07-29T22:00:00", "git": "def", "value": 0.0,
         "error": "attempt hung >230s in phase 'pre-init'"},
    ]) + "\n")
    report = bench_dashboard.render(bench_dashboard.load_rows([str(hist)]))
    assert "| 2107.9 | 0.950 | 1.002 | 37.2% |" in report
    assert "Failure timeline" in report and "pre-init" in report
    out = tmp_path / "d.md"
    assert bench_dashboard.main([str(hist), "-o", str(out)]) == 0
    assert out.read_text() == report
