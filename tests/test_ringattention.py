"""Ring attention (sequence parallelism) vs dense causal attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grove_tpu.ops.attention import causal_attention
from grove_tpu.ops.ringattention import ring_attention
from grove_tpu.parallel import build_mesh
from grove_tpu.parallel.mesh import MeshPlan


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=1, sp=4, tp=2),
    MeshPlan(dp=2, sp=2, tp=2),
    MeshPlan(dp=1, sp=8, tp=1),
])
def test_ring_matches_dense(cpu_devices, plan):
    mesh = build_mesh(plan, cpu_devices[:8])
    b, s, h, n_kv, d = 2, 32, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, n_kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, n_kv, d), jnp.float32)

    dense = causal_attention(q, k, v)
    ring = jax.jit(lambda q, k, v: ring_attention(mesh, q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_model_forward_with_ring_matches_dense(cpu_devices):
    """Full Llama forward with ring attention == dense forward."""
    import dataclasses
    from grove_tpu.models import llama
    from grove_tpu.parallel import shard_params
    from grove_tpu.parallel.sharding import logical_sharding

    cfg = dataclasses.replace(llama.CONFIGS["test-tiny"], dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshPlan(dp=1, sp=2, tp=4), cpu_devices[:8])
    sharded = shard_params(mesh, params)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size),
        logical_sharding(mesh, "batch", "seq"))
    dense = llama.forward(cfg, params, tokens)
    ring = jax.jit(lambda p, t: llama.forward(cfg, p, t, mesh=mesh,
                                              ring=True))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_differentiable(cpu_devices):
    """Gradients flow through the ring (training with SP)."""
    mesh = build_mesh(MeshPlan(dp=1, sp=4, tp=2), cpu_devices[:8])
    b, s, h, n_kv, d = 1, 16, 2, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, n_kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, n_kv, d), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(mesh, q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)
