"""Deploy bundle renderer (Helm-chart analog, reference T1
operator/charts/templates/) + the token-file auth path it feeds."""

from __future__ import annotations

import yaml
import pytest

from grove_tpu.deploy import (
    AUTO_TOKEN,
    DeployValues,
    load_values,
    render_bundle,
    validate_values,
    write_bundle,
)
from grove_tpu.runtime.errors import ValidationError


def test_gke_bundle_complete_and_parseable():
    files = render_bundle(DeployValues(), "gke")
    assert set(files) == {"namespace.yaml", "serviceaccount.yaml",
                          "priorityclass.yaml", "configmap-operator.yaml",
                          "secret-tokens.yaml", "deployment.yaml",
                          "service.yaml"}
    parsed = {name: yaml.safe_load(content)
              for name, content in files.items()}
    dep = parsed["deployment.yaml"]
    # wiring: deployment mounts the rendered ConfigMap and Secret
    vols = {v["name"]: v for v in
            dep["spec"]["template"]["spec"]["volumes"]}
    assert vols["config"]["configMap"]["name"] == \
        parsed["configmap-operator.yaml"]["metadata"]["name"]
    assert vols["tokens"]["secret"]["secretName"] == \
        parsed["secret-tokens.yaml"]["metadata"]["name"]
    ctr = dep["spec"]["template"]["spec"]["containers"][0]
    assert ctr["readinessProbe"]["httpGet"]["path"] == "/healthz"
    assert dep["spec"]["template"]["spec"]["priorityClassName"] == \
        parsed["priorityclass.yaml"]["metadata"]["name"]
    # the service selects the deployment's pods
    assert parsed["service.yaml"]["spec"]["selector"] == \
        dep["spec"]["selector"]["matchLabels"]


def test_embedded_operator_config_is_valid_and_tokenless():
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.api.serde import from_dict, unknown_keys

    files = render_bundle(
        DeployValues(config={"autoscaler": {"enabled": False}}), "gke")
    cm = yaml.safe_load(files["configmap-operator.yaml"])
    data = yaml.safe_load(cm["data"]["config.yaml"])
    assert unknown_keys(OperatorConfiguration, data) == []
    cfg = from_dict(OperatorConfiguration, data)
    assert cfg.autoscaler.enabled is False            # override survived
    assert cfg.server_auth.tokens == {}               # secrets not in CM


def test_auto_tokens_resolved_and_secret_shaped():
    files = render_bundle(DeployValues(), "gke")
    secret = yaml.safe_load(files["secret-tokens.yaml"])
    lines = [l for l in secret["stringData"]["tokens"].splitlines() if l]
    assert len(lines) == 1
    token, actor = lines[0].split(",")
    assert actor == "system:grove-operator"
    assert token != AUTO_TOKEN and len(token) > 20
    # each render generates fresh tokens
    files2 = render_bundle(DeployValues(), "gke")
    assert files2["secret-tokens.yaml"] != files["secret-tokens.yaml"]


def test_systemd_bundle():
    v = DeployValues(name="grove-ctl", fleet="v5e:4x4:2")
    files = render_bundle(v, "systemd")
    assert set(files) == {"grove-ctl.service", "config.yaml", "tokens",
                          "install.sh"}
    unit = files["grove-ctl.service"]
    assert "-m grove_tpu.cli serve" in unit
    assert "--fleet v5e:4x4:2" in unit
    assert f"GROVE_TOKEN_FILE={v.install_dir}/tokens" in unit
    assert "systemctl enable --now grove-ctl.service" in files["install.sh"]


def test_values_validation():
    with pytest.raises(ValidationError, match="DNS label"):
        validate_values(DeployValues(name="Not_A_Label"))
    with pytest.raises(ValidationError, match="replicas"):
        validate_values(DeployValues(replicas=0))
    with pytest.raises(ValidationError, match="unknown keys"):
        validate_values(DeployValues(config={"autoscalr": {}}))
    with pytest.raises(ValidationError, match="unknown deploy target"):
        render_bundle(DeployValues(), "helm")


def test_load_values_strict(tmp_path):
    p = tmp_path / "values.yaml"
    p.write_text("name: custom\nreplicsa: 2\n")
    with pytest.raises(ValidationError, match="unknown keys"):
        load_values(str(p))
    p.write_text("name: custom\nreplicas: 2\n")
    v = load_values(str(p))
    assert v.name == "custom" and v.replicas == 2


def test_write_bundle_secret_modes(tmp_path):
    import os
    files = render_bundle(DeployValues(), "systemd")
    written = write_bundle(files, str(tmp_path / "out"))
    assert len(written) == 4
    mode = os.stat(tmp_path / "out" / "tokens").st_mode & 0o777
    assert mode == 0o600


def test_cli_render_deploy(tmp_path, capsys):
    from grove_tpu.cli import main
    rc = main(["render-deploy", "--target", "gke",
               "--out", str(tmp_path / "gke")])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    assert len(out) == 7 and all((tmp_path / "gke").as_posix() in l
                                 for l in out)


def test_token_file_feeds_server_auth(tmp_path):
    """The rendered tokens file authenticates wire mutations end-to-end:
    GROVE_TOKEN_FILE → ServerAuthConfig → admission on the HTTP path."""
    from grove_tpu.api.config import OperatorConfiguration, load_token_file
    from grove_tpu.cluster import new_cluster
    from grove_tpu.server import ApiServer
    from grove_tpu.topology.fleet import FleetSpec, SliceSpec
    from grove_tpu.cli import _http

    tf = tmp_path / "tokens"
    tf.write_text("# comment\n\nsekret-abc,system:grove-operator\n"
                  "user-tok,user:alice\n")
    tokens = load_token_file(str(tf))
    assert tokens == {"sekret-abc": "system:grove-operator",
                      "user-tok": "user:alice"}

    cfg = OperatorConfiguration()
    cfg.server_auth.tokens.update(tokens)
    cl = new_cluster(config=cfg, fleet=FleetSpec(
        slices=[SliceSpec(generation="v5e", topology="4x4", count=1)]))
    manifest = ("kind: PodCliqueSet\nmetadata: {name: tf-pcs}\n"
                "spec:\n  replicas: 1\n  template:\n    cliques:\n"
                "      - {name: w, replicas: 1, tpu_chips_per_pod: 4}\n")
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            status, _ = _http(base, "/apply", method="POST",
                              body=manifest.encode(), token="wrong")
            assert status == 401
            status, out = _http(base, "/apply", method="POST",
                                body=manifest.encode(), token="sekret-abc")
            assert status == 200 and out[0]["action"] == "created"
        finally:
            srv.stop()


def test_token_file_rejects_malformed(tmp_path):
    from grove_tpu.api.config import load_token_file
    tf = tmp_path / "tokens"
    tf.write_text("justatokennoactor\n")
    with pytest.raises(ValidationError, match="line 1"):
        load_token_file(str(tf))
