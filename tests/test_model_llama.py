"""Model correctness: forward shapes, prefill/decode vs full forward parity,
and sharded execution over a virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grove_tpu.models import llama
from grove_tpu.ops.kvcache import KVCache
from grove_tpu.parallel import build_mesh, mesh_axes_for, shard_params
from grove_tpu.parallel.mesh import MeshPlan

CFG = llama.CONFIGS["test-tiny"]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def test_forward_shape(params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama.forward(CFG, params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_prefill_decode_matches_forward():
    """Greedy decode via the KV cache must match teacher-forced forward.

    Run in f32 so the comparison is numerically tight; bf16 is covered by
    the other tests.
    """
    import dataclasses
    cfg = dataclasses.replace(CFG, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    b, prompt_len, gen = 2, 8, 4
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (b, prompt_len + gen), 0, cfg.vocab_size)

    # Reference: full forward logits at each position.
    full_logits = llama.forward(cfg, params, tokens)

    cache = KVCache.create(cfg.n_layers, b, cfg.max_seq_len,
                           cfg.n_kv_heads, cfg.head_dim, dtype=jnp.float32)
    logits, cache = llama.prefill(cfg, params, tokens[:, :prompt_len], cache)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full_logits[:, prompt_len - 1]),
        rtol=1e-4, atol=1e-4)

    for i in range(gen):
        logits, cache = llama.decode_step(cfg, params,
                                          tokens[:, prompt_len + i], cache)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits[:, prompt_len + i]),
            rtol=1e-4, atol=1e-4)
    assert int(cache.lengths[0]) == prompt_len + gen


def test_ragged_prefill():
    """A short prompt padded into a longer batch must yield the same logits
    and decode trajectory as an unpadded batch of its own length."""
    import dataclasses
    cfg = dataclasses.replace(CFG, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    short, s_pad = 5, 12
    toks = jax.random.randint(jax.random.PRNGKey(7), (1, short), 0, cfg.vocab_size)
    padded = jnp.concatenate(
        [toks, jnp.zeros((1, s_pad - short), jnp.int32)], axis=1)

    cache_a = KVCache.create(cfg.n_layers, 1, cfg.max_seq_len,
                             cfg.n_kv_heads, cfg.head_dim, jnp.float32)
    logits_a, cache_a = llama.prefill(cfg, params, toks, cache_a)

    cache_b = KVCache.create(cfg.n_layers, 1, cfg.max_seq_len,
                             cfg.n_kv_heads, cfg.head_dim, jnp.float32)
    logits_b, cache_b = llama.prefill(cfg, params, padded, cache_b,
                                      lengths=jnp.array([short]))
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                               rtol=1e-4, atol=1e-4)
    assert int(cache_b.lengths[0]) == short

    # Decode one step from each: trajectories must match (pad K/V beyond
    # length are masked out by decode_attention).
    nxt = jnp.argmax(logits_a, -1)
    da, _ = llama.decode_step(cfg, params, nxt, cache_a)
    db, _ = llama.decode_step(cfg, params, nxt, cache_b)
    np.testing.assert_allclose(np.asarray(da), np.asarray(db),
                               rtol=1e-4, atol=1e-4)


def test_kvcache_has_room():
    cache = KVCache.create(2, 3, 16, 2, 4)
    cache = cache._replace(lengths=jnp.array([15, 16, 8], jnp.int32))
    assert np.asarray(cache.has_room()).tolist() == [True, False, True]
    assert cache.max_len == 16


def test_loss_finite(params):
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, CFG.vocab_size)
    loss = llama.loss_fn(CFG, params, tokens)
    assert jnp.isfinite(loss)


def test_sharded_forward_matches_single(cpu_devices):
    """tp=4 × sp=2 mesh execution must match the single-device result (f32)."""
    import dataclasses
    cfg = dataclasses.replace(CFG, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshPlan(dp=1, sp=2, tp=4), cpu_devices[:8])
    sharded = shard_params(mesh, params)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
    ref = llama.forward(cfg, params, tokens)
    out = jax.jit(lambda p, t: llama.forward(cfg, p, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mesh_axes_factorisation():
    for n in (1, 2, 4, 8, 16, 256):
        plan = mesh_axes_for(n)
        assert plan.size == n
        if n >= 4:
            # the flagship plan must exercise dp grad sync, not park
            # every factor on sp/tp (VERDICT r2 weak-5)
            assert plan.dp >= 2, plan
        if n >= 8:
            assert plan.dp >= 2 and plan.sp >= 2 and plan.tp >= 2, plan
    assert mesh_axes_for(8, max_tp=4) == MeshPlan(dp=2, sp=2, tp=2)


def test_chunked_prefill_matches_one_shot():
    """Chunked prefill (bounded attention reads, one executable per
    window) must match the one-shot prefill up to float accumulation
    order (XLA blocks the windowed matmuls differently), and decode
    IDENTICALLY from the resulting cache."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from grove_tpu.models import llama
    from grove_tpu.ops.kvcache import KVCache

    cfg = dataclasses.replace(llama.CONFIGS["test-tiny"], dtype=jnp.float32,
                              max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size)

    def fresh_cache():
        return KVCache.create(cfg.n_layers, 2, 64, cfg.n_kv_heads,
                              cfg.head_dim, cfg.dtype)

    want_logits, want_cache = llama.prefill(cfg, params, tokens,
                                            fresh_cache())
    got_logits, got_cache = llama.prefill_chunked(cfg, params, tokens,
                                                  fresh_cache(), chunk=8)
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(want_logits), rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_cache.k[:, :, :32]),
                               np.asarray(want_cache.k[:, :, :32]),
                               rtol=2e-3, atol=1e-5)
    assert np.array_equal(np.asarray(got_cache.lengths),
                          np.asarray(want_cache.lengths))
    # The caches decode identically from here.
    t_want, _ = (jnp.argmax(llama.decode_step(
        cfg, params, jnp.argmax(want_logits, -1).astype(jnp.int32),
        want_cache)[0], -1), None)
    t_got, _ = (jnp.argmax(llama.decode_step(
        cfg, params, jnp.argmax(got_logits, -1).astype(jnp.int32),
        got_cache)[0], -1), None)
    assert np.array_equal(np.asarray(t_want), np.asarray(t_got))
