"""Per-PCS workload identity tokens (the reference's satokensecret
component, C1g): minted once per PodCliqueSet, injected into pods as
GROVE_API_TOKEN, mapped by the server to a PCS-scoped workload actor
that may push metrics ONLY for its own PCS — and the Secret material
itself is invisible to non-system actors on every wire surface."""

from __future__ import annotations

import json
import sys
import urllib.request

import pytest

from grove_tpu.admission.authorization import OPERATOR_ACTOR
from grove_tpu.api import Pod, PodCliqueSet, constants as c
from grove_tpu.api.core import Secret
from grove_tpu.api.namegen import workload_token_secret_name
from grove_tpu.cluster import new_cluster
from grove_tpu.server import ApiServer
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import simple_pcs, wait_for
from test_server import _req

from timing import settle

OPERATOR_TOKEN = "wt-operator-token"


@pytest.fixture
def cluster():
    cl = new_cluster(fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="4x4", count=2)]))
    with cl:
        yield cl


@pytest.fixture
def server():
    from grove_tpu.api.config import OperatorConfiguration
    cfg = OperatorConfiguration()
    cfg.authorizer.enabled = True
    cfg.server_auth.tokens = {OPERATOR_TOKEN: OPERATOR_ACTOR}
    cl = new_cluster(config=cfg, fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="4x4", count=2)]))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        yield f"http://127.0.0.1:{srv.port}", cl
        srv.stop()


def _workload_token(client, pcs_name) -> str:
    sec = client.get(Secret, workload_token_secret_name(pcs_name))
    return sec.data["token"]


def test_secret_minted_once_and_cascades(cluster):
    client = cluster.client
    client.create(simple_pcs(name="tok"))
    wait_for(lambda: client.list(
        Secret, selector={c.LABEL_PCS_NAME: "tok"}), desc="secret minted")
    sec = client.get(Secret, "tok-workload-token")
    assert sec.meta.labels[c.LABEL_TOKEN_KIND] == c.TOKEN_KIND_WORKLOAD
    token = sec.data["token"]
    assert len(token) >= 24

    # stable across reconciles (a regenerated token would cut off
    # running pods)
    import time
    settle(0.5)
    assert client.get(Secret, "tok-workload-token").data["token"] == token

    client.delete(PodCliqueSet, "tok")
    wait_for(lambda: not client.list(
        Secret, selector={c.LABEL_PCS_NAME: "tok"}),
        desc="secret removed with the PCS")


def test_pods_receive_workload_token(tmp_path):
    """The ProcessKubelet injects GROVE_API_TOKEN from the PCS's secret
    — and never leaks an operator token inherited from its own shell.
    Needs REAL processes (fake kubelets never exec)."""
    import os
    from grove_tpu.agent.process import ProcessKubelet
    cl = new_cluster(
        fleet=FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                          count=2)], fake=False),
        fake_kubelet=False)
    cl.manager.add_runnable(ProcessKubelet(cl.client,
                                           workdir=str(tmp_path)))
    client = cl.client
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    os.environ["GROVE_API_TOKEN"] = "operator-shell-secret"
    try:
        with cl:
            out = (
                "import os\n"
                f"open({str(out_dir)!r} + '/' "
                "+ os.environ['GROVE_POD_NAME'], 'w')"
                ".write(os.environ.get('GROVE_API_TOKEN', 'MISSING'))\n"
                "import time; time.sleep(60)\n")
            pcs = simple_pcs(name="podtok", pods=2, chips=4)
            pcs.spec.template.cliques[0].container.argv = [
                sys.executable, "-c", out]
            client.create(pcs)
            wait_for(lambda: len(list(out_dir.iterdir())) == 2,
                     timeout=20.0, desc="pods wrote their token env")
            expected = _workload_token(client, "podtok")
    finally:
        os.environ.pop("GROVE_API_TOKEN", None)
    for f in out_dir.iterdir():
        got = f.read_text()
        assert got == expected, f"{f.name}: {got!r}"
        assert got != "operator-shell-secret"


def test_secret_reads_require_system_actor(server):
    base, cl = server
    cl.client.create(simple_pcs(name="sec"))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "sec"}), desc="minted")

    status, body = _req(f"{base}/api/Secret", token="")
    assert status == 403, (status, body)
    status, body = _req(f"{base}/api/Secret/sec-workload-token", token="")
    assert status == 403
    status, body = _req(f"{base}/api/Secret", token=OPERATOR_TOKEN)
    assert status == 200 and body[0]["data"]["token"]


def test_watch_hides_secret_events(server):
    base, cl = server
    # bootstrap the cursor BEFORE the secret exists
    status, boot = _req(f"{base}/watch", token="")
    assert status == 200
    cl.client.create(simple_pcs(name="wsec"))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "wsec"}), desc="minted")
    status, resp = _req(f"{base}/watch?since={boot['rv']}&timeout=1",
                        token="")
    assert status == 200
    kinds = {ev["kind"] for ev in resp["events"]}
    assert "Secret" not in kinds and kinds  # other events flow
    # a system actor DOES see them
    status, resp = _req(f"{base}/watch?since={boot['rv']}&timeout=1",
                        token=OPERATOR_TOKEN)
    assert "Secret" in {ev["kind"] for ev in resp["events"]}


def _push(base, token, kind, name, value=3.0, namespace="default"):
    body = json.dumps({"kind": kind, "name": name, "metric": "queue_depth",
                      "value": value, "namespace": namespace}).encode()
    return _req(f"{base}/metrics/push", "POST", body.decode(),
                content_type="application/json", token=token)


def test_workload_token_scopes_metric_pushes(server):
    base, cl = server
    cl.client.create(simple_pcs(name="mine"))
    cl.client.create(simple_pcs(name="other", pods=2))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "mine"}), desc="minted")
    wait_for(lambda: cl.client.list(Pod,
                                    selector={c.LABEL_PCS_NAME: "other"}),
             desc="other pods")
    token = _workload_token(cl.client, "mine")

    # own PCLQ: accepted
    status, body = _push(base, token, "PodClique", "mine-0-workers")
    assert status == 200, body
    # another PCS's PCLQ: rejected
    status, body = _push(base, token, "PodClique", "other-0-workers")
    assert status == 403 and "its own" in body["error"]
    # nonexistent object: rejected
    status, body = _push(base, token, "PodClique", "ghost")
    assert status == 403


def test_secret_mutating_verbs_guarded_even_without_authorizer():
    """The PATCH-echo leak: mutating verbs reply with the full object,
    so Secret access is guarded at the server for EVERY verb — even in
    the dev escape-hatch config (anonymous mutations on, authorizer
    off) where admission would not catch it."""
    from grove_tpu.api.config import OperatorConfiguration

    cfg = OperatorConfiguration()
    cfg.authorizer.enabled = False
    cfg.server_auth.allow_anonymous_mutations = True
    cl = new_cluster(config=cfg, fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="4x4", count=1)]))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            cl.client.create(simple_pcs(name="leak"))
            wait_for(lambda: cl.client.list(
                Secret, selector={c.LABEL_PCS_NAME: "leak"}),
                desc="minted")
            real = _workload_token(cl.client, "leak")
            status, body = _req(
                f"{base}/api/Secret/leak-workload-token", "PATCH", "{}",
                content_type="application/merge-patch+json", token="")
            assert status == 403, (status, body)
            assert real not in json.dumps(body)
            status, body = _req(
                f"{base}/api/Secret/leak-workload-token", "DELETE",
                token="")
            assert status == 403
            manifest = ("kind: Secret\nmetadata: {name: sneaky-secret}\n"
                        "data: {token: injected}\n")
            status, body = _req(f"{base}/apply", "POST", manifest,
                                token="")
            assert status == 403, (status, body)
        finally:
            srv.stop()


def test_workload_token_grants_no_mutations(server):
    """The escalation the review caught: a workload token must grant
    strictly LESS than anonymity, not a full actor — every mutating
    verb is rejected at the server before admission even runs."""
    base, cl = server
    cl.client.create(simple_pcs(name="esc"))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "esc"}), desc="minted")
    token = _workload_token(cl.client, "esc")

    manifest = "kind: PodCliqueSet\nmetadata: {name: sneaky}\nspec:\n" \
               "  replicas: 1\n  template:\n    cliques:\n" \
               "      - {name: w, replicas: 1, tpu_chips_per_pod: 4}\n"
    status, body = _req(f"{base}/apply", "POST", manifest, token=token)
    assert status == 403 and "metric pushes" in body["error"]
    status, body = _req(f"{base}/api/PodCliqueSet/esc", "DELETE",
                        token=token)
    assert status == 403
    # and it cannot read secrets either
    status, body = _req(f"{base}/api/Secret", token=token)
    assert status == 403


def test_require_token_for_metrics_accepts_workload_tokens(server):
    base, cl = server
    cl.manager.config.server_auth.require_token_for_metrics = True
    cl.client.create(simple_pcs(name="gated"))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "gated"}), desc="minted")
    status, body = _push(base, "", "PodClique", "gated-0-workers")
    assert status == 401
    token = _workload_token(cl.client, "gated")
    status, body = _push(base, token, "PodClique", "gated-0-workers")
    assert status == 200, body


def test_push_metric_helper_sends_workload_token(server, monkeypatch):
    """The shipped push_metric helper must attach the injected
    GROVE_API_TOKEN itself — with require_token_for_metrics on, a helper
    that omits the Authorization header gets 401 and the autoscaling
    feedback loop silently dies (pushes are advisory and swallowed)."""
    base, cl = server
    cl.manager.config.server_auth.require_token_for_metrics = True
    cl.client.create(simple_pcs(name="helper"))
    wait_for(lambda: cl.client.list(
        Secret, selector={c.LABEL_PCS_NAME: "helper"}), desc="minted")

    from grove_tpu.serving import metrics_push

    monkeypatch.setenv("GROVE_CONTROL_PLANE", base)
    monkeypatch.setenv("GROVE_PCLQ_NAME", "helper-0-workers")
    monkeypatch.delenv("GROVE_PCSG_NAME", raising=False)
    monkeypatch.delenv("GROVE_API_TOKEN", raising=False)
    # anonymous helper push: rejected by the gated server
    assert metrics_push.push_metric("queue_depth", 3.0) is False
    # with the kubelet-injected env, the helper authenticates by itself
    monkeypatch.setenv("GROVE_API_TOKEN", _workload_token(cl.client, "helper"))
    assert metrics_push.push_metric("queue_depth", 3.0) is True
