"""Manifest codec: strict decoding of user YAML (typo'd keys and
wrong-typed leaves must fail loudly, not silently become defaults)."""

import pytest

from grove_tpu.manifest import load_manifest, load_object
from grove_tpu.runtime.errors import ValidationError

GOOD = """
kind: PodCliqueSet
metadata: {name: ok}
spec:
  replicas: 2
  template:
    cliques:
      - {name: w, replicas: 2, tpu_chips_per_pod: 4}
---
kind: ClusterTopology
metadata: {name: topo}
"""


def test_multi_doc_manifest_loads():
    objs = load_manifest(GOOD)
    assert [o.KIND for o in objs] == ["PodCliqueSet", "ClusterTopology"]
    assert objs[0].spec.replicas == 2
    assert objs[0].spec.template.cliques[0].tpu_chips_per_pod == 4


def test_unknown_spec_key_rejected():
    doc = {"kind": "PodCliqueSet", "metadata": {"name": "x"},
           "spec": {"replicsa": 2}}
    with pytest.raises(ValidationError, match="spec.replicsa"):
        load_object(doc)


def test_nested_unknown_key_rejected():
    doc = {"kind": "PodCliqueSet", "metadata": {"name": "x"},
           "spec": {"template": {"cliques": [
               {"name": "w", "replicaz": 2}]}}}
    with pytest.raises(ValidationError, match="replicaz"):
        load_object(doc)


def test_wrong_typed_leaf_rejected():
    doc = {"kind": "PodCliqueSet", "metadata": {"name": "x"},
           "spec": {"replicas": {"oops": 1}}}
    with pytest.raises(ValidationError, match="spec.replicas"):
        load_object(doc)
    doc = {"kind": "PodCliqueSet", "metadata": {"name": "x"},
           "spec": {"replicas": "two"}}
    with pytest.raises(ValidationError, match="expected int"):
        load_object(doc)


def test_unknown_kind_and_missing_name():
    with pytest.raises(ValidationError, match="unknown kind"):
        load_object({"kind": "PodSet", "metadata": {"name": "x"}})
    with pytest.raises(ValidationError, match="metadata.name"):
        load_object({"kind": "PodCliqueSet", "metadata": {}})
