"""Sampling profiler (pprof/Pyroscope analog): all-threads stack
sampling, collapsed-stack export, per-phase capture, and the config-gated
HTTP debug surface."""

from __future__ import annotations

import threading
import time

import pytest

from grove_tpu.runtime.profiler import (
    PhaseProfiler,
    StackSampler,
    dump_stacks,
    profile_window,
)


def _busy_marker_fn(stop: threading.Event) -> None:
    while not stop.is_set():
        sum(i * i for i in range(500))


@pytest.fixture
def busy_thread():
    stop = threading.Event()
    t = threading.Thread(target=_busy_marker_fn, args=(stop,),
                         name="busy-marker", daemon=True)
    t.start()
    yield
    stop.set()
    t.join()


def test_sampler_sees_other_threads(busy_thread):
    sampler = profile_window(0.3, interval=0.005)
    assert sampler.samples > 10
    collapsed = sampler.collapsed()
    assert "_busy_marker_fn" in collapsed, collapsed[:500]
    # collapsed format: "a;b;c N" per line
    line = next(l for l in collapsed.splitlines() if "_busy_marker_fn" in l)
    stack, _, count = line.rpartition(" ")
    assert int(count) > 0 and ";" in stack


def test_top_reports_leaf_percentages(busy_thread):
    sampler = profile_window(0.3, interval=0.005)
    top = sampler.top(10)
    assert top and all({"func", "samples", "pct"} <= set(e) for e in top)
    assert abs(sum(e["pct"] for e in sampler.top(10_000)) - 100.0) < 1.0


def test_dump_stacks_includes_this_thread():
    text = dump_stacks()
    assert "test_dump_stacks_includes_this_thread" in text
    assert "--- thread" in text


def test_sampler_restart_refused():
    s = StackSampler(interval=0.005).start()
    with pytest.raises(AssertionError):
        s.start()
    s.stop()


def test_phase_profiler_exports(tmp_path, busy_thread):
    prof = PhaseProfiler(enabled=True, interval=0.005)
    with prof:
        prof.begin_phase("alpha")
        time.sleep(0.15)
        prof.begin_phase("beta")   # implicitly ends alpha
        time.sleep(0.15)
    assert set(prof.phases) == {"alpha", "beta"}
    summary = prof.export_dir(str(tmp_path))
    assert (tmp_path / "alpha.collapsed").exists()
    assert (tmp_path / "beta.collapsed").exists()
    assert (tmp_path / "profile-summary.json").exists()
    assert summary["alpha"]["samples"] > 0
    assert summary["alpha"]["duration_s"] > 0.1


def test_phase_profiler_disabled_is_noop(tmp_path):
    prof = PhaseProfiler(enabled=False)
    with prof:
        prof.begin_phase("alpha")
    assert prof.phases == {}
    assert prof.export_dir(str(tmp_path)) == {}


# ---- HTTP debug surface -------------------------------------------------

@pytest.fixture
def server_factory():
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.cluster import new_cluster
    from grove_tpu.server import ApiServer
    from grove_tpu.topology.fleet import FleetSpec, SliceSpec

    stack = []

    def make(profiling_enabled: bool):
        cfg = OperatorConfiguration()
        cfg.profiling.enabled = profiling_enabled
        cl = new_cluster(config=cfg, fleet=FleetSpec(
            slices=[SliceSpec(generation="v5e", topology="4x4", count=1)]))
        cl.start()
        srv = ApiServer(cl, port=0)
        srv.start()
        stack.append((cl, srv))
        return f"http://127.0.0.1:{srv.port}"

    yield make
    for cl, srv in stack:
        srv.stop()
        cl.stop()


def _get(base: str, path: str):
    from grove_tpu.cli import _http
    return _http(base, path)


def test_debug_endpoints_gated_by_config(server_factory):
    base = server_factory(profiling_enabled=False)
    status, body = _get(base, "/debug/profile?seconds=0.1")
    assert status == 404 and "disabled" in body["error"]
    status, _ = _get(base, "/debug/stacks")
    assert status == 404


def test_debug_profile_and_stacks(server_factory, busy_thread):
    base = server_factory(profiling_enabled=True)
    status, text = _get(base, "/debug/profile?seconds=0.3")
    assert status == 200 and "_busy_marker_fn" in text

    status, payload = _get(base, "/debug/profile?seconds=0.2&format=top")
    assert status == 200 and payload["samples"] > 0 and payload["top"]

    status, text = _get(base, "/debug/stacks")
    assert status == 200 and "--- thread" in text

    # window cap + bad input
    status, body = _get(base, "/debug/profile?seconds=9999")
    assert status == 400
    status, body = _get(base, "/debug/profile?seconds=nope")
    assert status == 400
    status, body = _get(base, "/debug/profile?seconds=0.1&format=wat")
    assert status == 400
