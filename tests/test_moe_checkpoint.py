"""MoE model family + orbax checkpointing."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grove_tpu.models import moe
from grove_tpu.parallel import build_mesh, shard_params
from grove_tpu.parallel.mesh import MeshPlan
from grove_tpu.serving import checkpoint

CFG = dataclasses.replace(moe.MOE_CONFIGS["moe-test-tiny"],
                          dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return moe.init_params(CFG, jax.random.PRNGKey(0))


def test_moe_forward_shape_and_finite(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                CFG.vocab_size)
    logits = moe.forward(CFG, params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))
    loss = moe.loss_fn(CFG, params, tokens)
    assert jnp.isfinite(loss)


def test_moe_routing_actually_selects():
    """Different tokens route to different experts: perturbing one
    expert's weights must change only the outputs of tokens routed to it.
    One layer — with more, attention propagates the perturbation to every
    later token and the locality check is meaningless."""
    cfg = dataclasses.replace(CFG, n_layers=1)
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 32), 0,
                                cfg.vocab_size)
    base = moe.forward(cfg, params, tokens)
    mutated = dict(params)
    mutated["layers"] = dict(params["layers"])
    mutated["layers"]["we_down"] = (
        params["layers"]["we_down"].at[:, 0].mul(2.0))  # expert 0 only
    out = moe.forward(cfg, mutated, tokens)
    changed = np.any(np.asarray(base) != np.asarray(out), axis=-1)[0]
    assert changed.any(), "no token used expert 0 at all (degenerate)"
    assert not changed.all(), "every token hit expert 0 (routing broken)"


def test_moe_sharded_matches_single(params, cpu_devices):
    mesh = build_mesh(MeshPlan(dp=1, sp=2, tp=4), cpu_devices[:8])
    sharded = shard_params(mesh, params)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                CFG.vocab_size)
    ref = moe.forward(CFG, params, tokens)
    out = jax.jit(lambda p, t: moe.forward(CFG, p, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_checkpoint_roundtrip(params, tmp_path):
    path = str(tmp_path / "ckpt")
    checkpoint.save_params(path, params, step=3)
    assert checkpoint.latest_step(path) == 3
    restored = checkpoint.load_params(path, step=3, like=params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restores_onto_mesh(params, cpu_devices, tmp_path):
    """Sharding-aware restore: leaves land with the target sharding."""
    mesh = build_mesh(MeshPlan(dp=1, sp=2, tp=4), cpu_devices[:8])
    sharded = shard_params(mesh, params)
    path = str(tmp_path / "ckpt")
    checkpoint.save_params(path, params, step=0)
    restored = checkpoint.load_params(path, step=0, like=sharded)
    leaf = restored["layers"]["we_gate"]
    assert leaf.sharding == sharded["layers"]["we_gate"].sharding
    np.testing.assert_array_equal(np.asarray(leaf),
                                  np.asarray(params["layers"]["we_gate"]))
