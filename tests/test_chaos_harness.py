"""Chaos harness: fault registry/scenario sanity, seeded
reproducibility, the expectations observability satellite, and the
end-to-end seeded runs (slow tier — the same shapes make chaos-smoke
and make chaos-soak gate on).
"""

from __future__ import annotations

import time

import pytest

from grove_tpu.chaos import FAULT_REGISTRY, SCENARIOS, ScenarioRunner
from grove_tpu.runtime.expectations import ExpectationsStore
from grove_tpu.runtime.metrics import GLOBAL_METRICS, parse_counters


# ---- registry / scenario wiring ----------------------------------------

def test_scenarios_reference_registered_faults():
    for name, fault_names in SCENARIOS.items():
        unknown = [f for f in fault_names if f not in FAULT_REGISTRY]
        assert not unknown, f"scenario {name} names unknown {unknown}"
    assert len(FAULT_REGISTRY) >= 6   # the ISSUE's fault catalog floor


def test_unknown_scenario_rejected():
    with pytest.raises(ValueError, match="unknown scenario"):
        ScenarioRunner(scenario="does-not-exist")


def test_mix_fault_choice_is_seed_deterministic():
    """The repro contract: the same seed replays the same fault
    schedule (which fault types, in which order, every cycle)."""
    def schedule(seed: int) -> list[list[str]]:
        r = ScenarioRunner(scenario="mix", seed=seed, cycles=3)
        return [[f.name for f in r._cycle_faults()] for _ in range(3)]

    assert schedule(11) == schedule(11)
    assert schedule(11) != schedule(12)   # and the seed actually matters


def test_mix_cycle_draws_at_least_four_distinct_fault_types():
    r = ScenarioRunner(scenario="mix", seed=3, cycles=1)
    names = [f.name for f in r._cycle_faults()]
    assert len(set(names)) >= 4


# ---- expectations observability (satellite) -----------------------------

def _pending_gauge(controller: str) -> float:
    got = parse_counters(GLOBAL_METRICS.render(),
                         "grove_expectations_pending")
    return got.get((("controller", controller),), 0.0)


def _expired_counter(controller: str) -> float:
    got = parse_counters(GLOBAL_METRICS.render(),
                         "grove_expectations_expired_total")
    return got.get((("controller", controller),), 0.0)


def test_expectations_pending_gauge_tracks_outstanding_uids():
    store = ExpectationsStore(ttl_seconds=30.0, controller="gaugetest")
    store.expect_creates("ns/a", ["u1", "u2"])
    store.expect_deletes("ns/a", ["u3"])
    assert _pending_gauge("gaugetest") == 3.0
    store.observe_create("ns/a", "u1")
    assert _pending_gauge("gaugetest") == 2.0
    store.observe_create("ns/a", "u2")
    store.observe_delete("ns/a", "u3")
    assert store.satisfied("ns/a")
    assert _pending_gauge("gaugetest") == 0.0


def test_expectation_ttl_expiry_counts_and_calls_back():
    """A TTL expiry is a LOST watch event, not housekeeping: the
    counter moves, the owner's callback fires with what leaked, and
    the store unblocks the controller (satisfied -> True)."""
    leaks: list[tuple] = []
    store = ExpectationsStore(ttl_seconds=0.05, controller="leaktest",
                              on_expired=lambda k, cr, de:
                              leaks.append((k, cr, de)))
    store.expect_creates("ns/b", ["u1", "u2"])
    store.observe_create("ns/b", "u1")
    assert not store.satisfied("ns/b")
    before = _expired_counter("leaktest")
    time.sleep(0.1)
    assert store.satisfied("ns/b")          # expired clears the barrier
    assert leaks == [("ns/b", 1, 0)]        # exactly what leaked
    assert _expired_counter("leaktest") == before + 1.0
    assert _pending_gauge("leaktest") == 0.0
    # Observed-clean keys never fire the leak path.
    store.expect_creates("ns/c", ["u9"])
    store.observe_create("ns/c", "u9")
    assert store.satisfied("ns/c")
    assert leaks == [("ns/b", 1, 0)]


def test_podclique_reconciler_warns_on_expired_expectation():
    """The wired path: the podclique reconciler's expiry callback lands
    an ExpectationExpired Warning event on the clique."""
    from grove_tpu.api import PodClique, new_meta
    from grove_tpu.controllers.podclique import PodCliqueReconciler
    from grove_tpu.runtime.events import events_for
    from grove_tpu.store.client import Client
    from grove_tpu.store.store import Store

    client = Client(Store())
    clique = client.create(PodClique(meta=new_meta("leaky")))
    rec = PodCliqueReconciler(client, scheduler_registry=None)
    assert rec.expectations.controller == "podclique"
    rec._expectation_expired("default/leaky", 2, 1)
    evs = events_for(client, "PodClique", "leaky")
    assert len(evs) == 1
    assert evs[0].type == "Warning"
    assert evs[0].reason == "ExpectationExpired"
    assert "2 create(s)" in evs[0].message
    assert clique.meta.uid  # the event attached to the live object


# ---- end-to-end seeded runs (slow tier) ---------------------------------

@pytest.mark.slow
@pytest.mark.timeout(500)
def test_mix_soak_two_cycles_all_invariants_green():
    """The make-chaos-smoke shape: 2 seeded mix cycles, >=4 fault
    types each, every invariant green between cycles."""
    runner = ScenarioRunner(scenario="mix", seed=7, cycles=2)
    report = runner.run()
    assert report["violations"] == [], report
    assert report["cycles_ok"] == 2
    assert len(report["fault_types_used"]) >= 4
    assert len(report["ttr_ms"]) == 2 and all(
        t > 0 for t in report["ttr_ms"])


@pytest.mark.slow
@pytest.mark.timeout(400)
def test_leader_kill_failover_small():
    """The item-4 acceptance shape at test size: SIGKILL the leader
    mid-deploy, the standby takes over via flock+lease, no orphaned or
    duplicated pods, reconcile resumed under the (scaled) budget. The
    300-pod version runs in make chaos-soak."""
    from grove_tpu.chaos.scenario import run_leader_kill

    report = run_leader_kill(pods=48, pods_per_gang=12,
                             resume_budget_s=30.0)
    assert report["ok"]
    assert report["violations"] == []
    assert report["pods_loaded"] <= report["pods"]
    assert report["time_to_resumed_s"] > 0
