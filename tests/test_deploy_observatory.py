"""Deploy observatory: per-PCS rollout progress records, the
/debug/deploy surface with its Client/HttpClient twins, the
grove_deploy_duration_seconds milestone histogram, and the
``grovectl deploy-status`` render."""

import math
import time

import pytest

from grove_tpu.api import PodCliqueSet
from grove_tpu.cluster import new_cluster
from grove_tpu.runtime.errors import NotFoundError
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import simple_pcs, wait_for


@pytest.fixture
def cluster():
    fleet = FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                        count=2)])
    cl = new_cluster(fleet=fleet)
    with cl:
        yield cl


def _wait_available_record(client, name):
    wait_for(lambda: client.get(
        PodCliqueSet, name).status.available_replicas == 1, desc="up")

    # The observer applies events asynchronously; the finalize lands
    # within a poll tick of the Available status flip — and on a slow
    # box the record itself may trail the status (no record yet is a
    # poll-again, not a crash).
    def finalized():
        try:
            return client.debug_deploy(name).get("available_at") \
                is not None
        except NotFoundError:
            return False

    wait_for(finalized, desc="deploy record finalized")
    return client.debug_deploy(name)


def test_deploy_record_full_ladder(cluster):
    """A deploy to Available records every pod through the
    created→scheduled→started→ready ladder, the gang count, the frozen
    milestone set, and a positive write-amplification number."""
    cluster.client.create(simple_pcs(name="dep1"))
    payload = _wait_available_record(cluster.client, "dep1")
    assert payload["pods"] == {"created": 3, "scheduled": 3,
                               "started": 3, "ready": 3}
    assert payload["gangs"] == {"total": 1, "scheduled": 1}
    miles = payload["milestones"]
    assert {"first_pod", "pods_created", "scheduled", "started",
            "ready", "available"} <= set(miles)
    t0 = payload["created_at"]
    assert t0 <= miles["first_pod"] <= miles["pods_created"]
    assert miles["scheduled"] <= miles["ready"] <= miles["available"]
    w = payload["writes"]
    assert w["writes"] > 0 and w["writes_per_pod"] > 0
    assert w["conflicts"] >= 0 and w["noop_writes"] >= 0
    assert w["queue_wait_s"] >= 0 and w["work_s"] > 0

    # The milestone histogram rendered once per phase with the pinned
    # lifecycle buckets.
    from grove_tpu.runtime import metrics as m
    text = cluster.manager.metrics_text()
    assert "# TYPE grove_deploy_duration_seconds histogram" in text
    hist = m.parse_histograms(text, "grove_deploy_duration_seconds")
    phases = {dict(labels).get("phase") for labels in hist}
    assert {"first_pod", "pods_created", "scheduled", "started",
            "ready", "available"} <= phases
    cum = hist[(("phase", "available"),)]
    assert set(cum) == set(m.LIFECYCLE_BUCKETS) | {math.inf}
    assert cum[math.inf] >= 1


def test_deploy_record_in_progress_and_unknown(cluster):
    """A deploy that cannot complete reports an in-progress record
    (available_at None, pods created but not scheduled); an unknown
    name raises NotFoundError on the in-process twin."""
    client = cluster.client
    client.create(simple_pcs(name="stuck", pods=5, chips=4))  # can't fit
    wait_for(lambda: (client.debug_deploy("stuck")["pods"]["created"]
                      if _has_record(client, "stuck") else 0) == 5,
             desc="pods recorded")
    payload = client.debug_deploy("stuck")
    assert payload["available_at"] is None
    assert payload["pods"]["created"] == 5
    assert payload["pods"]["ready"] == 0
    assert payload["milestones"] == {}          # frozen only at Available
    assert payload["writes"]["writes"] > 0      # live consumption delta
    with pytest.raises(NotFoundError):
        client.debug_deploy("no-such-pcs")


def _has_record(client, name) -> bool:
    try:
        client.debug_deploy(name)
        return True
    except NotFoundError:
        return False


def test_deploy_record_survives_deletion(cluster):
    """A completed deploy's record outlives its PCS (marked deleted,
    numbers frozen) so post-mortem inspection works."""
    client = cluster.client
    client.create(simple_pcs(name="gone"))
    done = _wait_available_record(client, "gone")
    client.delete(PodCliqueSet, "gone")
    wait_for(lambda: not client.list(PodCliqueSet), desc="deleted")
    wait_for(lambda: client.debug_deploy("gone")["deleted"],
             desc="record marked deleted")
    after = client.debug_deploy("gone")
    assert after["available_at"] == done["available_at"]
    assert after["writes"] == done["writes"]    # frozen, not live


def test_deploy_status_endpoint_wire_twin_and_cli(capsys):
    """GET /debug/deploy serves the same payload shape as the
    in-process twin, and ``grovectl deploy-status`` renders it with
    rollout-status-style exit codes (0 = Available, 1 = unknown)."""
    from grove_tpu.cli import main
    from grove_tpu.server import ApiServer
    from grove_tpu.store.httpclient import HttpClient

    cl = new_cluster(fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="4x4", count=2)]))
    with cl:
        srv = ApiServer(cl, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            cl.client.create(simple_pcs(name="depcli"))
            local = _wait_available_record(cl.client, "depcli")
            wire = HttpClient(base).debug_deploy("depcli")
            assert set(wire) == set(local)
            assert wire["pods"] == local["pods"]
            assert wire["milestones"].keys() == local["milestones"].keys()

            assert main(["deploy-status", "depcli",
                         "--server", base]) == 0
            out = capsys.readouterr().out
            assert "AVAILABLE after" in out
            assert "writes/pod" in out
            assert "created 3" in out and "ready 3" in out
            assert "1/1 scheduled" in out
            assert "% wait" in out
            # Unknown PCS: error to stderr, exit 1.
            assert main(["deploy-status", "ghost",
                         "--server", base]) == 1
            assert "error (404)" in capsys.readouterr().err
        finally:
            srv.stop()
