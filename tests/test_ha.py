"""HA control plane (grove_tpu/ha, proposal 0002): epoch-fenced writes,
leadership transitions (demote hygiene / warm-start re-promotion), the
hot-standby mirror + warm WAL-delta load, and the standby write
redirect. The full-scale failover proof is ``make bench-failover``;
these pin the mechanisms in isolation."""

from __future__ import annotations

import json
import os
import time

import pytest

from grove_tpu.api import (
    Node,
    Pod,
    PodCliqueSet,
    constants as c,
    new_meta,
)
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.podcliqueset import (
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
)
from grove_tpu.cluster import new_cluster
from grove_tpu.runtime.errors import ConflictError, FencedError
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.store.client import Client
from grove_tpu.store.persist import release_state_lock
from grove_tpu.store.store import Store
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

from test_e2e_simple import wait_for

from timing import settle


def pcs(name="web", replicas=1, pods=2):
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(replicas=replicas,
                              template=PodCliqueSetTemplate(
            cliques=[PodCliqueTemplate(
                name="w", replicas=pods, tpu_chips_per_pod=4,
                container=ContainerSpec(argv=["sleep", "inf"]))])))


# 2x4 slices (2 hosts / 8 chips each, the chaos-harness shape): one
# 2-pod x 4-chip gang packs a slice.
FLEET = FleetSpec(slices=[SliceSpec(generation="v5e", topology="2x4",
                                    count=2)])


# ---- epoch fencing at the store ----------------------------------------

def test_fenced_write_rejected_and_counted():
    store = Store()
    store.create(pcs("fence"))
    assert store.fencing_epoch() == 0
    epoch = store.bump_epoch()
    assert epoch == 1

    stale = Client(store)
    stale.epoch = 0
    before = GLOBAL_METRICS.counter_total("grove_store_fenced_writes_total")
    with pytest.raises(FencedError):
        stale.patch_status(PodCliqueSet, "fence", {})
    with pytest.raises(FencedError):
        stale.create(pcs("fence-2"))
    with pytest.raises(FencedError):
        stale.delete(PodCliqueSet, "fence")
    live = stale.get(PodCliqueSet, "fence")
    with pytest.raises(FencedError):
        stale.update(live)
    with pytest.raises(FencedError):
        stale.update_status(live)
    with pytest.raises(FencedError):
        stale.update_status_many([live])
    after = GLOBAL_METRICS.counter_total("grove_store_fenced_writes_total")
    assert after - before == 6
    # FencedError is a ConflictError: existing wire/conflict handling
    # treats it as terminal staleness, not a validation bug.
    assert issubclass(FencedError, ConflictError)


def test_current_epoch_and_unfenced_writes_pass():
    store = Store()
    store.bump_epoch()
    current = Client(store)
    current.epoch = store.fencing_epoch()
    current.create(pcs("ok"))                      # current epoch: fine
    unfenced = Client(store)                       # epoch None: never gated
    assert unfenced.epoch is None
    unfenced.patch_status(PodCliqueSet, "ok", {})
    # a FUTURE epoch (writer promoted against a store that hasn't seen
    # the bump yet) is not stale — accepted.
    ahead = Client(store)
    ahead.epoch = store.fencing_epoch() + 5
    ahead.patch_status(PodCliqueSet, "ok", {})


def test_ha_kill_switch_disables_fence(monkeypatch):
    monkeypatch.setenv("GROVE_HA", "0")
    store = Store()
    store.create(pcs("off"))
    store.bump_epoch()
    stale = Client(store)
    stale.epoch = 0
    stale.patch_status(PodCliqueSet, "off", {})    # no FencedError


# ---- epoch persistence (snapshot + WAL + zombie records) ---------------

def test_epoch_persists_through_wal_and_compaction(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("e"))
    assert s1.bump_epoch() == 1
    assert s1.bump_epoch() == 2

    s2 = Store(state_dir=d)                        # WAL replay
    assert s2.fencing_epoch() == 2
    s2._persister.compact(
        [o for objs in s2._objects.values() for o in objs.values()],
        rv=s2.current_rv(), epoch=s2.fencing_epoch())
    s3 = Store(state_dir=d)                        # snapshot only
    assert s3.fencing_epoch() == 2
    # sidecar mirrors the epoch for the warm loader
    assert json.load(open(os.path.join(d, "EPOCH")))["epoch"] == 2


def test_zombie_stale_epoch_wal_records_dropped_on_load(tmp_path):
    """A fenced ex-leader appending to the WAL after the takeover bump
    loses those records on the next load — the record-level half of
    the zombie guard (the store-level half is FencedError)."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("zombie", replicas=1))
    s1.bump_epoch()                                # the new leader fences
    # Zombie append: a stale-epoch put rewriting replicas, plus a
    # stale-epoch delete of the object — crafted as the dead writer's
    # file handle would have written them.
    from grove_tpu.api.serde import to_dict
    live = s1.get(PodCliqueSet, "zombie")
    live.spec.replicas = 99
    live.meta.resource_version = s1.current_rv() + 100
    with open(os.path.join(d, "wal.jsonl"), "a") as f:
        f.write(json.dumps({"op": "put", "kind": "PodCliqueSet",
                            "e": 0, "data": to_dict(live)}) + "\n")
        f.write(json.dumps({"op": "delete", "kind": "PodCliqueSet",
                            "ns": "default", "name": "zombie",
                            "rv": s1.current_rv() + 101, "e": 0}) + "\n")
    s2 = Store(state_dir=d)
    back = s2.get(PodCliqueSet, "zombie")          # delete was dropped
    assert back.spec.replicas == 1                 # put was dropped


# ---- warm (WAL-delta) load ---------------------------------------------

def _all_objects(store: Store) -> dict:
    return {(k, ns, name): o
            for k, objs in store._objects.items()
            for (ns, name), o in objs.items()}


def _mirror_at_now(store: Store) -> tuple[dict, int]:
    """A perfect mirror at the store's current rv (what a caught-up
    standby holds), as serde round-tripped copies."""
    from grove_tpu.api.serde import clone
    return ({k: clone(o) for k, o in _all_objects(store).items()},
            store.current_rv())


def test_warm_load_equals_full_load(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("a"))
    s1.create(pcs("b"))
    s1.delete(PodCliqueSet, "b")
    mirror, rv = _mirror_at_now(s1)
    # Delta past the mirror: an update, a create, and a delete.
    live = s1.get(PodCliqueSet, "a")
    live.spec.replicas = 7
    s1.update(live)
    s1.create(pcs("c"))
    s1.delete(PodCliqueSet, "c")
    s1.bump_epoch()

    warm = Store(state_dir=d, warm=(mirror, rv))
    assert warm._persister.last_load["mode"] == "warm"
    assert warm._persister.last_load["decoded"] < \
        warm._persister.last_load["lines"]
    assert warm.fencing_epoch() == 1
    assert warm.get(PodCliqueSet, "a").spec.replicas == 7
    with pytest.raises(Exception):
        warm.get(PodCliqueSet, "c")
    release_state_lock(d)

    full = Store(state_dir=d)
    assert full._persister.last_load["mode"] == "full"
    from grove_tpu.api.serde import to_dict
    warm_state = {k: to_dict(o) for k, o in _all_objects(warm).items()}
    full_state = {k: to_dict(o) for k, o in _all_objects(full).items()}
    assert warm_state == full_state
    assert warm.current_rv() == full.current_rv()


def test_warm_load_repairs_torn_tail_before_appending(tmp_path):
    """A SIGKILL mid-append (the failover case) leaves a torn final WAL
    line; the warm loader must repair it exactly as the full loader
    does — or the promoted store's first append merges into the torn
    line and the NEXT load drops every post-failover record."""
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("a"))
    mirror, rv = _mirror_at_now(s1)
    s1.create(pcs("b"))                          # the unmirrored delta
    with open(os.path.join(d, "wal.jsonl"), "a") as f:
        f.write('{"op": "put", "kind": "PodCliqueSet", "e": 0, "da')

    warm = Store(state_dir=d, warm=(mirror, rv))
    assert warm._persister.last_load["mode"] == "warm"
    warm.create(pcs("post-failover"))            # appends to the WAL
    release_state_lock(d)
    full = Store(state_dir=d)                    # nothing merged/lost
    assert {o.meta.name for o in full.list(PodCliqueSet)} == \
        {"a", "b", "post-failover"}


def test_warm_load_falls_back_on_zombie_rv_rewind(tmp_path):
    """A zombie leader appending through a stale handle rewinds the
    tail's rv ordering; the backward cut-point scan must refuse (full
    load handles zombies via the in-order epoch fence) rather than
    mistake the zombie's low rv for the mirrored boundary and drop the
    real leader's unmirrored records."""
    from grove_tpu.api.serde import to_dict
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("a"))
    mirror, rv = _mirror_at_now(s1)
    live = s1.get(PodCliqueSet, "a")
    live.spec.replicas = 9
    s1.update(live)                              # unmirrored: rv+1
    s1.bump_epoch()
    # Zombie append: stale epoch AND a rewound rv (its own counter).
    zombie = s1.get(PodCliqueSet, "a")
    zombie.spec.replicas = 1
    zombie.meta.resource_version = rv            # <= warm_rv: the trap
    with open(os.path.join(d, "wal.jsonl"), "a") as f:
        f.write(json.dumps({"op": "put", "kind": "PodCliqueSet",
                            "e": 0, "data": to_dict(zombie)}) + "\n")
    warm = Store(state_dir=d, warm=(mirror, rv))
    assert warm._persister.last_load["mode"] == "full"
    assert warm.get(PodCliqueSet, "a").spec.replicas == 9


def test_warm_load_refuses_newer_build_wal(tmp_path):
    """A WAL headed by a NEWER schema version must not be warm-decoded
    by an older standby — the fallback reaches load()'s proper
    StateVersionError refusal instead of silent downgrade corruption."""
    from grove_tpu.store.persist import StateVersionError
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("v"))
    mirror, rv = _mirror_at_now(s1)
    wal = os.path.join(d, "wal.jsonl")
    lines = open(wal).read().splitlines()
    header = json.loads(lines[0])
    header["v"] += 1                               # a newer build's WAL
    with open(wal, "w") as f:
        f.write("\n".join([json.dumps(header)] + lines[1:]) + "\n")
    release_state_lock(d)
    with pytest.raises(StateVersionError, match="newer build"):
        Store(state_dir=d, warm=(mirror, rv))


def test_leader_kill_fault_noops_with_ha_disabled(monkeypatch):
    import random
    from grove_tpu.chaos.faults import ChaosContext, LeaderKillFault
    monkeypatch.setenv("GROVE_HA", "0")
    cluster = new_cluster(fleet=FLEET)
    with cluster:
        ctx = ChaosContext(cluster, random.Random(0), workload_pcs="x")
        assert LeaderKillFault().inject(ctx) is False
        assert cluster.manager.leadership.is_leader  # nothing demoted


def test_warm_load_falls_back_when_snapshot_outruns_mirror(tmp_path):
    d = str(tmp_path / "state")
    s1 = Store(state_dir=d)
    s1.create(pcs("x"))
    mirror, rv = _mirror_at_now(s1)
    s1.create(pcs("y"))
    # Compaction folds the y-create into the snapshot: the mirror at rv
    # can no longer be completed from the WAL alone.
    s1._persister.compact(
        [o for objs in s1._objects.values() for o in objs.values()],
        rv=s1.current_rv(), epoch=0)
    warm = Store(state_dir=d, warm=(mirror, rv))
    assert warm._persister.last_load["mode"] == "full"
    warm.get(PodCliqueSet, "y")                    # nothing lost


# ---- wire fence + standby redirect -------------------------------------

@pytest.fixture()
def served_cluster():
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.server import ApiServer
    config = OperatorConfiguration()
    config.server_auth.allow_anonymous_mutations = True
    # An operator token so standbys can mirror Secrets (anonymous
    # watches censor them, breaking mirror contiguity by design).
    config.server_auth.tokens["op-token"] = OPERATOR_ACTOR
    cluster = new_cluster(config=config, fleet=FLEET)
    cluster.start()
    server = ApiServer(cluster, port=0)
    server.start()
    yield cluster, server
    server.stop()
    cluster.stop()


def test_wire_epoch_fence(served_cluster):
    from grove_tpu.store.httpclient import HttpClient
    cluster, server = served_cluster
    cluster.client.create(pcs("wire"))
    cluster.manager.store.bump_epoch()
    http = HttpClient(f"http://127.0.0.1:{server.port}")
    http.epoch = 0                                 # deposed writer
    with pytest.raises(ConflictError, match="fenced"):
        http.patch_status(PodCliqueSet, "wire", {})
    live = http.get(PodCliqueSet, "wire")
    with pytest.raises(ConflictError, match="fenced"):
        http.update_status(live)
    # current term: accepted (patch — no rv precondition, so a racing
    # controller status write can't turn the positive case into a 409)
    http.epoch = cluster.manager.store.fencing_epoch()
    http.patch_status(PodCliqueSet, "wire", {})


def test_debug_leadership_surfaces(served_cluster):
    from grove_tpu.store.httpclient import HttpClient
    cluster, server = served_cluster
    http = HttpClient(f"http://127.0.0.1:{server.port}")
    payload = http.debug_leadership()
    assert payload["role"] == "leader"
    assert payload["store_epoch"] == cluster.manager.store.fencing_epoch()
    twin = cluster.client.debug_leadership()
    assert twin["role"] == payload["role"]
    assert twin["replica"] == payload["replica"]


def test_leader_status_cli(served_cluster, capsys):
    import argparse
    from grove_tpu.cli import cmd_leader_status
    cluster, server = served_cluster
    args = argparse.Namespace(server=f"http://127.0.0.1:{server.port}",
                              ca=None)
    assert cmd_leader_status(args) == 0            # un-fenced leader
    out = capsys.readouterr().out
    assert "role:         leader" in out
    assert "epoch:" in out
    # a fenced replica (store epoch moved past its claim) exits 1 and
    # says so
    cluster.manager.store.bump_epoch()
    assert cmd_leader_status(args) == 1
    assert "FENCED" in capsys.readouterr().out


def test_standby_server_503_hint_and_client_follow(served_cluster):
    """The standby refuses writes with 503 + a leader hint; HttpClient
    and cli._http both follow the hint and land the write."""
    from grove_tpu.cli import _http
    from grove_tpu.ha.standby import HotStandby, StandbyServer
    from grove_tpu.store.httpclient import HttpClient
    cluster, server = served_cluster
    leader_url = f"http://127.0.0.1:{server.port}"
    standby = HotStandby(leader_url)
    standby.start()
    sserver = StandbyServer(standby)
    sserver.start()
    try:
        cluster.client.create(pcs("redir"))
        wait_for(lambda: standby.get_object(
            "PodCliqueSet", "redir", "default") is not None,
            desc="mirror catches the create")
        standby_url = f"http://127.0.0.1:{sserver.port}"
        # reads serve from the mirror
        http = HttpClient(standby_url)
        assert http.get(PodCliqueSet, "redir").meta.name == "redir"
        # a write follows the hint to the leader (client re-targets)
        http.patch_status(PodCliqueSet, "redir", {})
        assert http.server == leader_url
        # cli._http follows too
        status, body = _http(standby_url, "/api/PodCliqueSet/redir",
                             "DELETE")
        assert status == 200 and body.get("deleted") == "redir"
        # without a follow, the refusal names the leader
        raw = HttpClient(standby_url)
        raw.follow_leader = False
        from grove_tpu.runtime.errors import GroveError
        with pytest.raises(GroveError, match="standby"):
            raw.patch_status(PodCliqueSet, "redir", {})
    finally:
        sserver.stop()
        standby.stop()


def test_standby_mirror_stays_contiguous(served_cluster):
    from grove_tpu.ha.standby import HotStandby
    cluster, server = served_cluster
    standby = HotStandby(f"http://127.0.0.1:{server.port}",
                         token="op-token")
    standby.start()
    try:
        for i in range(3):
            cluster.client.create(pcs(f"m{i}"))
        cluster.client.delete(PodCliqueSet, "m1")
        rv0 = cluster.manager.store.current_rv()
        # Catch up to a FIXED point (the live cluster keeps writing
        # status behind us, so equality with a later current_rv races).
        wait_for(lambda: standby.rv >= rv0, desc="mirror catches rv0")
        assert standby.get_object("PodCliqueSet", "m2",
                                  "default") is not None
        assert standby.get_object("PodCliqueSet", "m1",
                                  "default") is None
        _objects, _rv, contiguous = standby.mirror_snapshot()
        assert contiguous, "a system-token watch delivers every seq " \
            "(nothing censored) — the warm-load precondition"
    finally:
        standby.stop()


# ---- leadership transitions: demote hygiene + warm re-promotion --------

def test_demote_parks_drops_and_clears_then_repromote():
    """The SURVEY §7 hygiene pin: losing leadership mid-flight drops
    queued work, clears the ExpectationsStore, and fences in-flight
    writers; re-promotion resyncs from live state and finishes the job
    with zero duplicates."""
    from grove_tpu.chaos.invariants import InvariantChecker

    cluster = new_cluster(fleet=FLEET)
    with cluster:
        mgr = cluster.manager
        client = cluster.client
        client.create(pcs("ha", pods=2))
        wait_for(lambda: client.get(PodCliqueSet, "ha")
                 .status.available_replicas >= 1, timeout=20.0,
                 desc="workload up before the transition")

        # A rival replica fences the store, and this manager notices.
        rival_epoch = mgr.store.bump_epoch()
        dropped = mgr.demote(leader_hint="rival")
        assert not mgr.leadership.is_leader
        # queued work is gone and new work is refused
        pclq = next(ctrl for ctrl in mgr.controllers
                    if ctrl.name == "podclique")
        from grove_tpu.runtime.controller import Request
        pclq.enqueue(Request("default", "ignored"))
        assert len(pclq.queue) == 0
        # expectations cleared (seed one to prove the hook runs on the
        # next demote too)
        reconciler_expectations = pclq.on_park.__self__
        reconciler_expectations.expect_creates("default/ha-0-w",
                                               ["uid-stale"])
        mgr.demote()
        assert reconciler_expectations.satisfied("default/ha-0-w")
        # deposed writers are fenced
        with pytest.raises(FencedError):
            mgr.cached_client.patch_status(PodCliqueSet, "ha", {})

        # A spec change lands while deposed (the USER is not fenced) —
        # nothing may act on it until re-promotion.
        live = client.get(PodCliqueSet, "ha")
        live.spec.replicas = 2
        client.update(live)
        settle(0.3)
        assert client.get(PodCliqueSet, "ha") \
            .status.available_replicas <= 1

        new_epoch = mgr.promote()
        assert new_epoch > rival_epoch
        assert mgr.leadership.is_leader
        assert mgr.leadership.transitions >= 2
        wait_for(lambda: client.get(PodCliqueSet, "ha")
                 .status.available_replicas >= 2, timeout=30.0,
                 desc="re-promoted leader finishes the scale-up")
        checker = InvariantChecker(cluster)
        violations = (checker.check_no_duplicates()
                      + checker.check_live_owner())
        assert not violations, violations
        # current-term writers work again
        mgr.cached_client.patch_status(PodCliqueSet, "ha", {})


def test_leader_kill_chaos_fault_roundtrip():
    """The chaos mix's leadership fault: inject proves the fence and
    demotes; heal re-promotes; the workload converges after."""
    import random
    from grove_tpu.chaos.faults import ChaosContext, LeaderKillFault

    cluster = new_cluster(fleet=FLEET)
    with cluster:
        client = cluster.client
        client.create(pcs("soak"))
        wait_for(lambda: client.get(PodCliqueSet, "soak")
                 .status.available_replicas >= 1, timeout=20.0,
                 desc="workload up")
        ctx = ChaosContext(cluster, random.Random(0),
                           workload_pcs="soak")
        fault = LeaderKillFault()
        assert fault.inject(ctx) is True
        assert not cluster.manager.leadership.is_leader
        fault.heal(ctx)
        assert cluster.manager.leadership.is_leader
        wait_for(lambda: client.get(PodCliqueSet, "soak")
                 .status.available_replicas >= 1, timeout=20.0,
                 desc="workload healthy after the transition")


# ---- in-process standby promotion (the subprocess twin is the smoke) ---

def test_hot_standby_promotes_warm_in_process(tmp_path):
    """Leader cluster on a state dir + server; standby mirrors it; the
    leader 'dies' (cluster stopped, lock released — the in-process
    stand-in for SIGKILL); promote() warm-loads, fences, and the new
    cluster reconciles the loaded workload."""
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.server import ApiServer

    d = str(tmp_path / "state")
    config = OperatorConfiguration()
    config.server_auth.tokens["op-token"] = OPERATOR_ACTOR
    leader = new_cluster(config=config, fleet=FLEET, state_dir=d)
    leader.start()
    server = ApiServer(leader, port=0)
    server.start()
    from grove_tpu.ha.standby import HotStandby
    standby = HotStandby(f"http://127.0.0.1:{server.port}", state_dir=d,
                         replica="standby-test", token="op-token")
    try:
        leader.client.create(pcs("ha"))
        wait_for(lambda: leader.client.get(PodCliqueSet, "ha")
                 .status.available_replicas >= 1, timeout=20.0,
                 desc="leader deploys")
        standby.start()
        wait_for(lambda: standby.rv >= leader.client.current_rv(),
                 desc="mirror caught up")
        # leader dies
        server.stop()
        leader.stop()
        release_state_lock(d)

        promoted = standby.promote()
        try:
            store = promoted.manager.store
            assert store._persister.last_load["mode"] == "warm"
            assert store.fencing_epoch() == 1
            assert promoted.manager.leadership.is_leader
            assert promoted.manager.leadership.replica == "standby-test"
            # loaded workload is live and reconciled by the new leader
            live = promoted.client.get(PodCliqueSet, "ha")
            assert live.spec.replicas == 1
            live.spec.replicas = 2
            promoted.client.update(live)
            wait_for(lambda: promoted.client.get(PodCliqueSet, "ha")
                     .status.available_replicas >= 2, timeout=30.0,
                     desc="promoted leader scales the loaded workload")
            # the dead leader's term is fenced
            stale = Client(store)
            stale.epoch = 0
            with pytest.raises(FencedError):
                stale.patch_status(PodCliqueSet, "ha", {})
        finally:
            promoted.stop()
    finally:
        standby.stop()
        server.stop()


# ---- controller parking unit ------------------------------------------

def test_delayqueue_drain_drops_pending_and_dirty():
    from grove_tpu.runtime.controller import Request, _DelayQueue
    q = _DelayQueue("t")
    a, b, d = (Request("default", x) for x in ("a", "b", "d"))
    q.add(a)
    q.add(b, delay=5.0)
    popped = q.get(timeout=1.0)
    q.add(popped)                                  # dirty while processing
    q.add(d, delay=0.0)
    dropped = q.drain()
    assert dropped == 3                    # b + d pending, a dirty
    q.done(popped)                                 # dirty re-add dropped too
    assert q.get(timeout=0.05) is None
    assert len(q) == 0
