"""Controller runtime: workqueue semantics, backoff, watch-driven
reconciles, expectations, slow-start, hashing, index reuse."""

import threading
import time

import pytest

from grove_tpu.api import Pod, new_meta
from grove_tpu.runtime.concurrent import run_with_slow_start
from grove_tpu.runtime.controller import Controller, Request, self_requests
from grove_tpu.runtime.expectations import ExpectationsStore
from grove_tpu.runtime.flow import StepResult, run_steps
from grove_tpu.runtime.hashutil import compute_hash
from grove_tpu.runtime.indextracker import available_indices
from grove_tpu.runtime.manager import Manager
from grove_tpu.store import FakeClient

from timing import settle


def test_flow_short_circuit():
    calls = []
    result = run_steps(
        lambda: calls.append("a") or StepResult.ok(),
        lambda: StepResult.requeue(1.5),
        lambda: calls.append("never"),
    )
    assert calls == ["a"]
    assert result.requeue_after == 1.5


def test_expectations():
    e = ExpectationsStore(ttl_seconds=0.2)
    e.expect_creates("k", ["u1", "u2"])
    assert not e.satisfied("k")
    e.observe_create("k", "u1")
    assert not e.satisfied("k")
    e.observe_create("k", "u2")
    assert e.satisfied("k")
    # ttl expiry path
    e.expect_deletes("k2", ["u3"])
    assert not e.satisfied("k2")
    settle(0.25)
    assert e.satisfied("k2")


def test_slow_start_stops_on_failure():
    attempts = []

    def ok():
        attempts.append("ok")

    def bad():
        attempts.append("bad")
        raise RuntimeError("x")

    done, errors = run_with_slow_start([ok, bad, ok, ok, ok])
    # batch1=[ok] batch2=[bad, ok] -> stop; batches 3+ never run
    assert done == 2 and len(errors) == 1
    assert len(attempts) == 3


def test_hash_stability():
    pod = Pod(meta=new_meta("a"))
    h1 = compute_hash(pod.spec)
    pod2 = Pod(meta=new_meta("a"))
    assert compute_hash(pod2.spec) == h1
    pod2.spec.tpu_chips = 4
    assert compute_hash(pod2.spec) != h1


def test_available_indices_reuses_holes():
    assert available_indices([0, 2, 5], 3) == [1, 3, 4]
    assert available_indices([], 2) == [0, 1]


def test_controller_reconciles_on_watch_event():
    client = FakeClient()
    seen = []
    done = threading.Event()

    def reconcile(req: Request):
        seen.append(req)
        done.set()
        return StepResult.finished()

    c = Controller("test", client, reconcile, workers=1)
    c.watches(["Pod"], self_requests)
    mgr = Manager(client=client, store=client.store)
    mgr.add_controller(c)
    mgr.start()
    try:
        client.create(Pod(meta=new_meta("p1")))
        assert done.wait(5.0), "reconcile never ran"
        assert seen[0] == Request("default", "p1")
        assert mgr.wait_idle(5.0)
        health = mgr.healthz()
        assert health["controllers"]["test"]["reconciles"] >= 1
    finally:
        mgr.stop()


def test_controller_backoff_retries_failures():
    client = FakeClient()
    counts = {"n": 0}
    succeeded = threading.Event()

    def reconcile(req: Request):
        counts["n"] += 1
        if counts["n"] < 3:
            return StepResult.fail(RuntimeError("transient"))
        succeeded.set()
        return StepResult.finished()

    c = Controller("retry", client, reconcile, workers=1,
                   backoff_base=0.01, backoff_max=0.05)
    c.start()
    try:
        c.enqueue(Request("default", "x"))
        assert succeeded.wait(5.0), f"only {counts['n']} attempts"
        assert counts["n"] == 3
    finally:
        c.stop()


def test_watch_event_accelerates_backoff():
    """An immediate add must override a pending delayed entry (a watch
    event cuts short a backoff window, k8s workqueue semantics)."""
    client = FakeClient()
    processed = threading.Event()

    c = Controller("accel", client, lambda req: (processed.set(),
                                                 StepResult.finished())[1],
                   workers=1)
    c.start()
    try:
        c.enqueue(Request("default", "x"), delay=5.0)
        time.sleep(0.05)
        c.enqueue(Request("default", "x"), delay=0.0)
        t0 = time.time()
        assert processed.wait(2.0), "request stuck behind backoff entry"
        assert time.time() - t0 < 1.0
    finally:
        c.stop()


def test_queue_dedupes_pending():
    client = FakeClient()
    block = threading.Event()
    processed = []

    def reconcile(req: Request):
        processed.append(req)
        block.wait(2.0)
        return StepResult.finished()

    c = Controller("dedupe", client, reconcile, workers=1)
    c.start()
    try:
        # first request occupies the worker; the rest dedupe to one pending
        c.enqueue(Request("default", "busy"))
        time.sleep(0.1)
        for _ in range(5):
            c.enqueue(Request("default", "later"))
        block.set()
        settle(0.5)
        assert processed.count(Request("default", "later")) == 1
    finally:
        c.stop()
