"""Build/version info (reference: operator/internal/version/version.go)."""

__version__ = "0.1.0"

GIT_COMMIT = "dev"


def version_info() -> dict:
    return {"version": __version__, "commit": GIT_COMMIT}
