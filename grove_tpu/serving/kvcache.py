"""Paged KV cache for the continuous-batching decode engine.

The seed engine (`DecodeEngine`) pre-allocates one contiguous
``max_len`` KV row per decode lane, so effective batch size is bounded
by the WORST-CASE sequence length: a 512-token cache budget funds 4
lanes at max_len 128 even when the live traffic averages 20 tokens.
This module replaces that with the vLLM memory model, TPU-shaped:

- **Fixed-size blocks.** One device pool per engine,
  ``[layers, num_blocks, block_size, n_kv, head_dim]`` for K and V.
  Block 0 is the NULL block: padded block-table rows and inactive
  batch slots point at it, so the scatter/gather paths never need a
  dynamic-shape branch — garbage lands in (and is read from) a block
  no live sequence owns, and the attention mask discards it.
- **Per-request block tables.** A sequence owns an ordered list of
  block ids; token position ``p`` lives at block ``table[p // bs]``,
  slot ``p % bs``. Tables are padded to bucketed widths on the way to
  the device (static shapes → no recompiles; see serving/schedule.py
  for the bucket ladder).
- **Host-side allocator.** A LIFO free list (reuse-hot blocks stay in
  cache) with strict invariants: allocation is all-or-nothing, a
  shortfall returns None (the scheduler's OOM backpressure signal —
  defer admission or preempt, never a partial grant), double-free and
  foreign-free raise. Everything here is plain host bookkeeping;
  nothing touches a device.

Effective batch is then bounded by TOKENS IN FLIGHT: the same 512-token
budget serves ~25 live 20-token sequences instead of 4 worst-case
lanes. The model-side gather/scatter lives in
``models/llama.decode_step_paged`` / ``prefill_chunk_paged``; the
design rationale (block size, bucket ladder, recompile story) is
docs/design/continuous-batching.md.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# Block 0 never leaves the allocator: padding rows of every block table
# point at it, and inactive batch slots scatter their dead writes into
# it. One sacrificial block buys static shapes everywhere else.
NULL_BLOCK = 0


class PagedKV(NamedTuple):
    """The device half: one K and one V block pool.

    Shapes: ``[layers, num_blocks, block_size, n_kv, head_dim]``. The
    pool rides jit boundaries as a plain pytree and is DONATED through
    every decode/prefill dispatch (the engine threads the returned pool
    forward, exactly like the contiguous cache)."""

    k: jnp.ndarray
    v: jnp.ndarray

    @classmethod
    def create(cls, n_layers: int, num_blocks: int, block_size: int,
               n_kv: int, head_dim: int, dtype=jnp.bfloat16) -> "PagedKV":
        shape = (n_layers, num_blocks, block_size, n_kv, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def tokens_capacity(self) -> int:
        """Usable token capacity (the null block is not allocatable)."""
        return (self.num_blocks - 1) * self.block_size


class BlockAllocator:
    """Host-side free-list allocator over the block pool.

    LIFO reuse (recently freed blocks are likeliest still warm in HBM
    caches / host page tables), all-or-nothing grants, and loud
    invariant violations: a double free or a free of a never-granted
    block is a scheduler bug, not a recoverable condition.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        assert num_blocks >= 2, "need at least the null block + one real"
        assert block_size >= 1
        self.num_blocks = num_blocks
        self.block_size = block_size
        # Block ids count down so early allocations pop low ids — makes
        # allocator traces readable; NULL_BLOCK (0) is never in the list.
        self._free: list[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._allocated: set[int] = set()
        # Counters for the telemetry/debug surfaces and the soak tests.
        self.allocs_total = 0
        self.frees_total = 0
        self.oom_events = 0
        self.high_water = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._allocated)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the null block)."""
        return self.num_blocks - 1

    @property
    def utilization(self) -> float:
        """Fraction of the allocatable pool in use — the paged analog
        of the lanes engine's kv_lane_utilization gauge."""
        return self.used_blocks / self.capacity if self.capacity else 0.0

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Grant ``n`` blocks, or None (backpressure) — never partial.
        The None is the signal the scheduler turns into deferred
        admission or preemption; raising here would make every
        steady-state OOM an exception on the hot path."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            self.oom_events += 1
            return None
        got = [self._free.pop() for _ in range(n)]
        self._allocated.update(got)
        self.allocs_total += n
        self.high_water = max(self.high_water, len(self._allocated))
        return got

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("freeing the null block")
            if b not in self._allocated:
                raise ValueError(
                    f"free of unallocated block {b} (double free or "
                    "foreign block) — scheduler bookkeeping is corrupt")
            self._allocated.remove(b)
            self._free.append(b)
            self.frees_total += 1

    def check(self) -> None:
        """Structural invariants (the soak test sweeps this between
        every operation): free ∪ allocated partitions [1, num_blocks),
        no duplicates anywhere, null block owned by neither."""
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate in free list"
        assert not (free & self._allocated), "block both free and allocated"
        assert NULL_BLOCK not in free and NULL_BLOCK not in self._allocated
        assert free | self._allocated == set(range(1, self.num_blocks)), \
            "leaked or foreign block"

    def payload(self) -> dict:
        return {"capacity": self.capacity, "used": self.used_blocks,
                "free": self.free_blocks, "block_size": self.block_size,
                "utilization": round(self.utilization, 4),
                "allocs_total": self.allocs_total,
                "frees_total": self.frees_total,
                "oom_events": self.oom_events,
                "high_water": self.high_water}


@dataclasses.dataclass
class SeqBlocks:
    """One sequence's block table: the ordered block ids backing token
    positions [0, capacity). Growth is allocator-mediated and
    all-or-nothing; ``release`` is idempotent."""

    allocator: BlockAllocator
    blocks: list[int] = dataclasses.field(default_factory=list)

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self.allocator.block_size

    def ensure(self, n_tokens: int) -> bool:
        """Grow the table to hold ``n_tokens`` total. False = OOM
        backpressure (table unchanged — the all-or-nothing grant means
        a failed ensure never strands half the growth)."""
        bs = self.allocator.block_size
        need = max(0, -(-n_tokens // bs) - len(self.blocks))
        if need == 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            return False
        self.blocks.extend(got)
        return True

    def release(self) -> None:
        if self.blocks:
            self.allocator.free(self.blocks)
            self.blocks = []


def pad_tables(tables: list[list[int]], width: int) -> np.ndarray:
    """Stack per-sequence block-id lists into a ``[len(tables), width]``
    int32 array, padding with the null block. ``width`` must cover the
    widest table (the scheduler's width bucket guarantees it)."""
    out = np.full((len(tables), width), NULL_BLOCK, np.int32)
    for i, t in enumerate(tables):
        assert len(t) <= width, (len(t), width)
        out[i, :len(t)] = t
    return out
