"""Paged KV cache for the continuous-batching decode engine.

The seed engine (`DecodeEngine`) pre-allocates one contiguous
``max_len`` KV row per decode lane, so effective batch size is bounded
by the WORST-CASE sequence length: a 512-token cache budget funds 4
lanes at max_len 128 even when the live traffic averages 20 tokens.
This module replaces that with the vLLM memory model, TPU-shaped:

- **Fixed-size blocks.** One device pool per engine,
  ``[layers, num_blocks, block_size, n_kv, head_dim]`` for K and V.
  Block 0 is the NULL block: padded block-table rows and inactive
  batch slots point at it, so the scatter/gather paths never need a
  dynamic-shape branch — garbage lands in (and is read from) a block
  no live sequence owns, and the attention mask discards it.
- **Per-request block tables.** A sequence owns an ordered list of
  block ids; token position ``p`` lives at block ``table[p // bs]``,
  slot ``p % bs``. Tables are padded to bucketed widths on the way to
  the device (static shapes → no recompiles; see serving/schedule.py
  for the bucket ladder).
- **Host-side allocator.** A LIFO free list (reuse-hot blocks stay in
  cache) with strict invariants: allocation is all-or-nothing, a
  shortfall returns None (the scheduler's OOM backpressure signal —
  defer admission or preempt, never a partial grant), double-free and
  foreign-free raise. Everything here is plain host bookkeeping;
  nothing touches a device.
- **Refcounted sharing + prefix tree** (PR 16). Blocks carry a
  refcount so the SAME block can back a shared prompt prefix in many
  live tables (SGLang's RadixAttention, block-granular). ``PrefixTree``
  hashes full-block token runs into a trie; on a sequence's last unref
  a tree-registered block parks in a CACHED LRU pool instead of the
  free list — reclaimable headroom that ``alloc`` silently evicts
  (leaf-first, LRU) before ever reporting OOM, so cached blocks never
  count against a live grant and the backpressure signal is unchanged.
  A sequence that diverges mid-block copies-on-write: the scheduler
  grants a fresh block, the engine device-copies the shared contents,
  and only then does any scatter land (the ``write-to-shared-block``
  grovelint rule polices that ordering).

Effective batch is then bounded by TOKENS IN FLIGHT: the same 512-token
budget serves ~25 live 20-token sequences instead of 4 worst-case
lanes. The model-side gather/scatter lives in
``models/llama.decode_step_paged`` / ``prefill_chunk_paged``; the
design rationale (block size, bucket ladder, recompile story) is
docs/design/continuous-batching.md and the sharing model is
docs/design/prefix-cache.md.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# Block 0 never leaves the allocator: padding rows of every block table
# point at it, and inactive batch slots scatter their dead writes into
# it. One sacrificial block buys static shapes everywhere else.
NULL_BLOCK = 0


class PagedKV(NamedTuple):
    """The device half: one K and one V block pool.

    Shapes: ``[layers, num_blocks, block_size, n_kv, head_dim]``. The
    pool rides jit boundaries as a plain pytree and is DONATED through
    every decode/prefill dispatch (the engine threads the returned pool
    forward, exactly like the contiguous cache).

    With int8 KV (``GROVE_KV_QUANT=int8``) the payload pools hold int8
    rows and ``k_scale``/``v_scale`` carry the per-(slot, head)
    symmetric dequant scales, ``[layers, num_blocks, block_size,
    n_kv]`` f32 — per-slot because rows are written incrementally (a
    whole-block scale would need slots the writer hasn't seen). Scales
    default to None so the bf16 path's pytree — and every executable
    compiled over it — is untouched when quantization is off."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @classmethod
    def create(cls, n_layers: int, num_blocks: int, block_size: int,
               n_kv: int, head_dim: int, dtype=jnp.bfloat16,
               quant: str = "off") -> "PagedKV":
        shape = (n_layers, num_blocks, block_size, n_kv, head_dim)
        if quant == "int8":
            sshape = (n_layers, num_blocks, block_size, n_kv)
            return cls(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
        assert quant == "off", f"unknown KV quant mode {quant!r}"
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def pool_bytes(self) -> int:
        """Device bytes of the whole pool, scales included."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return int(total)

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def tokens_capacity(self) -> int:
        """Usable token capacity (the null block is not allocatable)."""
        return (self.num_blocks - 1) * self.block_size


class BlockAllocator:
    """Host-side free-list allocator over the block pool.

    LIFO reuse (recently freed blocks are likeliest still warm in HBM
    caches / host page tables), all-or-nothing grants, and loud
    invariant violations: a double free or a free of a never-granted
    block is a scheduler bug, not a recoverable condition.

    With a ``PrefixTree`` attached (serving prefix cache, PR 16) every
    block is in exactly one of three states:

    - FREE: in the LIFO free list, contents garbage.
    - LIVE: refcount ≥ 1 — one count per live table holding it (plus
      one while a pending copy-on-write source). ``alloc`` grants at
      refcount 1; ``ref`` shares; ``free``/``unref`` decrements.
    - CACHED: refcount 0 but tree-registered — contents are a hashed
      prompt prefix worth keeping. Parked in an LRU pool that ``alloc``
      reclaims from (via the tree's leaf-first eviction hook) BEFORE
      reporting OOM, so cached blocks are headroom, never pressure: the
      all-or-nothing grant and the ``None`` backpressure signal are
      byte-identical to the unshared allocator.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        assert num_blocks >= 2, "need at least the null block + one real"
        assert block_size >= 1
        self.num_blocks = num_blocks
        self.block_size = block_size
        # Block ids count down so early allocations pop low ids — makes
        # allocator traces readable; NULL_BLOCK (0) is never in the list.
        self._free: list[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._refs: dict[int, int] = {}
        # Zero-ref blocks retained for the prefix cache, insertion
        # order = LRU (oldest first). Only the PrefixTree hooks below
        # ever move blocks in or out of here.
        self._cached: dict[int, None] = {}
        # Tree attachment points (None = unshared seed behavior).
        self.retain_hook = None     # block -> bool: cache on last unref?
        self.reclaim_hook = None    # () -> list[int]: evict one LRU unit
        # Counters for the telemetry/debug surfaces and the soak tests.
        self.allocs_total = 0
        self.frees_total = 0
        self.refs_total = 0
        self.oom_events = 0
        self.high_water = 0
        self.reclaimed_total = 0
        self.cached_high_water = 0
        self.adopted_total = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """LIVE blocks (refcount ≥ 1). Cached blocks are headroom and
        deliberately NOT counted: a drained engine with a warm prefix
        cache still reads used_blocks == 0."""
        return len(self._refs)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the null block)."""
        return self.num_blocks - 1

    @property
    def utilization(self) -> float:
        """Fraction of the allocatable pool in LIVE use — the paged
        analog of the lanes engine's kv_lane_utilization gauge (cached
        blocks are reclaimable, so they do not count as pressure)."""
        return self.used_blocks / self.capacity if self.capacity else 0.0

    def refcount(self, b: int) -> int:
        return self._refs.get(b, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free) + len(self._cached)

    def alloc(self, n: int) -> list[int] | None:
        """Grant ``n`` blocks, or None (backpressure) — never partial.
        The None is the signal the scheduler turns into deferred
        admission or preemption; raising here would make every
        steady-state OOM an exception on the hot path. A shortfall
        against the free list alone is NOT an OOM while the cached pool
        can cover it: unreferenced prefix blocks are evicted LRU-first
        to fill the grant (eviction before backpressure, always)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free) + len(self._cached):
            self.oom_events += 1
            return None
        while n > len(self._free):
            self._reclaim_one()
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._refs[b] = 1
        self.allocs_total += n
        self.high_water = max(self.high_water, len(self._refs))
        return got

    def adopt(self, n: int) -> list[int] | None:
        """Grant ``n`` blocks whose contents will be EXTERNALLY filled
        (the disaggregated handoff: a prefill engine's pool copies in,
        no local prefill dispatch ever writes them). Allocation
        semantics are exactly ``alloc`` — all-or-nothing, cached-LRU
        reclaim before backpressure, refcount 1 to the caller — the
        separate entry point exists so the telemetry can attribute
        handoff-adopted blocks distinctly from locally-written ones
        (docs/design/disaggregated-serving.md). The adopted block ids
        are LOCAL: the handoff remaps the source table onto them, it
        never imports foreign ids (a foreign-id free raises like any
        other unallocated free)."""
        got = self.alloc(n)
        if got is not None:
            self.adopted_total += n
        return got

    def _reclaim_one(self) -> None:
        """Evict one LRU unit from the cached pool into the free list.
        The tree's hook picks the victim (leaf-first) and drops its
        node(s); blocks it reports are moved here so the free/cached
        accounting lives in one place."""
        if self.reclaim_hook is None:
            raise RuntimeError("free-list shortfall with no reclaim hook "
                               "— can_alloc/alloc disagree")
        freed = self.reclaim_hook()
        if not freed:
            raise RuntimeError("cached-pool reclaim made no progress")
        for b in freed:
            del self._cached[b]
            self._free.append(b)
            self.reclaimed_total += 1

    def ref(self, b: int) -> None:
        """Share a block: bump a live refcount, or resurrect a cached
        block to LIVE at refcount 1 (a prefix-tree hit)."""
        if b in self._refs:
            self._refs[b] += 1
        elif b in self._cached:
            del self._cached[b]
            self._refs[b] = 1
        else:
            raise ValueError(f"ref of unallocated block {b}")
        self.refs_total += 1
        self.high_water = max(self.high_water, len(self._refs))

    def free(self, blocks: list[int]) -> None:
        """Drop one reference per listed block. The last reference
        either returns the block to the free list or — when the prefix
        tree claims it (``retain_hook``) — parks it in the cached LRU
        pool with its contents intact. Unref of a block nobody holds
        raises: that is a double free whether or not sharing is on."""
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("freeing the null block")
            r = self._refs.get(b)
            if r is None:
                raise ValueError(
                    f"free of unallocated block {b} (double free or "
                    "foreign block) — scheduler bookkeeping is corrupt")
            if r > 1:
                self._refs[b] = r - 1
            else:
                del self._refs[b]
                if self.retain_hook is not None and self.retain_hook(b):
                    self._cached[b] = None  # append = most recent
                    self.cached_high_water = max(self.cached_high_water,
                                                 len(self._cached))
                else:
                    self._free.append(b)
            self.frees_total += 1

    def check(self) -> None:
        """Structural invariants (the soak test sweeps this between
        every operation): free ∪ live ∪ cached partitions
        [1, num_blocks), no duplicates anywhere, every live refcount
        ≥ 1, null block owned by nobody."""
        free = set(self._free)
        live = set(self._refs)
        cached = set(self._cached)
        assert len(free) == len(self._free), "duplicate in free list"
        assert not (free & live), "block both free and live"
        assert not (free & cached), "block both free and cached"
        assert not (live & cached), "block both live and cached"
        assert NULL_BLOCK not in free | live | cached
        assert free | live | cached == set(range(1, self.num_blocks)), \
            "leaked or foreign block"
        assert all(r >= 1 for r in self._refs.values()), \
            "zero refcount held as live"

    def payload(self) -> dict:
        return {"capacity": self.capacity, "used": self.used_blocks,
                "free": self.free_blocks, "block_size": self.block_size,
                "cached": self.cached_blocks,
                "utilization": round(self.utilization, 4),
                "allocs_total": self.allocs_total,
                "frees_total": self.frees_total,
                "refs_total": self.refs_total,
                "oom_events": self.oom_events,
                "high_water": self.high_water,
                "reclaimed_total": self.reclaimed_total,
                "cached_high_water": self.cached_high_water,
                "adopted_total": self.adopted_total}


@dataclasses.dataclass
class SeqBlocks:
    """One sequence's block table: the ordered block ids backing token
    positions [0, capacity). Growth is allocator-mediated and
    all-or-nothing; ``release`` is idempotent."""

    allocator: BlockAllocator
    blocks: list[int] = dataclasses.field(default_factory=list)

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self.allocator.block_size

    def ensure(self, n_tokens: int) -> bool:
        """Grow the table to hold ``n_tokens`` total. False = OOM
        backpressure (table unchanged — the all-or-nothing grant means
        a failed ensure never strands half the growth)."""
        bs = self.allocator.block_size
        need = max(0, -(-n_tokens // bs) - len(self.blocks))
        if need == 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            return False
        self.blocks.extend(got)
        return True

    def release(self) -> None:
        if self.blocks:
            self.allocator.free(self.blocks)
            self.blocks = []


class PrefixNode:
    """One full block's worth of tokens in the prefix trie. ``key`` is
    the exact token tuple the block holds (the "hash" is dict hashing
    of that tuple — exact-match, collision-free by construction);
    ``block`` is the pool block whose KV backs those positions."""

    __slots__ = ("key", "block", "parent", "children")

    def __init__(self, key: tuple | None, block: int,
                 parent: "PrefixNode | None") -> None:
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict[tuple, PrefixNode] = {}


class PrefixTree:
    """Block-granular radix tree over prompt prefixes (SGLang's
    RadixAttention shape, sized to this engine).

    - **Keys are token tuples, one per FULL block** — position ``p`` of
      a registered chain holds exactly the KV a cold prefill would
      write there, so a hit is bitwise-identical to recompute.
    - **match** walks full-block children, then probes ONE partial
      block (the longest child-key prefix): the caller shares that
      block's already-computed tokens and must copy-on-write before
      writing its divergent tail. At most ``len(tokens) - 1`` tokens
      ever match — the final prompt token must run through prefill to
      produce first-token logits.
    - **Ownership**: match/insert never hold tree-side refs. Matched
      blocks are ref'd FOR THE CALLER (its release unrefs them);
      registration only marks a block worth caching, so the owner's
      last unref parks it in the allocator's cached LRU pool.
    - **Eviction** (the allocator's reclaim hook): oldest cached LEAF
      first — evicting a mid-chain node would orphan its descendants.
      When every cached node has children (possible once a divergent
      sequence grafts a live child under a cached parent), the oldest
      cached subtree is dropped whole: live descendants are merely
      unregistered (they free normally at last unref), cached ones are
      reclaimed as a bonus.
    """

    def __init__(self, allocator: BlockAllocator) -> None:
        self.allocator = allocator
        self.block_size = allocator.block_size
        self.root = PrefixNode(None, NULL_BLOCK, None)
        self._nodes: dict[int, PrefixNode] = {}   # block id -> node
        allocator.retain_hook = self._nodes.__contains__
        allocator.reclaim_hook = self._evict_lru_unit
        # Telemetry counters (ride the slo digest + engine payload).
        self.lookups = 0
        self.hits = 0                # lookups that matched ≥ 1 token
        self.tokens_matched_total = 0
        self.inserts = 0
        self.nodes_high_water = 0
        self.cow_shares = 0          # partial matches handed out

    @property
    def nodes(self) -> int:
        return len(self._nodes)

    # ---- lookup ----

    def match(self, tokens: np.ndarray
              ) -> tuple[list[int], int, tuple[int, int] | None]:
        """Longest registered prefix of ``tokens``, capped at
        ``len(tokens) - 1``. Returns ``(full_blocks, n_matched,
        partial)`` where ``full_blocks`` are whole-block hits in chain
        order, ``n_matched`` counts ALL matched tokens, and ``partial``
        is ``(block, k)`` when the last ``k`` of them sit in a shared
        block the caller must copy-on-write. Every returned block
        (including the partial source) carries one ref for the caller
        — on any later bail-out, unref them all."""
        self.lookups += 1
        bs = self.block_size
        limit = len(tokens) - 1
        node = self.root
        blocks: list[int] = []
        matched = 0
        while matched + bs <= limit:
            child = node.children.get(tuple(int(t) for t in
                                            tokens[matched:matched + bs]))
            if child is None:
                break
            self.allocator.ref(child.block)
            blocks.append(child.block)
            matched += bs
            node = child
        partial = None
        tail = tuple(int(t) for t in tokens[matched:limit])
        if tail:
            best, best_child = 0, None
            for key, child in node.children.items():
                n = 0
                for a, b in zip(key, tail):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best, best_child = n, child
            if best_child is not None:
                self.allocator.ref(best_child.block)
                partial = (best_child.block, best)
                matched += best
                self.cow_shares += 1
        if matched:
            self.hits += 1
            self.tokens_matched_total += matched
        return blocks, matched, partial

    # ---- registration ----

    def insert(self, tokens: np.ndarray, blocks: list[int]) -> int:
        """Register the chain of FULL blocks backing ``tokens`` (block
        ``i`` holds ``tokens[i*bs:(i+1)*bs]``). First writer wins: a
        key already present keeps its existing block (the duplicate
        simply frees at its owner's last unref), and the walk descends
        through the existing node so deeper suffix blocks still graft
        on. Returns newly registered nodes. No refs are taken."""
        bs = self.block_size
        assert len(blocks) * bs <= len(tokens), (len(blocks), len(tokens))
        node = self.root
        added = 0
        for i, b in enumerate(blocks):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None and b not in self._nodes \
                    and b in self.allocator._refs:
                child = PrefixNode(key, b, node)
                node.children[key] = child
                self._nodes[b] = child
                added += 1
            if child is None:
                break  # b already registered elsewhere: stop grafting
            node = child
        if added:
            self.inserts += 1
            self.nodes_high_water = max(self.nodes_high_water,
                                        len(self._nodes))
        return added

    # ---- eviction (allocator reclaim hook) ----

    def _evict_lru_unit(self) -> list[int]:
        """Evict one unit from the cached pool: the oldest cached leaf,
        or — if every cached node has children — the oldest cached
        subtree. Returns the cached block ids released (the allocator
        moves them to the free list)."""
        cached = self.allocator._cached
        victim = None
        for b in cached:
            if not self._nodes[b].children:
                victim = b
                break
        if victim is None:
            victim = next(iter(cached), None)
        if victim is None:
            return []
        return self._drop_subtree(self._nodes[victim])

    def _drop_subtree(self, node: PrefixNode) -> list[int]:
        """Unregister ``node`` and every descendant. Cached descendants
        are returned for reclaim; live ones just lose their cached-on-
        release promise (they free normally)."""
        if node.parent is not None:
            del node.parent.children[node.key]
        stack, freed = [node], []
        while stack:
            n = stack.pop()
            del self._nodes[n.block]
            if n.block in self.allocator._cached:
                freed.append(n.block)
            stack.extend(n.children.values())
            n.children = {}
            n.parent = None
        return freed

    def payload(self) -> dict:
        hit_rate = self.hits / self.lookups if self.lookups else 0.0
        return {"nodes": self.nodes,
                "cached_blocks": self.allocator.cached_blocks,
                "lookups": self.lookups, "hits": self.hits,
                "hit_rate": round(hit_rate, 4),
                "tokens_matched_total": self.tokens_matched_total,
                "inserts": self.inserts,
                "cow_shares": self.cow_shares,
                "nodes_high_water": self.nodes_high_water,
                "reclaimed_total": self.allocator.reclaimed_total}


def pad_tables(tables: list[list[int]], width: int) -> np.ndarray:
    """Stack per-sequence block-id lists into a ``[len(tables), width]``
    int32 array, padding with the null block. ``width`` must cover the
    widest table (the scheduler's width bucket guarantees it)."""
    out = np.full((len(tables), width), NULL_BLOCK, np.int32)
    for i, t in enumerate(tables):
        assert len(t) <= width, (len(t), width)
        out[i, :len(t)] = t
    return out
