"""Serving SLO telemetry — the request-lifecycle signals the control
plane scales on (docs/design/serving-slo.md).

The paper's serving target (Llama-70B disaggregated on v5e-256 at ≥90%
of bare JAX) is a LATENCY story as much as a throughput one: the
autoscaler must see time-to-first-token breach its SLO before users do.
Until this module, the data plane was blind — ``DecodeEngine`` exposed
one raw queue-depth hook and the autoscaler scaled on it statically.

``EngineTelemetry`` is the engine-side half: every tracked ``Request``
is stamped at enqueue / admit / first-token / completion (host-side
wall-clock stamps only — NOTHING on the JIT path; the decode step's
dispatch chain never sees a callback), and completions derive

- queue-wait      (enqueue → admit: how long the request sat queued),
- TTFT            (enqueue → first sampled token; the user-facing SLO),
- TPOT            (inter-token time over the decode phase),
- e2e latency     (enqueue → done),

into fixed-bucket histograms with pinned buckets (the same shape the
control plane's metrics hub renders, so ``quantile_from_buckets`` gives
the estimate a deployed alert would compute). Completion bookkeeping is
windowed (``host_sync_interval``), so completion-side stamps are
observed at drain time — up to interval-1 steps late by design; the
enqueue/admit stamps are exact.

Chunked prefill (the paged engine, PR 15) refines the first-token
stamp: ``first_token_ts`` lands when the CHUNK that produces the
token completes — the sampling moment — not at batch-wide prefill
completion, so TTFT stays honest when a prompt's windows interleave
with other work. ``admit_ts`` stays queue-exit; GROVE_TTFT_COMPAT=1
fuses the two exactly as before. Both engines route through one stamp
helper (engine._stamp_admit_impl), so the split can't drift between
them.

``snapshot()`` compresses the histograms into the percentile digest the
batched push ships (serving/metrics_push.push_samples): per-metric
value + aggregation mode, so the control plane's MetricsRegistry knows
summing a p99 across reporters is wrong (max/avg instead — see
MetricsRegistry aggregation modes).
"""

from __future__ import annotations

import threading
import time

from grove_tpu.runtime.metrics import _Hist, quantile_from_buckets

# Pinned buckets (seconds). A tiny CPU test engine lands in the
# sub-100ms bands; a loaded production engine under a traffic ramp can
# queue for tens of seconds — the default duration buckets would
# flatten one end or the other.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                2.5, 5.0, 10.0, 30.0, 60.0)
# Inter-token time: decode steps are ms-scale on real chips,
# sub-ms-to-ms on the CPU test mesh.
TPOT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0)
QUEUE_WAIT_BUCKETS = TTFT_BUCKETS
E2E_BUCKETS = (0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
               30.0, 60.0, 120.0)

# Histogram name -> pinned buckets (the engine-side metric catalog;
# serving_smoke asserts these render populated).
HISTOGRAMS = {
    "queue_wait_seconds": QUEUE_WAIT_BUCKETS,
    "ttft_seconds": TTFT_BUCKETS,
    "tpot_seconds": TPOT_BUCKETS,
    "e2e_latency_seconds": E2E_BUCKETS,
}


class EngineTelemetry:
    """Host-side request-lifecycle accounting for one serving engine.

    Thread-safe (the push pump reads snapshots while the decode loop
    observes completions), but every observation is a few dict/list
    ops — the <5% tokens/sec overhead pin in tests/test_serving.py
    holds because nothing here touches a device or a lock on the
    per-token path (tokens are counted once per drained window)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists = {name: _Hist(buckets)
                       for name, buckets in HISTOGRAMS.items()}
        self.requests_completed = 0
        self.tokens_total = 0
        # Point-sampled gauges (latest value wins, like any gauge).
        self.queue_depth = 0
        self.kv_utilization = 0.0
        # Latest memory accounting from the data-plane observatory
        # (serving/xprof.py memory_snapshot shape; None until the
        # observatory samples once). Rides the same digest as
        # TTFT/TPOT so the autoscaler and /debug/serving see memory
        # pressure, not just latency.
        self.memory: dict | None = None
        # Latest prefix-cache accounting (engine.prefix_stats shape:
        # hit_rate/cached_blocks/cached_bytes/reclaimed_bytes/...;
        # None until the engine samples once, or forever when
        # GROVE_PREFIX_CACHE=0).
        self.prefix: dict | None = None
        # Latest speculative-decoding accounting (engine.spec_stats
        # shape: acceptance_rate/accepted_per_dispatch/counters; None
        # until the engine samples once, or forever when
        # GROVE_SPEC_DECODE=0).
        self.spec: dict | None = None
        # Latest disaggregated-handoff accounting (engine.handoff_view
        # shape: requests/blocks/shared_blocks/bytes/deferred/seconds
        # + per-request derivatives; None until a handoff lands, or
        # forever when GROVE_DISAGG=0).
        self.handoff: dict | None = None
        # Latest per-phase attribution stats (reqtrace.phase_stats
        # shape: {phase: {count, total_s, dominant, p50_ms, p99_ms}};
        # None until the engine samples once, or forever when
        # GROVE_REQTRACE=0).
        self.phases: dict | None = None
        # Exemplar linkage (docs/design/request-tracing.md): the WORST
        # observed request per latency metric, by rid — the digest's
        # percentile rows carry these so a breached p99 resolves to a
        # full trace via ``grovectl request-trace <rid>``. The
        # slowest-K retained ring on the reqtrace side guarantees the
        # exemplar's trace outlives ring churn.
        self.exemplars: dict[str, dict] = {}

    # ---- engine-side hooks ----

    def sample_gauges(self, queue_depth: int,
                      kv_utilization: float) -> None:
        self.queue_depth = queue_depth
        self.kv_utilization = kv_utilization

    def sample_memory(self, mem: dict) -> None:
        """Latest engine memory accounting (xprof.memory_snapshot
        payload: kv_cache/weight/workspace/total bytes, kv_headroom,
        source) — point-sampled like the gauges."""
        self.memory = mem

    def sample_prefix(self, stats: dict) -> None:
        """Latest prefix-cache accounting (engine.prefix_stats payload:
        hit_rate, cached_blocks, cached/reclaimed bytes, cow_copies) —
        point-sampled like the gauges; rides the same digest so the
        autoscaler sees reuse alongside latency."""
        self.prefix = stats

    def sample_spec(self, stats: dict) -> None:
        """Latest speculative-decoding accounting (engine.spec_stats
        payload: acceptance_rate, accepted_per_dispatch, per-bucket
        counters) — point-sampled like the gauges; a low acceptance
        rate in the digest is the signal to shrink spec_k or swap the
        draft."""
        self.spec = stats

    def sample_handoff(self, stats: dict) -> None:
        """Latest prefill→decode handoff accounting (engine
        handoff_view payload: requests, cold/shared block counts,
        transfer bytes, deferred adoptions, per-request ms) —
        point-sampled like the gauges; a rising ms_per_request or
        deferred count in the digest is the transfer seam saturating."""
        self.handoff = stats

    def sample_phases(self, stats: dict) -> None:
        """Latest per-phase p99 attribution (reqtrace.phase_stats
        payload) — point-sampled like the gauges; the digest's
        "why slow" breakdown next to the "how slow" percentiles."""
        self.phases = stats

    def add_tokens(self, n: int) -> None:
        """Decoded-token counter, bumped once per drained window (NOT
        per token — the drain already walks the window)."""
        if n > 0:
            with self._lock:
                self.tokens_total += n

    def observe_request(self, req) -> None:
        """Fold one completed request's stamps into the histograms.
        ``req`` needs enqueue_ts/admit_ts/first_token_ts/done_ts floats
        (0.0 = never stamped) and a ``generated`` list."""
        done = req.done_ts or time.time()
        enq = req.enqueue_ts or req.admit_ts or done
        admit = req.admit_ts or enq
        first = req.first_token_ts or admit
        n_gen = len(req.generated)
        rid = getattr(req, "rid", -1)
        with self._lock:
            self.requests_completed += 1
            self._observe("queue_wait_seconds", max(0.0, admit - enq),
                          rid)
            self._observe("ttft_seconds", max(0.0, first - enq), rid)
            self._observe("e2e_latency_seconds", max(0.0, done - enq),
                          rid)
            if n_gen > 1:
                # The first token is the prefill's; the remaining
                # n_gen-1 are decode steps — TPOT is their mean pace.
                self._observe("tpot_seconds",
                              max(0.0, done - first) / (n_gen - 1), rid)

    def _observe(self, name: str, value: float,
                 rid: int = -1) -> None:
        h = self._hists[name]
        for i, ub in enumerate(h.buckets):
            if value <= ub:
                h.counts[i] += 1
                break
        else:
            h.counts[-1] += 1
        h.sum += value
        h.count += 1
        if rid >= 0:
            ex = self.exemplars.get(name)
            if ex is None or value > ex["value_s"]:
                self.exemplars[name] = {"rid": rid,
                                        "value_s": value}

    # ---- read surface ----

    def hist_count(self, name: str) -> int:
        with self._lock:
            return self._hists[name].count

    def quantile(self, name: str, q: float) -> float:
        """Bucket-interpolated quantile estimate (the same
        histogram_quantile a deployed Prometheus computes)."""
        with self._lock:
            h = self._hists[name]
            cum, c = {}, 0
            for ub, n in zip(h.buckets, h.counts):
                c += n
                cum[ub] = float(c)
            cum[float("inf")] = float(c + h.counts[-1])
        return quantile_from_buckets(q, cum)

    def snapshot(self) -> dict:
        """Percentile digest + gauges — the payload ``samples_for_push``
        turns into one batched push."""
        with self._lock:
            counts = {n: h.count for n, h in self._hists.items()}
            means = {n: (h.sum / h.count if h.count else 0.0)
                     for n, h in self._hists.items()}
            completed = self.requests_completed
            tokens = self.tokens_total
            exemplars = {n: dict(ex)
                         for n, ex in self.exemplars.items()}
        return {
            "exemplars": exemplars,
            "phases": self.phases,
            "queue_depth": self.queue_depth,
            "kv_utilization": self.kv_utilization,
            "memory": self.memory,
            "prefix": self.prefix,
            "spec": self.spec,
            "handoff": self.handoff,
            "requests_completed": completed,
            "tokens_total": tokens,
            "ttft_p50_s": self.quantile("ttft_seconds", 0.5),
            "ttft_p99_s": self.quantile("ttft_seconds", 0.99),
            "tpot_p50_s": self.quantile("tpot_seconds", 0.5),
            "tpot_p99_s": self.quantile("tpot_seconds", 0.99),
            "queue_wait_p99_s": self.quantile("queue_wait_seconds", 0.99),
            "e2e_p99_s": self.quantile("e2e_latency_seconds", 0.99),
            "counts": counts,
            "means": means,
        }


def samples_for_push(telemetry: EngineTelemetry) -> list[dict]:
    """The batched-push sample list for one engine's current state.

    Aggregation modes ride along with each sample so the registry
    combines multi-reporter values correctly WITHOUT name-sniffing:
    load signals sum (total queue depth drives scaling), utilizations
    average, worst-case latencies max (a 2-replica PCSG's p99 TTFT is
    its worst replica's, not their sum — the bug this plane fixes).
    """
    s = telemetry.snapshot()
    ms = 1000.0
    samples = []
    if s.get("memory"):
        mem = s["memory"]
        # Memory pressure alongside latency: headroom averages (the
        # scope's usable slack), byte totals sum across replicas.
        samples += [
            {"metric": "kv_headroom_frac",
             "value": float(mem.get("kv_headroom", 0.0)), "agg": "avg"},
            {"metric": "kv_cache_bytes",
             "value": float(mem.get("kv_cache_bytes", 0)), "agg": "sum"},
            {"metric": "hbm_total_bytes",
             "value": float(mem.get("total_bytes", 0)), "agg": "sum"},
        ]
    if s.get("prefix"):
        pfx = s["prefix"]
        # Prefix-cache reuse: hit-rate averages (a scope-level reuse
        # ratio), block/byte totals sum across replicas.
        samples += [
            {"metric": "prefix_hit_rate",
             "value": float(pfx.get("hit_rate", 0.0)), "agg": "avg"},
            {"metric": "prefix_cached_blocks",
             "value": float(pfx.get("cached_blocks", 0)), "agg": "sum"},
            {"metric": "prefix_reclaimed_bytes",
             "value": float(pfx.get("reclaimed_bytes", 0)), "agg": "sum"},
        ]
    if s.get("spec"):
        sp = s["spec"]
        # Speculation efficiency: rates average across replicas (a
        # scope-level acceptance ratio), the accepted-token counter
        # sums.
        samples += [
            {"metric": "spec_acceptance_rate",
             "value": float(sp.get("acceptance_rate", 0.0)),
             "agg": "avg"},
            {"metric": "spec_accepted_per_dispatch",
             "value": float(sp.get("accepted_per_dispatch", 0.0)),
             "agg": "avg"},
            {"metric": "spec_accepted_tokens",
             "value": float(sp.get("accepted_tokens", 0)), "agg": "sum"},
        ]
    if s.get("phases"):
        # p99 attribution (serving/reqtrace.py): per-phase p99 wall
        # rides the digest so the control plane sees WHERE the tail
        # lives, not just how long it is. Worst replica wins (max),
        # like the other tail latencies. These are also the
        # ``request_phase_p99_ms`` rows the bench history/dashboard
        # "p99 attribution" section consumes.
        samples += [
            {"metric": f"request_phase_p99_ms:{phase}",
             "value": float(d.get("p99_ms", 0.0)), "agg": "max"}
            for phase, d in sorted(s["phases"].items())
        ]
    if s.get("handoff"):
        ho = s["handoff"]
        # Disaggregation seam health: block/byte totals sum across
        # replica pairs, the per-request transfer cost averages (a
        # scope-level seam latency).
        samples += [
            {"metric": "handoff_blocks",
             "value": float(ho.get("blocks", 0)), "agg": "sum"},
            {"metric": "handoff_bytes",
             "value": float(ho.get("bytes", 0)), "agg": "sum"},
            {"metric": "handoff_ms_per_request",
             "value": float(ho.get("ms_per_request", 0.0)),
             "agg": "avg"},
        ]
    return samples + [
        {"metric": "queue_depth", "value": float(s["queue_depth"]),
         "agg": "sum"},
        {"metric": "kv_utilization", "value": float(s["kv_utilization"]),
         "agg": "avg"},
        {"metric": "ttft_p50_ms", "value": s["ttft_p50_s"] * ms,
         "agg": "avg"},
        {"metric": "ttft_p99_ms", "value": s["ttft_p99_s"] * ms,
         "agg": "max"},
        {"metric": "tpot_p50_ms", "value": s["tpot_p50_s"] * ms,
         "agg": "avg"},
        {"metric": "tpot_p99_ms", "value": s["tpot_p99_s"] * ms,
         "agg": "max"},
        {"metric": "queue_wait_p99_ms",
         "value": s["queue_wait_p99_s"] * ms, "agg": "max"},
        {"metric": "e2e_p99_ms", "value": s["e2e_p99_s"] * ms,
         "agg": "max"},
        {"metric": "requests_completed",
         "value": float(s["requests_completed"]), "agg": "sum"},
        {"metric": "tokens_total", "value": float(s["tokens_total"]),
         "agg": "sum"},
    ]
