"""Continuous-batching schedule policy for the paged decode engine.

Pure host bookkeeping — no jax imports, nothing here touches a device.
The engine (serving/engine.py ``PagedDecodeEngine``) owns the device
arrays and the jitted dispatch; this module owns the decisions:

- **Bucketed shapes.** Every dispatch shape comes off two fixed
  power-of-two ladders (batch slots, block-table width), so the set of
  executables is FINITE and workload-independent: once the buckets a
  deployment actually uses are warm, steady state runs zero recompiles
  (the decode_smoke / CompileTracker pin). Rounding a 5-sequence batch
  up to 8 wastes three rows of compute — the classic static-shape
  trade, and still far cheaper than one mid-traffic XLA build.
- **Admission.** A request is admitted when a decode slot is free and
  the allocator grants its first prefill chunk. A shortfall defers the
  request in place (FIFO; no head-of-line skipping — a starving big
  request must eventually get its blocks).
- **Chunked prefill.** Prompts are processed one fixed-size chunk per
  engine tick, interleaved with decode steps: the longest prompt can
  stall TPOT for at most one chunk's wall time, never the whole
  prefill. Capacity is ensured for the chunk's VALID tokens only; the
  padded tail scatters into the null block via the kernel's
  ``n_valid`` mask (models/llama._paged_scatter — without the mask,
  the clipped scatter corrupted a real block's tokens, found the hard
  way in kernel bring-up).
- **Preemption by recompute.** When decode needs a block and the pool
  is dry, the NEWEST running sequence is evicted: blocks freed,
  prompt + generated-so-far becomes its recompute prompt, and it
  re-enters at the head of the admission queue (vLLM's recompute
  policy). The victim's stamps and token counts survive — recompute
  regenerates cache state, not history.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from grove_tpu.serving.kvcache import BlockAllocator, SeqBlocks


def bucket_ladder(maximum: int, start: int = 1) -> list[int]:
    """Powers of two from ``start`` up, capped by (and always
    including) ``maximum`` — the fixed shape ladder."""
    assert maximum >= 1
    out, v = [], max(1, start)
    while v < maximum:
        out.append(v)
        v *= 2
    out.append(maximum)
    return sorted(set(out))


def pick_bucket(n: int, ladder: list[int]) -> int:
    """Smallest ladder entry >= n."""
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds the top bucket {ladder[-1]}")


@dataclasses.dataclass(eq=False)  # identity semantics: seqs are keys
class PagedSeq:
    """One request's life inside the paged engine. ``tokens`` is what
    prefill must process — the prompt, or prompt + generated for a
    recompute after preemption. ``pos`` is tokens already written to
    the cache; ``n_generated`` counts sampled tokens (the prefill's
    first token included, matching the lanes engine's accounting)."""

    req: object                     # serving.engine.Request
    tokens: np.ndarray              # int32 [len] — prefill input
    blocks: SeqBlocks
    order: int                      # admission sequence (preemption key)
    pos: int = 0                    # tokens written to the KV cache
    n_generated: int = 0
    recompute: bool = False         # re-prefill after preemption
    last_token: int = -1            # host view of the newest token
    preemptions: int = 0

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    @property
    def prefill_done(self) -> bool:
        return self.pos >= len(self.tokens)

    def finished(self) -> bool:
        return self.n_generated >= self.req.max_new_tokens


class PagedScheduler:
    """Admission / prefill / decode-set policy over one allocator.

    States a sequence moves through:
    ``preempted`` (recompute queue, drains first) → ``prefilling``
    (chunks advancing) → ``running`` (in the decode batch) → gone
    (finished: blocks freed by the engine). The engine calls the
    transition methods; everything here is synchronous host work.
    """

    def __init__(self, allocator: BlockAllocator, max_slots: int,
                 max_blocks_per_seq: int, chunk: int) -> None:
        self.allocator = allocator
        self.max_slots = max_slots
        self.chunk = chunk
        self.max_blocks_per_seq = max_blocks_per_seq
        self.batch_buckets = bucket_ladder(max_slots)
        self.width_buckets = bucket_ladder(max_blocks_per_seq)
        self.prefilling: deque[PagedSeq] = deque()
        self.running: list[PagedSeq] = []
        self.preempted: deque[PagedSeq] = deque()
        self._order = 0
        # Policy counters (debug payloads + tests).
        self.admitted_total = 0
        self.deferred_total = 0
        self.preemptions_total = 0

    # ---- occupancy ----

    @property
    def live(self) -> int:
        return len(self.prefilling) + len(self.running)

    @property
    def slots_free(self) -> int:
        return self.max_slots - self.live

    def has_prefill_work(self) -> bool:
        return bool(self.prefilling)

    # ---- admission ----

    def _chunk_capacity(self, seq: PagedSeq) -> int:
        """Token capacity the NEXT chunk dispatch needs: its VALID
        tokens (the kernel's n_valid mask reroutes the padded tail to
        the null block, so backing the padding would just tighten OOM
        pressure in small pools for nothing)."""
        return min(seq.pos + self.chunk, len(seq.tokens),
                   self.max_blocks_per_seq * self.allocator.block_size)

    def _head_starved(self) -> bool:
        """True when the prefill head's next chunk cannot currently be
        granted — new admissions must then defer (head priority), or
        an admit/evict cycle could livelock: the head's shortfall gets
        re-granted to fresh admissions forever."""
        if not self.prefilling:
            return False
        head = self.prefilling[0]
        bs = self.allocator.block_size
        need = (-(-self._chunk_capacity(head) // bs)
                - len(head.blocks.blocks))
        return need > 0 and not self.allocator.can_alloc(need)

    def admit(self, req, tokens: np.ndarray,
              recompute: bool = False) -> PagedSeq | None:
        """Admit one request if a slot is free, the prefill head is not
        starved, and the allocator grants the first chunk. None =
        backpressure (nothing allocated)."""
        if self.slots_free <= 0 or self._head_starved():
            self.deferred_total += 1
            return None
        seq = PagedSeq(req=req, tokens=np.asarray(tokens, np.int32),
                       blocks=SeqBlocks(self.allocator), order=self._order,
                       recompute=recompute)
        if not seq.blocks.ensure(self._chunk_capacity(seq)):
            self.deferred_total += 1
            return None
        self._order += 1
        self.admitted_total += 1
        self.prefilling.append(seq)
        return seq

    def readmit(self, seq: PagedSeq) -> PagedSeq | None:
        """Move the front preempted sequence back in (called before
        fresh admissions so recompute work drains first)."""
        got = self.admit(seq.req, seq.tokens, recompute=True)
        if got is not None:
            got.n_generated = seq.n_generated
            got.preemptions = seq.preemptions
        return got

    # ---- prefill ----

    def next_prefill(self) -> PagedSeq | None:
        """The chunk to run this tick: front of the prefill queue,
        ready only if its next (padded) chunk's capacity is granted.
        FIFO — a later prompt never overtakes a blocked earlier one."""
        if not self.prefilling:
            return None
        seq = self.prefilling[0]
        if not seq.blocks.ensure(self._chunk_capacity(seq)):
            return None
        return seq

    def promote(self, seq: PagedSeq) -> None:
        """Prefill finished → join the decode batch (continuous: this
        happens at ANY step, between any two decode dispatches)."""
        assert self.prefilling and self.prefilling[0] is seq
        self.prefilling.popleft()
        self.running.append(seq)

    # ---- decode-set maintenance ----

    def retire(self, seq: PagedSeq) -> None:
        """Remove a finished sequence and free its blocks."""
        self.running.remove(seq)
        seq.blocks.release()

    def evict_newest_prefilling(self, protect: PagedSeq | None = None
                                ) -> PagedSeq | None:
        """Release the NEWEST prefilling sequence's blocks and drop it
        from the prefill queue (its Request restarts from scratch via
        the engine's queue — no token was produced yet, so nothing is
        replayed). The escape hatch for prefill head-of-line OOM when
        NOTHING is decoding: with every block pinned by other
        prefilling sequences that can never advance (head-only FIFO),
        waiting for completions would wait forever."""
        candidates = [s for s in self.prefilling if s is not protect]
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: s.order)
        self.prefilling.remove(victim)
        victim.blocks.release()
        victim.pos = 0
        self.preemptions_total += 1
        return victim

    def preempt_newest(self, protect: PagedSeq | None = None
                       ) -> PagedSeq | None:
        """Evict the newest running sequence (≠ ``protect``) for
        recompute: free its blocks, queue it at the preempted head.
        Returns the victim, or None when nobody is evictable."""
        candidates = [s for s in self.running if s is not protect]
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: s.order)
        self.running.remove(victim)
        victim.blocks.release()
        # Recompute input: everything decoded so far rides the new
        # prompt, so prefill reconstructs the exact cache state (greedy
        # or seeded sampling — history is replayed, not re-drawn).
        # CALLERS MUST DRAIN FIRST: req.generated is the replay source,
        # and an undrained window here would replay a cache one-or-more
        # tokens short (a value-equality heuristic cannot detect that —
        # greedy decode repeats tokens routinely), so assert instead.
        gen = list(getattr(victim.req, "generated", []))
        assert victim.last_token < 0 or (
            gen and gen[-1] == victim.last_token), \
            "preempt_newest called with undrained window tokens"
        victim.tokens = np.concatenate(
            [np.asarray(victim.req.prompt[:victim.req.prompt_len],
                        np.int32),
             np.asarray(gen, np.int32)]) if gen else \
            np.asarray(victim.req.prompt[:victim.req.prompt_len], np.int32)
        victim.pos = 0
        victim.preemptions += 1
        self.preemptions_total += 1
        self.preempted.appendleft(victim)
        return victim

    def ensure_decode_capacity(self) -> list[PagedSeq]:
        """Grant every running sequence room for one more token,
        preempting newest-first on shortfall. Returns the victims (the
        engine re-queues them). A lone un-growable sequence is left to
        the engine to force-finish — preempting the only occupant
        would livelock."""
        victims: list[PagedSeq] = []
        for seq in list(self.running):
            if seq not in self.running:
                continue  # already evicted this sweep
            while not seq.blocks.ensure(seq.pos + 1):
                v = self.preempt_newest(protect=seq)
                if v is None:
                    return victims  # engine handles the stuck lone seq
                victims.append(v)
        return victims

    # ---- shape selection ----

    def decode_shape(self) -> tuple[int, int]:
        """(batch bucket, width bucket) for the current running set."""
        n = len(self.running)
        w = max((len(s.blocks.blocks) for s in self.running), default=1)
        return pick_bucket(n, self.batch_buckets), \
            pick_bucket(w, self.width_buckets)

    def payload(self) -> dict:
        return {"running": len(self.running),
                "prefilling": len(self.prefilling),
                "preempted": len(self.preempted),
                "max_slots": self.max_slots,
                "chunk": self.chunk,
                "batch_buckets": self.batch_buckets,
                "width_buckets": self.width_buckets,
                "admitted_total": self.admitted_total,
                "deferred_total": self.deferred_total,
                "preemptions_total": self.preemptions_total,
                "allocator": self.allocator.payload()}
