"""Continuous-batching schedule policy for the paged decode engine.

Pure host bookkeeping — no jax imports, nothing here touches a device.
The engine (serving/engine.py ``PagedDecodeEngine``) owns the device
arrays and the jitted dispatch; this module owns the decisions:

- **Bucketed shapes.** Every dispatch shape comes off two fixed
  power-of-two ladders (batch slots, block-table width), so the set of
  executables is FINITE and workload-independent: once the buckets a
  deployment actually uses are warm, steady state runs zero recompiles
  (the decode_smoke / CompileTracker pin). Rounding a 5-sequence batch
  up to 8 wastes three rows of compute — the classic static-shape
  trade, and still far cheaper than one mid-traffic XLA build.
- **Admission.** A request is admitted when a decode slot is free and
  the allocator grants its first prefill chunk. A shortfall defers the
  request in place (FIFO; no head-of-line skipping — a starving big
  request must eventually get its blocks).
- **Chunked prefill.** Prompts are processed one fixed-size chunk per
  engine tick, interleaved with decode steps: the longest prompt can
  stall TPOT for at most one chunk's wall time, never the whole
  prefill. Capacity is ensured for the chunk's VALID tokens only; the
  padded tail scatters into the null block via the kernel's
  ``n_valid`` mask (models/llama._paged_scatter — without the mask,
  the clipped scatter corrupted a real block's tokens, found the hard
  way in kernel bring-up).
- **Preemption by recompute.** When decode needs a block and the pool
  is dry, the NEWEST running sequence is evicted: blocks freed,
  prompt + generated-so-far becomes its recompute prompt, and it
  re-enters at the head of the admission queue (vLLM's recompute
  policy). The victim's stamps and token counts survive — recompute
  regenerates cache state, not history.
- **Prefix-aware admission** (PR 16, ``prefix_tree`` attached).
  Admission first matches the prompt against the block-granular prefix
  tree: whole-block hits join the table SHARED (ref'd, never copied),
  a mid-block divergence grants one fresh block as a copy-on-write
  target (the engine device-copies before the first scatter), and the
  allocator grant covers only the COLD SUFFIX. ``seq.pos`` starts at
  the matched token count, so prefill chunks skip matched tokens
  entirely — warm-prefix TTFT collapses to the suffix's chunks.
  Eviction ordering on shortfall is cached-then-preempt: ``alloc``
  reclaims unreferenced cached blocks (LRU) before it ever reports the
  OOM that defers admission or preempts running work, so a warm cache
  never steals capacity from live traffic. Every release path
  (retire / preempt / prefill-evict) registers the sequence's full
  blocks into the tree first — a preempted victim usually re-admits
  straight out of the cache it just parked.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from grove_tpu.serving.kvcache import BlockAllocator, SeqBlocks


def bucket_ladder(maximum: int, start: int = 1) -> list[int]:
    """Powers of two from ``start`` up, capped by (and always
    including) ``maximum`` — the fixed shape ladder."""
    assert maximum >= 1
    out, v = [], max(1, start)
    while v < maximum:
        out.append(v)
        v *= 2
    out.append(maximum)
    return sorted(set(out))


def pick_bucket(n: int, ladder: list[int]) -> int:
    """Smallest ladder entry >= n."""
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds the top bucket {ladder[-1]}")


@dataclasses.dataclass(eq=False)  # identity semantics: seqs are keys
class PagedSeq:
    """One request's life inside the paged engine. ``tokens`` is what
    prefill must process — the prompt, or prompt + generated for a
    recompute after preemption. ``pos`` is tokens already written to
    the cache; ``n_generated`` counts sampled tokens (the prefill's
    first token included, matching the lanes engine's accounting)."""

    req: object                     # serving.engine.Request
    tokens: np.ndarray              # int32 [len] — prefill input
    blocks: SeqBlocks
    order: int                      # admission sequence (preemption key)
    pos: int = 0                    # tokens written to the KV cache
    n_generated: int = 0
    recompute: bool = False         # re-prefill after preemption
    last_token: int = -1            # host view of the newest token
    preemptions: int = 0
    prefix_matched: int = 0         # tokens served from the prefix tree
    cow_src: int = -1               # shared block awaiting copy-on-write
    cow_dst: int = -1               # fresh block the copy lands in
    # Speculative decode: upper bound on tokens dispatched but not yet
    # drained. The device commits a DATA-DEPENDENT count per spec step
    # (accepted + bonus ≤ k+1); the host can't know it until the window
    # drains, so capacity grants use pos + inflight as the conservative
    # device-length bound. Drains fold the real counts into ``pos`` and
    # zero this. Always 0 in non-speculative mode.
    inflight: int = 0

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    @property
    def prefill_done(self) -> bool:
        return self.pos >= len(self.tokens)

    def finished(self) -> bool:
        return self.n_generated >= self.req.max_new_tokens


class PagedScheduler:
    """Admission / prefill / decode-set policy over one allocator.

    States a sequence moves through:
    ``preempted`` (recompute queue, drains first) → ``prefilling``
    (chunks advancing) → ``running`` (in the decode batch) → gone
    (finished: blocks freed by the engine). The engine calls the
    transition methods; everything here is synchronous host work.
    """

    def __init__(self, allocator: BlockAllocator, max_slots: int,
                 max_blocks_per_seq: int, chunk: int,
                 prefix_tree=None) -> None:
        self.allocator = allocator
        self.max_slots = max_slots
        self.chunk = chunk
        self.max_blocks_per_seq = max_blocks_per_seq
        self.prefix_tree = prefix_tree  # kvcache.PrefixTree | None
        self.batch_buckets = bucket_ladder(max_slots)
        self.width_buckets = bucket_ladder(max_blocks_per_seq)
        self.prefilling: deque[PagedSeq] = deque()
        self.running: list[PagedSeq] = []
        self.preempted: deque[PagedSeq] = deque()
        # serving/reqtrace.RequestObservatory | None — the owning
        # engine shares its recorder so preemption/prefix boundaries
        # stamp from the transition itself (pure host bookkeeping,
        # unconditional: a preemption-storm request's attribution must
        # never be sampled away).
        self.reqtrace = None
        self._order = 0
        # Policy counters (debug payloads + tests).
        self.admitted_total = 0
        self.deferred_total = 0
        self.preemptions_total = 0
        self.prefix_tokens_skipped_total = 0

    # ---- occupancy ----

    @property
    def live(self) -> int:
        return len(self.prefilling) + len(self.running)

    @property
    def slots_free(self) -> int:
        return self.max_slots - self.live

    def has_prefill_work(self) -> bool:
        return bool(self.prefilling)

    # ---- admission ----

    def _chunk_capacity(self, seq: PagedSeq) -> int:
        """Token capacity the NEXT chunk dispatch needs: its VALID
        tokens (the kernel's n_valid mask reroutes the padded tail to
        the null block, so backing the padding would just tighten OOM
        pressure in small pools for nothing)."""
        return min(seq.pos + self.chunk, len(seq.tokens),
                   self.max_blocks_per_seq * self.allocator.block_size)

    def _head_starved(self) -> bool:
        """True when the prefill head's next chunk cannot currently be
        granted — new admissions must then defer (head priority), or
        an admit/evict cycle could livelock: the head's shortfall gets
        re-granted to fresh admissions forever."""
        if not self.prefilling:
            return False
        head = self.prefilling[0]
        bs = self.allocator.block_size
        need = (-(-self._chunk_capacity(head) // bs)
                - len(head.blocks.blocks))
        return need > 0 and not self.allocator.can_alloc(need)

    def admit(self, req, tokens: np.ndarray,
              recompute: bool = False) -> PagedSeq | None:
        """Admit one request if a slot is free, the prefill head is not
        starved, and the allocator grants the first chunk. None =
        backpressure (nothing allocated — a failed admission also
        unrefs any prefix-tree hits it took, so matched blocks fall
        back to the cached pool untouched).

        With a prefix tree, matching runs FIRST: whole-block hits join
        the table shared, a mid-block hit adds one fresh copy-on-write
        target block, and the grant covers only the cold suffix.
        ``seq.pos`` starts past every matched token, so prefill skips
        them entirely."""
        if self.slots_free <= 0 or self._head_starved():
            self.deferred_total += 1
            return None
        tokens = np.asarray(tokens, np.int32)
        seq = PagedSeq(req=req, tokens=tokens,
                       blocks=SeqBlocks(self.allocator), order=self._order,
                       recompute=recompute)
        if self.prefix_tree is not None:
            shared, matched, partial = self.prefix_tree.match(tokens)
            seq.blocks.blocks = shared
            seq.pos = seq.prefix_matched = matched
            if partial is not None:
                src, _ = partial
                got = self.allocator.alloc(1)
                if got is None:
                    self._release_seq(seq)
                    self.allocator.free([src])
                    self.deferred_total += 1
                    return None
                seq.blocks.blocks.append(got[0])
                seq.cow_src, seq.cow_dst = src, got[0]
        if not seq.blocks.ensure(self._chunk_capacity(seq)):
            self._release_seq(seq)
            self.deferred_total += 1
            return None
        if self._head_starved():
            # The grant just taken starved the prefill head's next
            # chunk. The pre-grant gate above cannot see this: a warm
            # admission's cold need can be tiny (prefix hits cover the
            # rest), so it passes, grabs exactly the head's shortfall,
            # gets evicted as newest, and re-admits forever — a
            # livelock the cold path never hits (its own first-chunk
            # grant fails first). Roll back: matched refs fall to the
            # cached pool, exclusive blocks to the free list, and the
            # head's ensure succeeds again this tick.
            self._release_seq(seq)
            self.deferred_total += 1
            return None
        self.prefix_tokens_skipped_total += seq.prefix_matched
        if self.reqtrace is not None and self.prefix_tree is not None \
                and not recompute:
            bs = self.allocator.block_size
            self.reqtrace.note_prefix(
                req.rid, seq.prefix_matched // bs,
                -(-len(tokens) // bs), seq.prefix_matched)
        if not recompute:
            # TTFT segmentation for the bench surfaces (warm vs cold):
            # first admission only — a later recompute hit is recovery,
            # not a warm arrival.
            req.cached_tokens = seq.prefix_matched
        self._order += 1
        self.admitted_total += 1
        self.prefilling.append(seq)
        return seq

    def readmit(self, seq: PagedSeq) -> PagedSeq | None:
        """Move the front preempted sequence back in (called before
        fresh admissions so recompute work drains first)."""
        got = self.admit(seq.req, seq.tokens, recompute=True)
        if got is not None:
            got.n_generated = seq.n_generated
            got.preemptions = seq.preemptions
        return got

    # ---- prefill ----

    def next_prefill(self) -> PagedSeq | None:
        """The chunk to run this tick: front of the prefill queue,
        ready only if its next (padded) chunk's capacity is granted.
        FIFO — a later prompt never overtakes a blocked earlier one."""
        if not self.prefilling:
            return None
        seq = self.prefilling[0]
        if not seq.blocks.ensure(self._chunk_capacity(seq)):
            return None
        return seq

    def promote(self, seq: PagedSeq) -> None:
        """Prefill finished → join the decode batch (continuous: this
        happens at ANY step, between any two decode dispatches). The
        prompt's full blocks register into the prefix tree NOW — they
        are immutable from here (decode writes start past the prompt),
        so a concurrent identical prompt shares them while this one is
        still decoding."""
        assert self.prefilling and self.prefilling[0] is seq
        self.prefilling.popleft()
        self._register_prefix(seq)
        self.running.append(seq)

    def detach_prefill_head(self, seq: PagedSeq) -> None:
        """Prefill finished in DISAGG mode: drop the sequence from the
        prefill queue WITHOUT releasing its blocks — ownership moves to
        the HandoffPayload (serving/handoff.py), whose ``release()``
        registers + unrefs them once the decode side has adopted. The
        prefix registration here mirrors ``promote``: the prompt's full
        blocks are immutable from this point, so a concurrent identical
        prompt on this prefill tier shares them while the payload is
        still in flight."""
        assert self.prefilling and self.prefilling[0] is seq
        self.prefilling.popleft()
        self._register_prefix(seq)

    def adopt_running(self, seq: PagedSeq) -> None:
        """Join a handoff-adopted sequence straight into the decode
        batch: its KV already exists locally (adopted blocks + decode-
        side prefix hits), so it skips the prefilling state entirely —
        the disagg analog of admit-then-promote. The caller has already
        gated on ``slots_free``."""
        assert self.slots_free > 0, "adopt_running past the slot gate"
        seq.order = self._order
        self._order += 1
        self.admitted_total += 1
        self.prefix_tokens_skipped_total += seq.prefix_matched
        self.running.append(seq)

    # ---- release / registration (every block-freeing path) ----

    def _release_seq(self, seq: PagedSeq) -> None:
        """Drop every reference the sequence holds: its table, plus a
        pending copy-on-write source if the engine never resolved it
        (admission bail-out, prefill eviction). Shared blocks fall to
        their other holders or the cached pool; exclusive unregistered
        ones return to the free list."""
        if seq.cow_src >= 0:
            self.allocator.free([seq.cow_src])
            seq.cow_src = seq.cow_dst = -1
        seq.blocks.release()

    def _register_prefix(self, seq: PagedSeq) -> None:
        """Register the sequence's FULL blocks of known content into
        the prefix tree — called at every release site BEFORE the
        blocks are unref'd, so the last unref parks them cached instead
        of freeing them. Content is prompt + drained generated tokens
        (position ``p`` holds ``prompt[p]`` or
        ``generated[p - prompt_len]`` — the recompute replay identity),
        capped at ``seq.pos``: undrained window tokens just shorten
        what this release can cache."""
        if self.prefix_tree is None:
            return
        req = seq.req
        gen = list(getattr(req, "generated", []))
        content = np.asarray(req.prompt[:req.prompt_len], np.int32)
        if gen:
            content = np.concatenate([content,
                                      np.asarray(gen, np.int32)])
        n_known = min(seq.pos, len(content))
        n_full = n_known // self.allocator.block_size
        if n_full:
            self.prefix_tree.insert(content[:n_known],
                                    seq.blocks.blocks[:n_full])

    # ---- decode-set maintenance ----

    def retire(self, seq: PagedSeq) -> None:
        """Remove a finished sequence and free its blocks (registering
        its prefix first, so an identical prompt arriving next admits
        straight out of the cached pool)."""
        self.running.remove(seq)
        self._register_prefix(seq)
        self._release_seq(seq)

    def evict_newest_prefilling(self, protect: PagedSeq | None = None
                                ) -> PagedSeq | None:
        """Release the NEWEST prefilling sequence's blocks and drop it
        from the prefill queue (its Request restarts from scratch via
        the engine's queue — no token was produced yet, so nothing is
        replayed). The escape hatch for prefill head-of-line OOM when
        NOTHING is decoding: with every block pinned by other
        prefilling sequences that can never advance (head-only FIFO),
        waiting for completions would wait forever."""
        candidates = [s for s in self.prefilling if s is not protect]
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: s.order)
        self.prefilling.remove(victim)
        self._register_prefix(victim)
        self._release_seq(victim)
        victim.pos = 0
        victim.prefix_matched = 0
        self.preemptions_total += 1
        return victim

    def preempt_newest(self, protect: PagedSeq | None = None
                       ) -> PagedSeq | None:
        """Evict the newest running sequence (≠ ``protect``) for
        recompute: free its blocks, queue it at the preempted head.
        Returns the victim, or None when nobody is evictable."""
        candidates = [s for s in self.running if s is not protect]
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: s.order)
        self.running.remove(victim)
        self._register_prefix(victim)
        self._release_seq(victim)
        # Recompute input: everything decoded so far rides the new
        # prompt, so prefill reconstructs the exact cache state (greedy
        # or seeded sampling — history is replayed, not re-drawn).
        # CALLERS MUST DRAIN FIRST: req.generated is the replay source,
        # and an undrained window here would replay a cache one-or-more
        # tokens short (a value-equality heuristic cannot detect that —
        # greedy decode repeats tokens routinely), so assert instead.
        gen = list(getattr(victim.req, "generated", []))
        assert victim.last_token < 0 or (
            gen and gen[-1] == victim.last_token), \
            "preempt_newest called with undrained window tokens"
        victim.tokens = np.concatenate(
            [np.asarray(victim.req.prompt[:victim.req.prompt_len],
                        np.int32),
             np.asarray(gen, np.int32)]) if gen else \
            np.asarray(victim.req.prompt[:victim.req.prompt_len], np.int32)
        victim.pos = 0
        victim.prefix_matched = 0
        victim.inflight = 0
        victim.preemptions += 1
        self.preemptions_total += 1
        self.preempted.appendleft(victim)
        if self.reqtrace is not None:
            self.reqtrace.note_preempt(victim.req.rid,
                                       reason="capacity")
        return victim

    def ensure_decode_capacity(self, tokens_per_tick: int = 1
                               ) -> list[PagedSeq]:
        """Grant every running sequence room for ``tokens_per_tick``
        more tokens past its in-flight bound, preempting newest-first
        on shortfall. Returns the victims (the engine re-queues them).
        A lone un-growable sequence is left to the engine to
        force-finish — preempting the only occupant would livelock.

        Speculative ticks pass the full k+1-token span, but a sequence
        can run degraded on any prefix of it (the spec kernel's per-seq
        ``limit`` clamps acceptance to backed capacity), so a span
        shortfall falls back to the +1 grant before it ever preempts —
        identical eviction pressure to the non-speculative policy.

        Ensure targets cap at the per-sequence block capacity so a
        near-the-limit sequence never grows its table past the width
        ladder (the engine's length limit truncates its commit)."""
        cap = self.max_blocks_per_seq * self.allocator.block_size
        victims: list[PagedSeq] = []
        for seq in list(self.running):
            if seq not in self.running:
                continue  # already evicted this sweep
            while not seq.blocks.ensure(min(seq.pos + seq.inflight
                                            + tokens_per_tick, cap)):
                if tokens_per_tick > 1 and seq.blocks.ensure(
                        min(seq.pos + seq.inflight + 1, cap)):
                    break  # degraded span: clamp, don't evict
                v = self.preempt_newest(protect=seq)
                if v is None:
                    return victims  # engine handles the stuck lone seq
                victims.append(v)
        return victims

    # ---- shape selection ----

    def decode_shape(self) -> tuple[int, int]:
        """(batch bucket, width bucket) for the current running set."""
        n = len(self.running)
        w = max((len(s.blocks.blocks) for s in self.running), default=1)
        return pick_bucket(n, self.batch_buckets), \
            pick_bucket(w, self.width_buckets)

    def payload(self) -> dict:
        return {"running": len(self.running),
                "prefilling": len(self.prefilling),
                "preempted": len(self.preempted),
                "max_slots": self.max_slots,
                "chunk": self.chunk,
                "batch_buckets": self.batch_buckets,
                "width_buckets": self.width_buckets,
                "admitted_total": self.admitted_total,
                "deferred_total": self.deferred_total,
                "preemptions_total": self.preemptions_total,
                "prefix_tokens_skipped_total":
                    self.prefix_tokens_skipped_total,
                "prefix": (self.prefix_tree.payload()
                           if self.prefix_tree is not None else None),
                "allocator": self.allocator.payload()}
