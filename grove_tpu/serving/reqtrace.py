"""Request observatory — per-request distributed tracing and p99
latency attribution for the serving path
(docs/design/request-tracing.md).

The SLO layer (serving/slo.py) can say THAT p99 TTFT breached; it
cannot say WHY — after PR 16–18 a request crosses up to three tiers
(prefix cache → prefill engine → handoff → paged decode with
speculation), and a breach is a number with no story. This module
gives every sampled request a story: a bounded host-side span recorder
stamping the seams the engine already crosses —

- enqueue / admit        (queue_wait: how long it sat before work),
- prefix-cache match     (blocks hit/missed at admission),
- prefill chunks         (bucket-labelled, one span per sampled chunk),
- handoff                (detach → remap/copy → adopt; the trace rides
                          ``HandoffPayload.trace`` so ONE trace spans
                          both tiers of GROVE_DISAGG=1),
- decode segments        (split at preemption/recompute boundaries),
- speculation windows    (per-window acceptance),
- completion.

On top of the ring sit the two consumers the router PR needs:

- **p99 attribution** — each finished trace classifies its dominant
  phase (argmax of accumulated per-phase seconds), feeding the
  ``grove_request_phase_seconds{phase}`` histogram family; a
  slowest-K retained ring holds the worst traces by e2e so the tail
  is never sampled away by ring churn.
- **exemplar linkage** — the SLO digest's percentile rows carry
  exemplar request ids (worst observed value per metric, tracked by
  ``EngineTelemetry``) that resolve to full traces here via
  ``grovectl request-trace <rid>``.

Everything is host-side dict/list work — NOTHING on the JIT path, no
device syncs, no wrappers around jitted callables. Per-request seam
stamps (enqueue/admit/handoff/done) are unconditional: once per
request, never per step. Per-TICK decoration (prefill chunk spans,
spec windows) rides the xprof-style sampling gate
(``should_sample()``), and grovelint's ``reqtrace-gate`` rule pins
that recording inside ``_decode_tick``/``_prefill_tick`` stays behind
it. ``GROVE_REQTRACE=0`` restores the exact prior hot path: engines
construct with ``reqtrace=None`` and every call site guards on it, so
the token stream and the lowering set are byte-identical (pinned by
decode_smoke). Overhead with it ON is pinned <5% by the dual
estimator in tests/test_reqtrace.py.

Surfaces follow the house pattern: ``GET /debug/requests/<ns>/<name>``
(server.py, read-gated like /debug/xprof), ``Client.debug_requests`` /
``HttpClient.debug_requests`` twins, and ``grovectl request-trace``
rendering the span timeline with the dominant phase starred.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
import weakref

# Attribution taxonomy: the phase buckets a finished trace's wall time
# is split into. Dominant phase = argmax — the one-word answer to
# "why was this request slow". ``spec`` never appears here: spec
# windows are decode dispatches and accumulate as decode time; the
# per-window spans carry the acceptance detail instead.
PHASES = ("queue_wait", "prefix_match", "prefill", "handoff",
          "decode", "preempt_recompute")

# Spans one trace may hold before it starts dropping (a pathological
# 100k-token decode must not grow an unbounded span list — phase
# accumulation keeps counting; only the span detail is shed).
SPAN_CAP = 512


def enabled() -> bool:
    """The observatory kill switch, read at engine construction (same
    contract as GROVE_XPROF/GROVE_TRACE: 0 = the exact pre-feature
    hot path — no recorder, no branches taken, no stamps)."""
    return os.environ.get("GROVE_REQTRACE", "1") != "0"


@dataclasses.dataclass
class Span:
    phase: str
    label: str
    t0: float          # absolute wall-clock start
    seconds: float
    detail: dict | None = None


class RequestTrace:
    """One request's span timeline plus its per-phase accumulation.

    Mutated only under the owning observatory's lock. ``marks`` holds
    open-segment start stamps (prefill_start/decode_start/
    preempt_start) between the seam calls that close them.
    """

    __slots__ = ("rid", "created_ts", "spans", "dropped_spans",
                 "phase_seconds", "marks", "done_ts", "dominant",
                 "e2e_s")

    def __init__(self, rid: int, created_ts: float) -> None:
        self.rid = rid
        self.created_ts = created_ts
        self.spans: list[Span] = []
        self.dropped_spans = 0
        self.phase_seconds: dict[str, float] = {}
        self.marks: dict[str, float] = {}
        self.done_ts = 0.0
        self.dominant: str | None = None
        self.e2e_s = 0.0

    def add_span(self, phase: str, label: str, t0: float,
                 seconds: float, detail: dict | None = None,
                 accumulate: bool = True) -> None:
        if accumulate:
            self.phase_seconds[phase] = \
                self.phase_seconds.get(phase, 0.0) + max(0.0, seconds)
        if len(self.spans) >= SPAN_CAP:
            self.dropped_spans += 1
            return
        self.spans.append(Span(phase, label, t0, seconds, detail))

    def classify(self) -> str:
        """Dominant phase: argmax of accumulated seconds. A trace with
        no accumulation (dropped mid-flight) attributes to queue_wait
        — the only phase every request provably entered."""
        if not self.phase_seconds:
            return "queue_wait"
        return max(self.phase_seconds, key=self.phase_seconds.get)

    def to_dict(self) -> dict:
        return {
            "rid": self.rid,
            "created_ts": round(self.created_ts, 6),
            "done": bool(self.done_ts),
            "e2e_s": round(self.e2e_s, 6),
            "dominant": self.dominant,
            "phases": {p: round(s, 6)
                       for p, s in self.phase_seconds.items()},
            "dropped_spans": self.dropped_spans,
            "spans": [{
                "phase": s.phase,
                "label": s.label,
                "t0_off_ms": round((s.t0 - self.created_ts) * 1e3, 3),
                "ms": round(s.seconds * 1e3, 3),
                **({"detail": s.detail} if s.detail else {}),
            } for s in sorted(self.spans, key=lambda s: s.t0)],
        }


class RequestObservatory:
    """Bounded per-request span recorder for one engine (or one shared
    disagg pair — ``make_disagg`` hands BOTH tiers the same instance,
    like the shared ``EngineTelemetry``, so a trace spans the seam).

    Three rings, all bounded:

    - ``_live``: in-flight traces keyed by rid (capped; a submit storm
      past the cap drops new traces and counts them — never grows).
    - ``_ring``: finished traces, newest-N (deque, evictions counted
      into ``grove_reqtrace_dropped_total`` so churn is visible).
    - ``_slowest``: top-K finished traces by e2e — the tail the ring
      would otherwise sample away. p99 exemplars resolve here long
      after the ring has churned past them.
    """

    def __init__(self, capacity: int | None = None,
                 sample_every: int | None = None,
                 slowest_k: int | None = None,
                 live_cap: int | None = None,
                 metrics=None, name: str | None = None,
                 namespace: str = "default") -> None:
        if metrics is None:
            from grove_tpu.runtime.metrics import GLOBAL_METRICS
            metrics = GLOBAL_METRICS
        if capacity is None:
            capacity = int(os.environ.get("GROVE_REQTRACE_RING", 256))
        if sample_every is None:
            sample_every = int(os.environ.get("GROVE_REQTRACE_SAMPLE", 4))
        if slowest_k is None:
            slowest_k = int(os.environ.get("GROVE_REQTRACE_SLOWEST", 8))
        if live_cap is None:
            live_cap = int(os.environ.get("GROVE_REQTRACE_LIVE", 4096))
        self.capacity = max(1, capacity)
        self.sample_every = max(1, sample_every)
        self.slowest_k = max(1, slowest_k)
        self.live_cap = max(1, live_cap)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._live: dict[int, RequestTrace] = {}
        self._ring: collections.deque[RequestTrace] = collections.deque(
            maxlen=self.capacity)
        self._slowest: list[RequestTrace] = []
        self._ticks = 0
        self.dropped = 0
        self.finished_total = 0
        self._phase_cache: tuple = (None, {})
        self.namespace = namespace
        self.name = name or _next_auto_name()
        register(self)

    # ---- sampling gate (the per-tick decoration gate; seam stamps
    # are unconditional and never route through it) ----

    def should_sample(self) -> bool:
        """Every Nth TICK's chunk/window decoration is recorded — one
        modulo per tick, the same 1/N shape as xprof's FlightRecorder.
        Phase attribution does NOT depend on this: phase seconds come
        from the unconditional seam stamps, so sampling only thins the
        per-chunk span detail."""
        self._ticks += 1
        return (self._ticks - 1) % self.sample_every == 0

    def _drop(self, n: int = 1) -> None:
        self.dropped += n
        self._metrics.inc("grove_reqtrace_dropped_total", n)

    # ---- seam hooks (unconditional: once per request per seam) ----

    def note_enqueue(self, rid: int, ts: float | None = None,
                     prompt_len: int = 0,
                     max_new_tokens: int = 0) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            if rid in self._live:
                return
            if len(self._live) >= self.live_cap:
                self._drop()
                return
            t = RequestTrace(rid, ts)
            t.add_span("queue_wait", "enqueued", ts, 0.0,
                       {"prompt_len": int(prompt_len),
                        "max_new_tokens": int(max_new_tokens)},
                       accumulate=False)
            self._live[rid] = t

    def note_admit(self, rid: int, ts: float | None = None) -> None:
        """Queue exit: closes queue_wait, opens the prefill segment."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None or "prefill_start" in t.marks:
                return
            t.add_span("queue_wait", "", t.created_ts,
                       ts - t.created_ts)
            t.marks["prefill_start"] = ts

    def note_prefix(self, rid: int, matched_blocks: int,
                    total_blocks: int, matched_tokens: int,
                    seconds: float = 0.0) -> None:
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            t.add_span("prefix_match",
                       f"{matched_blocks}/{total_blocks} blocks",
                       time.time() - seconds, seconds,
                       {"matched_tokens": matched_tokens})

    def note_chunk(self, rid: int, bucket: int, seconds: float,
                   tokens: int) -> None:
        """One sampled prefill chunk (bucket-labelled). Decoration
        only: prefill phase seconds accumulate from the admit →
        prefill-done boundaries, so thinning chunks never skews
        attribution. MUST stay behind the sampling gate inside
        ``_prefill_tick`` (grovelint: reqtrace-gate)."""
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            t.add_span("prefill", f"chunk[{bucket}]",
                       time.time() - seconds, seconds,
                       {"tokens": tokens}, accumulate=False)

    def note_prefill_done(self, rid: int,
                          ts: float | None = None) -> None:
        """Prefill completion. If the sequence was replaying a
        preemption recompute, the elapsed prefill counts as
        preempt_recompute — recovery work, not first-pass prefill."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            start = t.marks.pop("prefill_start", None)
            if start is None:
                return
            phase = ("preempt_recompute" if "preempt_start" in t.marks
                     else "prefill")
            t.add_span(phase, "prefill" if phase == "prefill"
                       else "recompute-prefill", start, ts - start)

    def note_handoff(self, rid: int, detach_ts: float,
                     ts: float | None = None, blocks: int = 0,
                     nbytes: int = 0, shared: int = 0) -> None:
        """Detach → remap/copy → adopt, measured from the payload's
        ``created_ts``. Opens the decode segment on the adopting
        tier."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            t.add_span("handoff", f"{blocks} blocks"
                       + (f" ({shared} shared)" if shared else ""),
                       detach_ts, ts - detach_ts, {"bytes": nbytes})

    def note_decode_start(self, rid: int,
                          ts: float | None = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            t.marks.setdefault("decode_start", ts)

    def note_preempt(self, rid: int, ts: float | None = None,
                     reason: str = "capacity") -> None:
        """Preemption boundary: closes the open decode segment, opens
        the preempt_recompute segment. Called from the scheduler's
        victim path and the prefill-victim requeue — NEVER sampled;
        a preemption-storm request's attribution must survive."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            start = t.marks.pop("decode_start", None)
            if start is not None:
                t.add_span("decode", "segment", start, ts - start)
            t.marks["preempt_start"] = ts
            t.add_span("preempt_recompute", f"preempted ({reason})",
                       ts, 0.0, accumulate=False)

    def note_resume(self, rid: int, ts: float | None = None) -> None:
        """Recompute replay finished and the sequence is back in
        decode: closes preempt_recompute, reopens the decode
        segment."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            start = t.marks.pop("preempt_start", None)
            if start is not None:
                t.add_span("preempt_recompute", "resumed", start,
                           ts - start)
            t.marks["decode_start"] = ts

    def note_spec_window(self, rid: int, window: int, accepted: int,
                         drafted: int) -> None:
        """One speculation window's acceptance (decode-phase detail;
        the window's wall already accumulates through the decode
        segment)."""
        with self._lock:
            t = self._live.get(rid)
            if t is None:
                return
            t.add_span("decode", f"spec[{window}] +{accepted}/{drafted}",
                       time.time(), 0.0,
                       {"accepted": accepted, "drafted": drafted},
                       accumulate=False)

    def note_done(self, rid: int, ts: float | None = None) -> None:
        """Completion: closes any open segment, classifies the
        dominant phase, feeds grove_request_phase_seconds{phase}, and
        retires the trace into the ring (and slowest-K if it
        qualifies)."""
        ts = time.time() if ts is None else ts
        with self._lock:
            t = self._live.pop(rid, None)
            if t is None:
                return
            start = t.marks.pop("decode_start", None)
            if start is not None:
                t.add_span("decode", "segment", start, ts - start)
            start = t.marks.pop("preempt_start", None)
            if start is not None:
                # Died while preempted (evicted/truncated): the wait
                # still attributes as recovery time.
                t.add_span("preempt_recompute", "unresolved", start,
                           ts - start)
            start = t.marks.pop("prefill_start", None)
            if start is not None:
                t.add_span("prefill", "prefill (at completion)",
                           start, ts - start)
            t.done_ts = ts
            t.e2e_s = max(0.0, ts - t.created_ts)
            t.dominant = t.classify()
            self.finished_total += 1
            if len(self._ring) == self._ring.maxlen:
                self._drop()
            self._ring.append(t)
            self._retain_slowest(t)
        for phase, secs in t.phase_seconds.items():
            self._metrics.observe("grove_request_phase_seconds", secs,
                                  phase=phase)

    def _retain_slowest(self, t: RequestTrace) -> None:
        s = self._slowest
        s.append(t)
        s.sort(key=lambda x: -x.e2e_s)
        del s[self.slowest_k:]

    # ---- disagg seam: the trace rides the HandoffPayload ----

    def live_trace(self, rid: int) -> RequestTrace | None:
        with self._lock:
            return self._live.get(rid)

    def adopt_trace(self, trace: RequestTrace | None) -> None:
        """Adopt a trace carried on a HandoffPayload. With the shared
        disagg recorder this is a no-op (the rid is already live);
        with per-tier recorders it splices the producer's spans into
        this tier's live set so the timeline stays one trace."""
        if trace is None:
            return
        with self._lock:
            if trace.rid in self._live:
                return
            if len(self._live) >= self.live_cap:
                self._drop()
                return
            self._live[trace.rid] = trace

    # ---- read surface ----

    def phase_stats(self) -> dict[str, dict]:
        """Per-phase stats over finished traces (ring ∪ slowest-K):
        count, total seconds, p99 ms, dominated count. Computed at
        read time — the record path stays append-only — and cached
        per completion count, so the engine's per-completion telemetry
        rider costs a dict lookup when nothing finished since."""
        key = (self.finished_total, self.dropped)
        if self._phase_cache[0] == key:
            return self._phase_cache[1]
        acc: dict[str, dict] = {}
        for t in self._finished():
            for phase, secs in t.phase_seconds.items():
                d = acc.setdefault(phase, {"count": 0, "total_s": 0.0,
                                           "dominant": 0, "_vals": []})
                d["count"] += 1
                d["total_s"] += secs
                d["_vals"].append(secs)
                if t.dominant == phase:
                    d["dominant"] += 1
        for d in acc.values():
            vals = sorted(d.pop("_vals"))
            d["total_s"] = round(d["total_s"], 6)
            d["p50_ms"] = round(vals[len(vals) // 2] * 1e3, 3)
            d["p99_ms"] = round(
                vals[min(len(vals) - 1, int(len(vals) * 0.99))] * 1e3, 3)
        self._phase_cache = (key, acc)
        return acc

    def _finished(self) -> list[RequestTrace]:
        with self._lock:
            seen: dict[int, RequestTrace] = {t.rid: t for t in self._ring}
            for t in self._slowest:
                seen.setdefault(t.rid, t)
            return list(seen.values())

    def find(self, rid: int) -> dict | None:
        """Resolve one rid to its trace dict — slowest-K first (the
        exemplar path), then the ring, then live in-flight traces."""
        with self._lock:
            for t in self._slowest:
                if t.rid == rid:
                    return t.to_dict()
            for t in reversed(self._ring):
                if t.rid == rid:
                    return t.to_dict()
            t = self._live.get(rid)
            return t.to_dict() if t is not None else None

    def payload(self) -> dict:
        """The /debug/requests payload (one shape for both client
        twins; ``render_request_trace`` and grovectl render it)."""
        with self._lock:
            traces = [t.to_dict() for t in self._ring]
            slowest = [t.to_dict() for t in self._slowest]
            live = len(self._live)
        return {
            "scope": {"namespace": self.namespace, "name": self.name},
            "sample_every": self.sample_every,
            "ring": {"len": len(traces), "capacity": self.capacity,
                     "finished_total": self.finished_total},
            "live": live,
            "dropped": self.dropped,
            "phases": self.phase_stats(),
            "slowest": slowest,
            "traces": traces,
        }


# ---- per-process recorder registry (the debug_requests surface) ----

_REGISTRY: "collections.OrderedDict[tuple[str, str], weakref.ref]" = \
    collections.OrderedDict()
_REGISTRY_CAPACITY = 64
_registry_lock = threading.Lock()
_auto_seq = [0]


def _next_auto_name() -> str:
    with _registry_lock:
        _auto_seq[0] += 1
        return f"engine-{_auto_seq[0]}"


def register(rec: RequestObservatory, name: str | None = None,
             namespace: str | None = None) -> None:
    """(Re)register a recorder under a scope. Engines auto-register as
    default/engine-N at construction; serving wrappers re-register
    under the control-plane scope name, so ``grovectl request-trace
    --name <name>`` finds it. Weakly held and LRU-capped, exactly the
    xprof registry shape."""
    if name is not None:
        rec.name = name
    if namespace is not None:
        rec.namespace = namespace
    key = (rec.namespace, rec.name)
    with _registry_lock:
        _REGISTRY.pop(key, None)
        _REGISTRY[key] = weakref.ref(rec)
        while len(_REGISTRY) > _REGISTRY_CAPACITY:
            _REGISTRY.popitem(last=False)


def recorder_for(name: str, namespace: str = "default",
                 ) -> RequestObservatory | None:
    with _registry_lock:
        ref = _REGISTRY.get((namespace, name))
        rec = ref() if ref is not None else None
        if ref is not None and rec is None:
            del _REGISTRY[(namespace, name)]
        return rec


def scopes() -> list[tuple[str, str]]:
    with _registry_lock:
        return [k for k, ref in _REGISTRY.items() if ref() is not None]


# ---- rendering (grovectl request-trace) ----

def render_request_trace(payload: dict, rid: int) -> list[str]:
    """Human rendering of one request's trace out of a
    /debug/requests payload: phase attribution (dominant starred),
    then the span timeline."""
    trace = None
    for t in (payload.get("slowest") or []) + (payload.get("traces")
                                               or []):
        if t.get("rid") == rid:
            trace = t
            break
    scope = payload.get("scope") or {}
    out = [f"engine:    {scope.get('namespace', '?')}/"
           f"{scope.get('name', '?')}"]
    if trace is None:
        out.append(f"request {rid}: no trace retained (ring "
                   f"{(payload.get('ring') or {}).get('len', 0)}/"
                   f"{(payload.get('ring') or {}).get('capacity', 0)}, "
                   f"dropped {payload.get('dropped', 0)})")
        return out
    state = "done" if trace.get("done") else "in flight"
    out.append(f"request:   rid {rid}  ({state}, "
               f"e2e {trace.get('e2e_s', 0.0) * 1e3:.1f} ms)")
    dominant = trace.get("dominant")
    phases = trace.get("phases") or {}
    if phases:
        out.append("")
        out.append(f"  {'phase':<19}{'seconds':>10}{'frac':>8}")
        total = sum(phases.values()) or 1.0
        for name in sorted(phases, key=lambda p: -phases[p]):
            star = " *" if name == dominant else ""
            out.append(f"  {name:<19}{phases[name]:>10.4f}"
                       f"{phases[name] / total * 100:>7.1f}%{star}")
    spans = trace.get("spans") or []
    if spans:
        out.append("")
        out.append(f"  {'+ms':>9}  {'dur ms':>9}  "
                   f"{'phase':<19}label")
        for s in spans:
            star = " *" if s.get("phase") == dominant else ""
            out.append(f"  {s.get('t0_off_ms', 0.0):>9.1f}  "
                       f"{s.get('ms', 0.0):>9.2f}  "
                       f"{s.get('phase', '?'):<19}"
                       f"{s.get('label', '')}{star}")
    if trace.get("dropped_spans"):
        out.append(f"  ({trace['dropped_spans']} spans dropped at "
                   f"cap {SPAN_CAP})")
    return out
