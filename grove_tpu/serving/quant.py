"""Weight-only int8 quantization for the serving path.

Decode at small batch is HBM-bandwidth-bound and the weight read
dominates (bench roofline: params_bytes/batch ≫ KV bytes), so storing
matmul weights as int8 with per-output-channel bf16 scales nearly
halves the bytes the hot loop moves — the standard TPU serving
configuration (weight-only, symmetric, per-channel: accuracy-neutral in
practice, and XLA fuses the int8→bf16 upcast + scale into the matmul's
operand read so HBM sees only int8).

``QTensor`` is a registered pytree: quantized leaves ride ``device_put``,
``lax.scan`` over stacked layers (the leading L axis slices q and scale
together), and jit boundaries like plain arrays. The model consumes them
through ``llama._w`` (materialize-on-read); norms and rope tables stay
bf16. The KV cache has its own int8 mode (``GROVE_KV_QUANT=int8``,
per-slot-per-head scales — serving/kvcache.py); every byte estimate that
mentions KV — the bench roofline note above, xprof's
``decode_hbm_bytes_per_token``, ``grove_hbm_bytes`` — derives from
``kv_bytes_per_token_per_layer`` below so the numbers cannot drift apart
when quantization flips on.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    q: jnp.ndarray       # int8, same shape as the original weight
    scale: jnp.ndarray   # bf16, broadcastable (contracted axes kept as 1)

    @property
    def shape(self):
        return self.q.shape

    def materialize(self) -> jnp.ndarray:
        return self.q.astype(self.scale.dtype) * self.scale


# Parameter leaf -> axes CONTRACTED by its matmul (scale must be
# per-output-channel, i.e. reduced over exactly these axes). Leading L
# stacking axis included where present.
_CONTRACT_AXES: dict[str, tuple[int, ...]] = {
    "wq": (1,), "wk": (1,), "wv": (1,),        # [L, d, h, hd] @ d
    "wo": (1, 2),                              # [L, h, hd, d] @ (h, hd)
    "w_gate": (1,), "w_up": (1,),              # [L, d, ff]    @ d
    "w_down": (1,),                            # [L, ff, d]    @ ff
    "lm_head": (0,),                           # [d, v]        @ d
    "tok_embed": (1,),                         # [v, d] gather: per-row
}


def quantize_tensor(w: jnp.ndarray, axes: tuple[int, ...]) -> QTensor:
    """Symmetric per-channel int8: scale = amax/127 over ``axes``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q=q.astype(jnp.int8), scale=scale.astype(jnp.bfloat16))


def quantize_params(params: Any) -> Any:
    """Quantize every known matmul leaf of a Llama param tree; norms and
    unknown leaves (e.g. MoE experts) pass through untouched."""
    def leaf(path, w):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        axes = _CONTRACT_AXES.get(name)
        if axes is None:
            return w
        return quantize_tensor(w, axes)
    return jax.tree_util.tree_map_with_path(leaf, params)


def kv_bytes_per_token_per_layer(cfg: Any, kv_quant: str = "off") -> int:
    """Bytes one token's K+V occupy in one layer of the serving cache.

    THE shared derivation for every KV byte estimate (bench rows,
    xprof's HBM roofline, ``grove_hbm_bytes``): int8 KV stores one
    int8 per element plus one f32 scale per (slot, head) for each of
    K and V (serving/kvcache.py's scale layout)."""
    if kv_quant == "int8":
        return 2 * cfg.n_kv_heads * (cfg.head_dim + 4)
    assert kv_quant == "off", f"unknown KV quant mode {kv_quant!r}"
    return 2 * cfg.n_kv_heads * cfg.head_dim * jnp.dtype(cfg.dtype).itemsize


def kv_block_bytes(cfg: Any, block_size: int, kv_quant: str = "off") -> int:
    """Device bytes of one K+V block pair across all layers — what one
    allocator grant costs in HBM (the unit behind kv_headroom and the
    bench's blocks-per-byte-budget sizing)."""
    return cfg.n_layers * block_size * kv_bytes_per_token_per_layer(
        cfg, kv_quant)


def params_bytes(params: Any) -> int:
    """Actual bytes of a (possibly quantized) param tree — the number the
    bench's HBM roofline must use once weights are int8."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
