"""KV block handoff protocol for disaggregated prefill→decode serving.

The tentpole seam of GROVE_DISAGG=1 (docs/design/
disaggregated-serving.md): a ``PrefillEngine`` runs chunked prefill to
completion against its OWN block pool, then streams the finished
sequence to the ``PagedDecodeEngine`` as a ``HandoffPayload`` — the
request, its tokens, the source block ids in table order, the prefill
position, and the sampler state (the first sampled token). Because both
pools are block-granular with per-request tables, adoption is a
block-id REMAP plus a per-block pool copy (same process: one jitted
device copy per block; cross-engine: the identical payload rides an
ICI/DCN transfer) — never a buffer reshape.

Ownership rules (the refcount contract the soak tests pin):

- The payload OWNS one reference per source block from detach until
  ``release()``. The producing engine's scheduler detaches the
  sequence without freeing (``detach_prefill_head``), so a payload in
  flight keeps its blocks live in the SOURCE allocator.
- The consumer adopts FRESH blocks from its own allocator
  (``BlockAllocator.adopt``) and copies payloads across pools; source
  block ids never enter the destination allocator (a foreign free
  raises there by construction).
- ``release()`` is idempotent and is the ONLY path that drops the
  source references. The producer registered the prompt's full blocks
  into its prefix tree at detach time, so the unref parks them in the
  source's cached LRU pool — the source side keeps its warm prefix
  (matched prefix blocks never move — a repeat prompt prefills only
  its cold suffix).
- If the producer dies mid-handoff, un-released payloads die with its
  allocator (chaos: prefill-replica-kill); the decode side holds no
  reference to anything of the producer's, so its allocator stays
  clean and the request simply re-prefills.

Composition: int8 KV blocks transfer as-is — the copy moves the int8
payload AND the per-slot scale rows, no requantize (both engines must
run the same kv_quant mode; the facade asserts it). The decode side's
prefix cache still matches adopted tokens, so a warm decode-side
prefix turns block copies into shared refs (only the cold suffix
transfers).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np


@dataclasses.dataclass
class HandoffPayload:
    """One finished prefill, in flight between engines.

    ``tokens`` is the full prefill input (the prompt, or prompt +
    generated for a recompute replay) — exactly what the decode side
    needs for prefix matching and later preemption recompute.
    ``first_token`` is the materialized sampler state: the token the
    producing chunk sampled, already appended to ``req.generated`` and
    TTFT-stamped by the producer.
    """

    rid: int
    req: object                     # serving.engine.Request
    tokens: np.ndarray              # int32 [pos] — prefill input
    first_token: int                # sampler state (last sampled token)
    blocks: list[int]               # SOURCE block ids, table order
    pos: int                        # tokens written to the source pool
    n_generated: int
    recompute: bool
    source: object                  # producing PrefillEngine
    block_bytes: int                # bytes one block moves (quant-aware)
    # The request's live trace (serving/reqtrace.RequestTrace | None):
    # carried across the seam so ONE trace spans both tiers — the
    # adopting engine splices it via ``RequestObservatory.adopt_trace``
    # (a no-op under the shared disagg recorder, a real splice with
    # per-tier recorders). None when tracing is off.
    trace: object = None
    created_ts: float = dataclasses.field(default_factory=time.time)
    _released: bool = dataclasses.field(default=False, repr=False)

    @property
    def nbytes(self) -> int:
        """Transfer bytes this payload represents (K + V + scales for
        every block) — the figure the bench cross-checks against the
        live pool's nbytes."""
        return len(self.blocks) * self.block_bytes

    def release(self) -> None:
        """Drop the payload's source-side block references (idempotent).
        The producer registered the prompt's full blocks at detach, so
        the unref parks them in the source's cached LRU pool instead of
        freeing — the producer keeps its warm prefix across handoffs."""
        if self._released:
            return
        self._released = True
        self.source._release_handoff(self)
