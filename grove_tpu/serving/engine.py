"""Serving engines: continuous-batching decode + disaggregated prefill.

The workload half of the framework: what runs inside the pods that the
control plane gang-schedules. The reference operator runs third-party
engines (vLLM/SGLang — README.md:35-41); here the engine is first-party
and TPU-shaped:

- fixed decode batch lanes (static shapes; one compiled decode step),
- prefill and decode as separate jitted programs so they can live in
  separate pods (disaggregated serving): ``PrefillWorker`` returns the
  per-sequence KV slab; ``DecodeEngine.insert`` splices it into a free
  lane (the KV-transfer seam — over ICI/DCN in multi-host deployments),
- donated cache buffers (no per-step reallocation),
- a queue-depth metric hook feeding the control plane's autoscaler.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.models import llama
from grove_tpu.models.llama import LlamaConfig
from grove_tpu.ops.kvcache import KVCache
from grove_tpu.serving.handoff import HandoffPayload
from grove_tpu.serving.kvcache import (NULL_BLOCK, PagedKV, BlockAllocator,
                                       PrefixTree, SeqBlocks, pad_tables)
from grove_tpu.serving.schedule import PagedScheduler, PagedSeq, pick_bucket


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Token sampling: temperature 0 = greedy argmax; otherwise
    temperature-scaled categorical over the top_k logits (0 = full
    vocab). Compiled into the decode step (static branch)."""

    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0


def sample_tokens(logits: jnp.ndarray, key: jax.Array,
                  cfg: SamplerConfig) -> jnp.ndarray:
    """logits [b, vocab] -> tokens [b] per the sampler config."""
    if cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / cfg.temperature
    if cfg.top_k > 0 and cfg.top_k < scaled.shape[-1]:
        kth = jax.lax.top_k(scaled, cfg.top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # [s] int32 (may be right-padded)
    max_new_tokens: int = 32
    generated: list[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # True prompt length (≠ len(prompt) for padded rows). Lets the engine
    # do all cache-capacity math on the host: after g generated tokens
    # the lane's next write lands at prompt_len + g - 1.
    prompt_len: int = -1
    # SLO stamps (serving/slo.py): host wall-clock, 0.0 = never reached.
    # enqueue/admit/first-token are exact; done is observed at window
    # drain, so it can trail the true completion by interval-1 steps.
    enqueue_ts: float = 0.0
    admit_ts: float = 0.0
    first_token_ts: float = 0.0
    done_ts: float = 0.0
    # Prompt tokens served from the prefix cache at first admission
    # (0 = cold). The bench surfaces segment warm/cold TTFT on this.
    cached_tokens: int = 0

    def __post_init__(self):
        if self.prompt_len < 0:
            self.prompt_len = len(self.prompt)


@dataclasses.dataclass
class PrefillResult:
    """Everything decode needs to continue a sequence: the KV slab and the
    first sampled token (the disaggregation transfer payload)."""

    k: jnp.ndarray        # [layers, s_pad, n_kv, d]
    v: jnp.ndarray        # [layers, s_pad, n_kv, d]
    length: int
    next_token: int


def _stamp_admit_impl(req: Request, now: float, admit: float | None,
                      compat: bool, telemetry) -> None:
    """Shared admission-stamp semantics for both engines (lanes and
    paged): ``now`` is when the first token existed, ``admit`` when the
    request left the queue. Compat mode (GROVE_TTFT_COMPAT=1) fuses
    them back to the historical single stamp. The prefill-sampled
    token is counted here so the drain only accounts decode tokens."""
    if compat or admit is None or admit > now:
        admit = now
    req.admit_ts = admit
    if not req.enqueue_ts:
        req.enqueue_ts = admit
    req.first_token_ts = now
    if telemetry is not None:
        telemetry.add_tokens(1)


def _complete_impl(req: Request, completed: list, telemetry) -> None:
    """Shared completion bookkeeping: stamp done, record, fold into
    the telemetry."""
    req.done = True
    req.done_ts = time.time()
    completed.append(req)
    if telemetry is not None:
        telemetry.observe_request(req)


class PrefillWorker:
    """The prefill side of disaggregated serving (chips optimised for
    throughput over long prompts)."""

    def __init__(self, cfg: LlamaConfig, params, batch: int = 1,
                 max_prompt: int | None = None,
                 sampler: SamplerConfig | None = None,
                 quant: str | None = None,
                 prefill_chunk: int | None = None):
        self.cfg = cfg
        self.params = params
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        if quant == "int8":
            from grove_tpu.serving.quant import quantize_params
            self.params = quantize_params(self.params)
        self.batch = batch
        self.max_prompt = max_prompt or cfg.max_seq_len
        self.sampler = sampler or SamplerConfig()
        self._rng = jax.random.PRNGKey(self.sampler.seed)
        # Chunked prefill (llama.prefill_chunked): bounds the attention
        # working set for long prompts — the prefill worker's whole job
        # is long prompts, so this is its natural posture. One-shot stays
        # the default (single executable, exact ragged-lengths logits).
        if prefill_chunk:
            assert self.max_prompt % prefill_chunk == 0, \
                (self.max_prompt, prefill_chunk)
        self.prefill_chunk = prefill_chunk

        def run(params, tokens, lengths, cache):
            return llama.prefill(cfg, params, tokens, cache, lengths)

        self._prefill = jax.jit(run, donate_argnums=(3,))
        self._cache = KVCache.create(cfg.n_layers, batch, self.max_prompt,
                                     cfg.n_kv_heads, cfg.head_dim, cfg.dtype)

    def prefill(self, prompts: list[np.ndarray]) -> list[PrefillResult]:
        """Prefill up to ``batch`` prompts (right-padded to one length)."""
        assert 0 < len(prompts) <= self.batch
        s_pad = self.max_prompt
        toks = np.zeros((self.batch, s_pad), np.int32)
        lengths = np.zeros((self.batch,), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
            lengths[i] = len(p)
        if self.prefill_chunk:
            logits, cache = llama.prefill_chunked(
                self.cfg, self.params, jnp.asarray(toks), self._cache,
                chunk=self.prefill_chunk, lengths=jnp.asarray(lengths))
        else:
            logits, cache = self._prefill(self.params, jnp.asarray(toks),
                                          jnp.asarray(lengths), self._cache)
        self._cache = cache
        if self.sampler.temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            next_tokens = np.asarray(sample_tokens(logits, sub, self.sampler))
        else:
            next_tokens = np.asarray(jnp.argmax(logits, axis=-1))
        out = []
        for i in range(len(prompts)):
            out.append(PrefillResult(
                k=cache.k[:, i], v=cache.v[:, i],
                length=int(lengths[i]), next_token=int(next_tokens[i])))
        return out


class DecodeEngine:
    """Continuous-batching decode over fixed lanes.

    Two operating modes:
    - standalone: ``admit_prompts`` prefills in-engine (single-pod serving,
      also the bench path);
    - disaggregated: ``insert`` splices a PrefillResult produced elsewhere.
    """

    def __init__(self, cfg: LlamaConfig, key_or_params, batch: int = 8,
                 max_len: int | None = None,
                 metric_hook: Callable[[int], None] | None = None,
                 host_sync_interval: int = 8,
                 sampler: SamplerConfig | None = None,
                 quant: str | None = None,
                 telemetry=None,
                 xprof=None,
                 reqtrace=None):
        self.cfg = cfg
        # Init-only: the sampled step closes over this config at compile
        # time, so later mutation cannot take effect (and is rejected).
        self._sampler = sampler or SamplerConfig()
        if isinstance(key_or_params, jax.Array) and key_or_params.dtype == jnp.uint32:
            self.params = llama.init_params(cfg, key_or_params)
        else:
            self.params = key_or_params
        # Weight-only int8 (serving/quant.py): decode is HBM-bound on the
        # weight read, so this is ~the bandwidth win it looks like.
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        self.quant = quant
        if quant == "int8":
            from grove_tpu.serving.quant import quantize_params
            self.params = quantize_params(self.params)
        self.batch = batch
        self.max_len = max_len or cfg.max_seq_len
        self.metric_hook = metric_hook
        # Optional serving/slo.EngineTelemetry: request-lifecycle stamps
        # and latency histograms, all host-side (None = zero overhead;
        # the JIT path is identical either way).
        self.telemetry = telemetry
        # Completion bookkeeping needs sampled tokens on the host; fetching
        # every step would serialise dispatch behind a device→host sync.
        # Tokens accumulate on device and drain every ``host_sync_interval``
        # steps (a finished lane decodes at most interval-1 wasted steps).
        self.host_sync_interval = max(1, host_sync_interval)
        self.cache = KVCache.create(cfg.n_layers, batch, self.max_len,
                                    cfg.n_kv_heads, cfg.head_dim, cfg.dtype)
        self._tokens = jnp.zeros((batch,), jnp.int32)
        self._active = np.zeros((batch,), bool)
        self._requests: list[Request | None] = [None] * batch
        self._queue: deque[Request] = deque()
        self._pending_tokens: list[jnp.ndarray] = []
        # Steps already pending when a lane was (re)admitted: tokens from
        # before the admission belong to the previous occupant, not the
        # new request.
        self._lane_window_start = np.zeros((batch,), np.int32)
        self._next_rid = 0
        self.completed: list[Request] = []
        self.steps = 0

        sampler_cfg = self._sampler
        self._sampling = sampler_cfg.temperature > 0.0
        self._rng = jax.random.PRNGKey(sampler_cfg.seed)

        def step_greedy(params, tokens, cache):
            logits, cache = llama.decode_step(cfg, params, tokens, cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        def step_sampled(params, tokens, cache, key):
            logits, cache = llama.decode_step(cfg, params, tokens, cache)
            key, sub = jax.random.split(key)
            return sample_tokens(logits, sub, sampler_cfg), cache, key

        # The greedy 3-ary step stays the public compiled surface
        # (benchmarks, raw loops); sampling engines use the key-threaded
        # variant internally and only compile it when actually sampling.
        self._step = jax.jit(step_greedy, donate_argnums=(2,))
        self._step_sampled = jax.jit(step_sampled, donate_argnums=(2,))

        # Block decode: host_sync_interval steps fused into ONE executable
        # via lax.scan, window tokens [K, b] stacked on device. One
        # dispatch + one async fetch per window instead of K dispatches —
        # the difference between dispatch-bound and HBM-bound decode on
        # high-latency transports (the tunnelled PJRT relay most of all).
        K = self.host_sync_interval

        def block_greedy(params, tokens, cache):
            def body(carry, _):
                t, c = carry
                nt, c = step_greedy(params, t, c)
                return (nt, c), nt
            (t, c), window = jax.lax.scan(body, (tokens, cache), None,
                                          length=K)
            return t, c, window

        def block_sampled(params, tokens, cache, key):
            def body(carry, _):
                t, c, k = carry
                nt, c, k = step_sampled(params, t, c, k)
                return (nt, c, k), nt
            (t, c, key), window = jax.lax.scan(body, (tokens, cache, key),
                                               None, length=K)
            return t, c, window, key

        self._step_block = jax.jit(block_greedy, donate_argnums=(2,))
        self._step_block_sampled = jax.jit(block_sampled, donate_argnums=(2,))

        def pf(params, tokens, lengths, cache):
            return llama.prefill(cfg, params, tokens, cache, lengths)

        self._prefill = jax.jit(pf, donate_argnums=(3,))

        # TTFT stamp semantics: by default admit_ts is queue-exit
        # (pre-prefill) and first_token_ts is prefill completion — the
        # split the flight recorder's direct prefill timing enables.
        # GROVE_TTFT_COMPAT=1 restores the historical fused stamp
        # (admit == first-token, both post-prefill).
        self._ttft_compat = os.environ.get("GROVE_TTFT_COMPAT", "0") == "1"

        # Data-plane observatory (serving/xprof.py): compile tracking
        # on the jitted callables, sampled device timings, memory
        # gauges — all host-side. ``xprof`` may be an Observatory (the
        # caller names the scope), None (auto-create unless
        # GROVE_XPROF=0), or False (explicitly off). With the
        # observatory off, every attribute below stays the raw jit and
        # the hot path is exactly the pre-observatory shape.
        self.xprof = None
        if xprof is not False:
            from grove_tpu.serving import xprof as xprof_mod
            if xprof is not None:
                self.xprof = xprof
                self.xprof.cfg = cfg
                self.xprof.batch = batch
                self.xprof.max_len = self.max_len
            elif xprof_mod.enabled():
                self.xprof = xprof_mod.Observatory(
                    cfg=cfg, batch=batch, max_len=self.max_len)
        if self.xprof is not None:
            wrap = self.xprof.compile.wrap
            self._prefill = wrap("prefill", self._prefill)
            self._step = wrap("step", self._step)
            self._step_sampled = wrap("step_sampled", self._step_sampled)
            self._step_block = wrap("step_block", self._step_block)
            self._step_block_sampled = wrap("step_block_sampled",
                                            self._step_block_sampled)

        # Request observatory (serving/reqtrace.py): bounded per-request
        # span recorder stamping lifecycle seams the engine already
        # crosses — nothing on the JIT path. Same contract as xprof:
        # a RequestObservatory (caller names the scope), None
        # (auto-create unless GROVE_REQTRACE=0), or False (explicitly
        # off). Off means self.reqtrace is None and every stamp site
        # short-circuits on the None check — the prior hot path exactly.
        self.reqtrace = None
        if reqtrace is not False:
            from grove_tpu.serving import reqtrace as reqtrace_mod
            if reqtrace is not None:
                self.reqtrace = reqtrace
            elif reqtrace_mod.enabled():
                self.reqtrace = reqtrace_mod.RequestObservatory()

    @property
    def sampler(self) -> SamplerConfig:
        return self._sampler

    # ---- compiled-callable access (benchmarks, custom loops) ----

    def compiled_prefill(self):
        """The jitted prefill: (params, tokens[b,s], lengths[b], cache) ->
        (last-token logits [b, vocab], cache). Stable public surface for
        callers that drive the compiled programs without lane bookkeeping."""
        return self._prefill

    def compiled_step(self):
        """The jitted decode step: (params, tokens[b], cache) ->
        (next tokens [b], cache). Cache argument is donated."""
        return self._step

    def compiled_step_block(self):
        """The jitted K-step decode block (K = host_sync_interval):
        (params, tokens[b], cache) -> (tokens[b], cache, window[K, b]).
        One dispatch decodes K tokens per lane; cache is donated."""
        return self._step_block, self.host_sync_interval

    # ---- request intake ----

    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32) -> int:
        req = Request(rid=self._next_rid, prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=max_new_tokens,
                      enqueue_ts=time.time())
        self._next_rid += 1
        self._queue.append(req)
        rt = self.reqtrace
        if rt is not None:
            rt.note_enqueue(req.rid, ts=req.enqueue_ts,
                            prompt_len=len(req.prompt),
                            max_new_tokens=max_new_tokens)
        self._report_metric()
        return req.rid

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def kv_lane_utilization(self) -> float:
        """Fraction of decode lanes occupied — the KV-headroom signal
        (1.0 = no free lane to admit into)."""
        return float(np.count_nonzero(self._active)) / self.batch

    def _report_metric(self) -> None:
        if self.metric_hook is not None:
            self.metric_hook(len(self._queue))
        if self.telemetry is not None:
            self.telemetry.sample_gauges(len(self._queue),
                                         self.kv_lane_utilization)
            if self.reqtrace is not None \
                    and self.reqtrace.finished_total:
                self.telemetry.sample_phases(
                    self.reqtrace.phase_stats())
        if self.xprof is not None:
            self.xprof.observe_memory(self, self.telemetry)

    def _stamp_admit(self, req: Request, now: float,
                     admit: float | None = None) -> None:
        """Admission stamps. ``now`` is when the first token existed
        (the prefill's sampled token, post-prefill); ``admit`` is when
        the request left the queue (pre-prefill). Historically one
        stamp covered both, which conflated queue-exit with prefill
        completion in the queue-wait histogram — the flight recorder
        times prefill directly now, so the stamps split.
        GROVE_TTFT_COMPAT=1 (or a path with no queue-exit time) fuses
        them back to the old derivation. A request that never went
        through submit() gets enqueue = admit: zero queue wait. Both
        admission paths append the prefill token right after stamping,
        so it is counted here — the drain only sees decode tokens."""
        _stamp_admit_impl(req, now, admit, self._ttft_compat,
                          self.telemetry)
        rt = self.reqtrace
        if rt is not None:
            rt.note_admit(req.rid, ts=req.admit_ts)

    def _complete(self, req: Request) -> None:
        """Shared completion bookkeeping (window drain + lane retire):
        stamp done, record, and fold the request into the telemetry."""
        _complete_impl(req, self.completed, self.telemetry)
        rt = self.reqtrace
        if rt is not None:
            rt.note_done(req.rid, ts=req.done_ts)

    # ---- standalone mode (bench path) ----

    def admit_prompts(self, prompts: jnp.ndarray,
                      max_new_tokens: int | None = None,
                      lengths: jnp.ndarray | None = None) -> None:
        """Prefill a full batch [batch, s] into the lanes.

        ``lengths`` [batch] gives true per-lane prompt lengths for ragged
        (right-padded) batches; defaults to s for all lanes. With
        ``max_new_tokens`` each lane gets a tracked Request, so the full
        completion bookkeeping runs (the real serving path); without it,
        lanes decode untracked (raw-throughput loops).
        """
        b, s = prompts.shape
        assert b == self.batch
        if lengths is None:
            lengths = jnp.full((b,), s, jnp.int32)
        else:
            lengths = jnp.asarray(lengths, jnp.int32)
        x = self.xprof
        admit_wall = time.time()  # queue-exit: prefill not yet started
        if x is not None:
            t0 = time.perf_counter()
        logits, self.cache = self._prefill(self.params, prompts, lengths,
                                           self.cache)
        if self._sampling:
            self._rng, sub = jax.random.split(self._rng)
            self._tokens = sample_tokens(logits, sub, self._sampler)
        else:
            self._tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if x is not None:
            jax.block_until_ready(self._tokens)
            x.record("prefill", time.perf_counter() - t0, tokens=b)
        self._active[:] = True
        if max_new_tokens is not None:
            prompts_np = np.asarray(prompts)
            lengths_np = np.asarray(lengths)
            first = np.asarray(self._tokens)
            self._lane_window_start[:] = len(self._pending_tokens)
            now = time.time()
            for i in range(b):
                req = Request(rid=self._next_rid, prompt=prompts_np[i],
                              max_new_tokens=max_new_tokens,
                              prompt_len=int(lengths_np[i]))
                self._next_rid += 1
                self._requests[i] = req
                self._stamp_admit(req, now, admit=admit_wall)
                # Count the prefill-sampled token like insert() does —
                # both admission paths account tokens identically.
                req.generated.append(int(first[i]))
            self._report_metric()

    # ---- disaggregated mode ----

    def free_lanes(self) -> list[int]:
        return [i for i in range(self.batch) if not self._active[i]]

    def release_lane(self, lane: int,
                     zero_kv: bool = True) -> "Request | None":
        """Retire a lane's occupant and free the lane (public API for
        callers that drive lane turnover themselves — the disagg bench,
        an external router doing its own completion policy). The KV
        length is zeroed so the lane's next occupant starts from a
        clean cache row, exactly as completion bookkeeping does;
        ``zero_kv=False`` skips that device write for the retire-then-
        immediately-insert hand-off pattern, where insert() stamps the
        lane's length anyway. Returns the retired Request (marked done)
        or None for an untracked/empty lane. Idempotent on free lanes."""
        occupant = self._requests[lane]
        if occupant is not None:
            # Tokens this lane already decoded belong to the retiring
            # request: drain pending windows first, exactly as the
            # completion path does — otherwise up to interval-1 decoded
            # tokens would vanish from the returned Request.
            self._drain()
        req = self._requests[lane]  # the drain may have completed it
        if req is not None:
            self._complete(req)
            self._requests[lane] = None
        if self._active[lane]:
            self._active[lane] = False
            if zero_kv:
                lengths = self.cache.lengths.at[lane].set(0)
                self.cache = self.cache._replace(lengths=lengths)
            self._report_metric()
        return occupant

    def insert(self, lane: int, result: PrefillResult,
               request: Request | None = None) -> None:
        """Splice a prefilled sequence into a free lane (KV handoff)."""
        assert not self._active[lane], f"lane {lane} busy"
        s = result.k.shape[1]
        k = self.cache.k.at[:, lane, :s].set(result.k.astype(self.cache.k.dtype))
        v = self.cache.v.at[:, lane, :s].set(result.v.astype(self.cache.v.dtype))
        lengths = self.cache.lengths.at[lane].set(result.length)
        self.cache = KVCache(k=k, v=v, lengths=lengths)
        self._tokens = self._tokens.at[lane].set(result.next_token)
        self._active[lane] = True
        self._requests[lane] = request
        self._lane_window_start[lane] = len(self._pending_tokens)
        if request is not None:
            request.prompt_len = result.length
            # A request pre-stamped at queue-exit (admit_from_queue)
            # keeps that admit; bare inserts fuse admit = first-token.
            self._stamp_admit(request, time.time(),
                              admit=request.admit_ts or None)
            request.generated.append(result.next_token)
            rt = self.reqtrace
            if rt is not None:
                # Lane insert IS the prefill→decode splice: the worker's
                # prefill ran between queue-exit and here.
                rt.note_prefill_done(request.rid)
                rt.note_decode_start(request.rid)

    def admit_from_queue(self, prefiller: PrefillWorker) -> int:
        """Move queued requests through the prefiller into free lanes."""
        admitted = 0
        lanes = self.free_lanes()
        while lanes and self._queue:
            take = min(len(lanes), prefiller.batch, len(self._queue))
            popped = time.time()  # queue-exit, before the prefill runs
            reqs = [self._queue.popleft() for _ in range(take)]
            for r in reqs:
                r.admit_ts = popped
            x = self.xprof
            if x is not None:
                # The worker's jit is NOT one of this engine's wrapped
                # callables, so compile detection watches its cache
                # size directly — a grown cache means this wall was an
                # XLA build, recorded as a compile and kept out of the
                # device-time histogram.
                cache_size = getattr(getattr(prefiller, "_prefill", None),
                                     "_cache_size", None)
                before = cache_size() if cache_size is not None else -1
                t0 = time.perf_counter()
            results = prefiller.prefill([r.prompt for r in reqs])
            if x is not None:
                # prefill() fetches the sampled tokens to host, so the
                # wall here is completed device time, not dispatch.
                dt = time.perf_counter() - t0
                compiled = (cache_size is not None
                            and cache_size() != before)
                if compiled:
                    x.compile.note_external_compile("worker_prefill", dt)
                else:
                    x.recorder.record("prefill", dt, tokens=take)
            for req, res in zip(reqs, results):
                self.insert(lanes.pop(0), res, req)
                admitted += 1
        self._report_metric()
        return admitted

    # ---- decode ----

    def step(self) -> None:
        """One decode step across all lanes (inactive lanes compute too —
        static shapes beat per-lane control flow on TPU)."""
        x = self.xprof
        sampled = x is not None and x.should_sample()
        if sampled:
            # Drain the pending dispatch chain first, then time this
            # step with synced ends: the delta is device time for ONE
            # step, not queued backlog.
            jax.block_until_ready(self._tokens)
            t0 = time.perf_counter()
        if self._sampling:
            self._tokens, self.cache, self._rng = self._step_sampled(
                self.params, self._tokens, self.cache, self._rng)
        else:
            self._tokens, self.cache = self._step(self.params, self._tokens,
                                                  self.cache)
        if sampled:
            jax.block_until_ready(self._tokens)
            x.record("sample" if self._sampling else "step",
                     time.perf_counter() - t0, tokens=self.batch)
        self.steps += 1
        if any(r is not None for r in self._requests):
            self._pending_tokens.append(self._tokens)
            if len(self._pending_tokens) >= self.host_sync_interval:
                self._drain()

    def _fetch_windows(self, windows: list[jnp.ndarray]) -> np.ndarray:
        """Fetch accumulated block windows to host ([w, batch] rows).
        The once-per-window device→host sync lives HERE, outside the
        step loop's dispatch path — the host-sync-in-step-loop lint
        rule pins that split (docs/design/static-analysis.md)."""
        x = self.xprof
        if x is not None:
            t0 = time.perf_counter()
        toks = np.asarray(windows[0] if len(windows) == 1
                          else jnp.concatenate(windows, axis=0))
        if x is not None:
            x.record("host_transfer", time.perf_counter() - t0)
        return toks

    def _lane_has_room(self, req: Request, n: int) -> bool:
        """Host-side capacity check (no device fetch): after g generated
        tokens the lane's next write lands at prompt_len + g - 1, so n
        more steps fit iff that stays within max_len. write_row clamps
        silently past max_len — completing the lane a window early
        prevents the clamp from corrupting the cache tail."""
        return req.prompt_len + len(req.generated) - 1 + n <= self.max_len

    def _drain(self) -> None:
        """Process accumulated single-step tokens: one host fetch per
        window."""
        if not self._pending_tokens:
            return
        if self.xprof is not None:
            t0 = time.perf_counter()
        toks = np.asarray(jnp.stack(self._pending_tokens))  # [w, batch]
        if self.xprof is not None:
            self.xprof.record("host_transfer", time.perf_counter() - t0)
        self._pending_tokens.clear()
        self._process_window(toks, offsets=self._lane_window_start)
        self._lane_window_start[:] = 0

    def _process_window(self, toks: np.ndarray,
                        offsets: np.ndarray | None = None) -> None:
        """Completion bookkeeping over a [w, batch] token window.
        ``offsets[i]`` = rows belonging to lane i's previous occupant
        (single-step path; block windows never contain them)."""
        freed = False
        appended = 0
        for i, req in enumerate(self._requests):
            if req is None or not self._active[i]:
                continue
            start = int(offsets[i]) if offsets is not None else 0
            for t in toks[start:, i]:
                req.generated.append(int(t))
                appended += 1
                if len(req.generated) >= req.max_new_tokens:
                    break
            if len(req.generated) >= req.max_new_tokens or \
                    not self._lane_has_room(req, self.host_sync_interval):
                self._complete(req)
                self._requests[i] = None
                self._active[i] = False
                freed = True
                lengths = self.cache.lengths.at[i].set(0)
                self.cache = self.cache._replace(lengths=lengths)
        if self.telemetry is not None:
            self.telemetry.add_tokens(appended)
        if freed:
            self._report_metric()

    def sync(self) -> None:
        # Drain outstanding bookkeeping, then a tiny host fetch that
        # hard-syncs the dispatch chain (some remote PJRT transports
        # complete block_until_ready early).
        self._drain()
        np.asarray(self._tokens)

    def run(self, steps: int) -> None:
        """Decode ``steps`` steps with block dispatch (throughput mode):
        full windows go through the fused K-step executable — one
        dispatch per window, window tokens accumulating ON DEVICE — and
        bookkeeping drains with a single concatenated fetch at the end
        (on high-RTT transports every mid-run fetch would stall the
        dispatch chain for a round trip). The remainder decodes through
        single steps. Completion is therefore observed per ``run`` call,
        not per window: callers wanting tighter completion latency call
        ``step()`` (latency mode) or ``run`` in smaller chunks. Lane
        admission happens between calls, never inside one."""
        K = self.host_sync_interval
        self._drain()  # single-step leftovers use the offset bookkeeping
        tracked = any(r is not None for r in self._requests)
        if tracked:
            # Deferred bookkeeping can't free lanes mid-run, so cap the
            # block phase at the steps every tracked lane has room for;
            # the rest goes through the draining single-step path.
            safe = min((self.max_len - req.prompt_len
                        - len(req.generated) + 1
                        for req in self._requests if req is not None),
                       default=steps)
            block_steps = min(steps, max(0, safe))
        else:
            block_steps = steps
        steps -= (block_steps // K) * K
        windows: list[jnp.ndarray] = []
        x = self.xprof
        for _ in range(block_steps // K):
            sampled = x is not None and x.should_sample()
            if sampled:
                jax.block_until_ready(self._tokens)
                t0 = time.perf_counter()
            if self._sampling:
                self._tokens, self.cache, window, self._rng = \
                    self._step_block_sampled(self.params, self._tokens,
                                             self.cache, self._rng)
            else:
                self._tokens, self.cache, window = self._step_block(
                    self.params, self._tokens, self.cache)
            if sampled:
                jax.block_until_ready(self._tokens)
                x.record("sample" if self._sampling else "step",
                         time.perf_counter() - t0, steps=K,
                         tokens=K * self.batch)
            self.steps += K
            if tracked:
                windows.append(window)
        fetched = False
        if windows:
            # This fetch doubles as the hard sync for the block phase:
            # it waits on the last window's compute, and its final row
            # IS the current token state — no second round trip needed.
            self._process_window(self._fetch_windows(windows))
            fetched = True
        for _ in range(steps):
            self.step()
        if steps or not fetched:
            self.sync()


class PagedDecodeEngine:
    """Continuous-batching decode over a paged KV cache.

    The throughput rebuild of ``DecodeEngine`` (GROVE_ENGINE=paged —
    the default; ``lanes`` restores the seed engine):

    - **Paged KV** (serving/kvcache.py): fixed-size blocks + per-request
      block tables replace per-lane max-length buffers, so effective
      batch is bounded by tokens in flight, not worst-case length, and
      decode attention reads the BUCKETED live width instead of a
      max_len-wide padded row.
    - **Continuous batching** (serving/schedule.py): requests join and
      leave the decode batch at any step. Dispatch shapes come off
      fixed power-of-two bucket ladders — a finite executable set, so
      warmed steady state runs ZERO recompiles (pinned by
      tools/decode_smoke.py via the CompileTracker).
    - **Chunked prefill**: prompts advance one fixed chunk per engine
      tick, interleaved with decode, so a long prompt stalls TPOT for
      at most one chunk. The chunk executable takes a TRACED offset —
      one program per (chunk, width-bucket), reused at every window
      position.
    - **GSPMD execution**: every dispatch is ``jax.jit`` with
      ``NamedSharding`` in/out shardings over the ICI mesh
      (parallel/sharding.paged_step_shardings — the modern GSPMD
      pattern, not pmap). On a 1-chip CPU mesh the shardings collapse
      to no-ops; on a v5e slice the KV pool and attention heads shard
      over tp with XLA inserting the collectives. Same engine, both
      worlds.

    Host discipline: the per-step dispatch path performs NO device
    syncs (the host-sync-in-step-loop grovelint rule). Sampled tokens
    chain on device; bookkeeping drains once per ``host_sync_interval``
    window or at a composition change, whichever comes first.
    """

    def __init__(self, cfg: LlamaConfig, key_or_params, batch: int = 8,
                 max_len: int | None = None,
                 block_size: int | None = None,
                 num_blocks: int | None = None,
                 prefill_chunk: int | None = None,
                 metric_hook: Callable[[int], None] | None = None,
                 host_sync_interval: int = 8,
                 sampler: SamplerConfig | None = None,
                 quant: str | None = None,
                 telemetry=None,
                 xprof=None,
                 reqtrace=None,
                 mesh=None,
                 prefix_cache: bool | None = None,
                 spec_decode: bool | None = None,
                 spec_k: int | None = None,
                 draft_params=None,
                 kv_quant: str | None = None):
        self.cfg = cfg
        self._sampler = sampler or SamplerConfig()
        if isinstance(key_or_params, jax.Array) \
                and key_or_params.dtype == jnp.uint32:
            self.params = llama.init_params(cfg, key_or_params)
        else:
            self.params = key_or_params
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        self.quant = quant
        if quant == "int8":
            from grove_tpu.serving.quant import quantize_params
            self.params = quantize_params(self.params)
        self.batch = batch          # max decode slots
        self.max_len = max_len or cfg.max_seq_len
        self.metric_hook = metric_hook
        self.telemetry = telemetry
        self.host_sync_interval = max(1, host_sync_interval)
        self._ttft_compat = os.environ.get("GROVE_TTFT_COMPAT", "0") == "1"

        # Block geometry. Defaults: 16-token blocks (a v5e lane-friendly
        # granule; GROVE_PAGED_BLOCK overrides) and a pool sized to the
        # lanes engine's worst case (batch × max_len) so the DEFAULT
        # shape never regresses capacity — deployments shrink num_blocks
        # to bank the memory win.
        if block_size is None:
            block_size = int(os.environ.get("GROVE_PAGED_BLOCK", 16))
        block_size = max(1, min(block_size, self.max_len))
        self.block_size = block_size
        self.max_blocks_per_seq = -(-self.max_len // block_size)
        if num_blocks is None:
            num_blocks = batch * self.max_blocks_per_seq + 1  # + null
        # The pool must fit at least ONE full sequence, or a lone
        # max-length request could never be served no matter how the
        # scheduler evicts (everything else degrades gracefully;
        # this cannot).
        assert num_blocks - 1 >= self.max_blocks_per_seq, \
            (num_blocks, self.max_blocks_per_seq)
        # int8 paged KV (GROVE_KV_QUANT=int8): blocks store int8 payload
        # plus per-slot-per-head f32 scales — roughly half the bytes a
        # bf16 block moves, dequant fused into the gather. "off" is the
        # untouched bf16 path byte-for-byte.
        if kv_quant is None:
            kv_quant = os.environ.get("GROVE_KV_QUANT", "off")
        assert kv_quant in ("off", "int8"), \
            f"unknown KV quant mode {kv_quant!r}"
        self.kv_quant = kv_quant
        self.kv = PagedKV.create(cfg.n_layers, num_blocks, block_size,
                                 cfg.n_kv_heads, cfg.head_dim, cfg.dtype,
                                 quant=kv_quant)
        self._alloc = BlockAllocator(num_blocks, block_size)
        if prefill_chunk is None:
            prefill_chunk = int(os.environ.get("GROVE_PAGED_CHUNK", 32))
        self.prefill_chunk = max(1, min(prefill_chunk, self.max_len))
        # Global prefix cache (GROVE_PREFIX_CACHE=0 is the off switch:
        # no tree, no refcount sharing, the PR 15 allocator behavior
        # byte-for-byte). Token output is bitwise-identical either way
        # — cached KV is exactly what a cold prefill would have written
        # — so the switch trades memory/lookup work, never correctness.
        if prefix_cache is None:
            prefix_cache = os.environ.get("GROVE_PREFIX_CACHE", "1") != "0"
        self._prefix = PrefixTree(self._alloc) if prefix_cache else None
        # Bytes one block pins across both pools (K and V, plus scales
        # when quantized) — the reclaimed/cached byte gauges ride this.
        # Derived from the ONE shared helper so bench rows, xprof's
        # roofline and these gauges can never disagree.
        from grove_tpu.serving.quant import kv_block_bytes
        self._block_bytes = kv_block_bytes(cfg, block_size, kv_quant)
        self.cow_copies = 0
        self._cow_jit = None
        # Disaggregated handoff state (GROVE_DISAGG): the cross-pool
        # copy executable builds lazily on first adoption (or at the
        # facade's warmup), so mono engines never construct it and the
        # mono lowering pin stays byte-identical. Stats accumulate on
        # the CONSUMER side — the adopt() call is where bytes move.
        self._handoff_jit = None
        self.handoff_stats = {"requests": 0, "blocks": 0,
                              "shared_blocks": 0, "bytes": 0,
                              "seconds": 0.0, "deferred": 0}
        self._sched = PagedScheduler(self._alloc, batch,
                                     self.max_blocks_per_seq,
                                     self.prefill_chunk,
                                     prefix_tree=self._prefix)

        # Speculative decoding (GROVE_SPEC_DECODE=1, default off): a
        # draft model shares the tokenizer, block tables and allocator
        # but owns its own (smaller, never-quantized) KV pool; each
        # decode dispatch drafts k tokens and verifies ALL of them in
        # one fused k+1-wide step. Greedy acceptance commits the
        # longest agreeing prefix plus one bonus token — BITWISE the
        # greedy non-speculative output, so the switch trades compute
        # for dispatches, never correctness. Rejected drafts roll back
        # as bookkeeping only: their rows sit above the committed
        # length (causally invisible) and are overwritten next
        # dispatch — no block copies.
        if spec_decode is None:
            spec_decode = os.environ.get("GROVE_SPEC_DECODE", "0") == "1"
        self.spec_decode = bool(spec_decode)
        if spec_k is None:
            spec_k = int(os.environ.get("GROVE_SPEC_K", "3"))
        self.spec_k = max(1, int(spec_k))
        self._draft_cfg = None
        self._draft_params = None
        self.draft_kv = None
        self._spec_stats = {"draft_tokens": 0, "accepted_tokens": 0,
                            "committed_tokens": 0, "dispatches": 0,
                            "rows": 0, "per_bucket": {}}
        if self.spec_decode:
            if draft_params is None:
                # Derived tiny draft: ~1/4 width/depth of the target,
                # same vocab/head_dim/max_seq_len so tables and rope
                # are shared (models/llama.draft_config).
                self._draft_cfg = llama.draft_config(cfg)
                self._draft_params = llama.init_params(
                    self._draft_cfg,
                    jax.random.PRNGKey(self._sampler.seed + 1))
            elif isinstance(draft_params, str) and draft_params == "self":
                # Self-draft: the target drafts for itself. Every draft
                # agrees, acceptance is k/k deterministically — the
                # bench/smoke configuration that isolates the
                # dispatch-amortization win from draft quality.
                self._draft_cfg = cfg
                self._draft_params = self.params
            else:
                self._draft_cfg, self._draft_params = draft_params

        # ---- GSPMD: mesh + shardings (1-chip CPU degrades to no-ops) --
        from grove_tpu.parallel import sharding as shardlib
        from grove_tpu.parallel.mesh import single_device_mesh
        if mesh is None:
            mesh = single_device_mesh()
        tp = mesh.shape.get("tp", 1)
        assert cfg.n_kv_heads % tp == 0, \
            f"n_kv_heads {cfg.n_kv_heads} must divide over tp={tp}"
        self.mesh = mesh
        self._self_draft = (self.spec_decode
                            and self._draft_params is self.params)
        self.params = shardlib.shard_params(mesh, self.params)
        kv_sh = shardlib.paged_kv_sharding(mesh)
        if self.kv.quantized:
            sc_sh = shardlib.paged_scale_sharding(mesh)
            self.kv = PagedKV(
                k=jax.device_put(self.kv.k, kv_sh),
                v=jax.device_put(self.kv.v, kv_sh),
                k_scale=jax.device_put(self.kv.k_scale, sc_sh),
                v_scale=jax.device_put(self.kv.v_scale, sc_sh))
        else:
            self.kv = PagedKV(k=jax.device_put(self.kv.k, kv_sh),
                              v=jax.device_put(self.kv.v, kv_sh))
        if self.spec_decode and self._self_draft:
            # Self-draft needs NO draft pool: the fused step drafts
            # directly against the target pool (whose drafted-over
            # slots the verify chunk rewrites bitwise-identically), so
            # the KV footprint is the plain engine's.
            self._draft_params = self.params
        elif self.spec_decode:
            dcfg = self._draft_cfg
            assert dcfg.n_kv_heads % tp == 0, \
                f"draft n_kv_heads {dcfg.n_kv_heads} must divide tp={tp}"
            self._draft_params = shardlib.shard_params(
                mesh, self._draft_params)
            draft = PagedKV.create(dcfg.n_layers, num_blocks, block_size,
                                   dcfg.n_kv_heads, dcfg.head_dim,
                                   cfg.dtype)
            self.draft_kv = PagedKV(k=jax.device_put(draft.k, kv_sh),
                                    v=jax.device_put(draft.v, kv_sh))
        # Host-fed buffers (tokens at recompose, tables, prefill chunks)
        # are COMMITTED to the replicated sharding before dispatch:
        # an uncommitted host array and a device-chained committed one
        # would otherwise key two executables per bucket.
        self._rep = shardlib.replicated(mesh)

        self._rng = jax.random.PRNGKey(self._sampler.seed)
        self._sampling = self._sampler.temperature > 0.0
        # Speculative acceptance is an argmax-agreement test: under
        # sampling there is no "the" token to agree with, so the combo
        # is rejected outright rather than silently degrading.
        assert not (self.spec_decode and self._sampling), \
            "speculative decoding is greedy-only (temperature must be 0)"

        # Per-bucket jitted executables (lazy): each (shape-bucket) key
        # owns its own jit object, so its cache holds exactly one entry
        # and a recompile is impossible by construction — the finite
        # bucket ladder is the zero-steady-state-recompiles guarantee.
        self._step_jits: dict[tuple, Callable] = {}
        self._prefill_jits: dict[int, Callable] = {}
        self._spec_jits: dict[tuple, Callable] = {}
        self._draft_prefill_jits: dict[int, Callable] = {}

        # Request flow state.
        self._queue: deque[Request] = deque()
        self._next_rid = 0
        self.completed: list[Request] = []
        self.steps = 0              # decode dispatches
        self.ticks = 0              # engine ticks (prefill or decode)
        # Device-resident decode state for the CURRENT composition.
        self._tokens = None         # [B] int32 (B = batch bucket)
        self._lengths_dev = None    # [B] int32
        self._tables_dev = None     # [B, W] int32
        self._cur_shape: tuple[int, int] | None = None
        self._tables_sig: tuple = ()
        self._run_order: tuple = ()
        self._composition_dirty = True
        self._pending: list[jnp.ndarray] = []
        self._finishing: list = []

        # Data-plane observatory (same contract as the lanes engine).
        self.xprof = None
        if xprof is not False:
            from grove_tpu.serving import xprof as xprof_mod
            if xprof is not None:
                self.xprof = xprof
                self.xprof.cfg = cfg
                self.xprof.batch = batch
                self.xprof.max_len = self.max_len
            elif xprof_mod.enabled():
                self.xprof = xprof_mod.Observatory(
                    cfg=cfg, batch=batch, max_len=self.max_len)
        if self.xprof is not None:
            # Roofline byte basis: the observatory's KV terms must use
            # what this engine actually moves.
            self.xprof.kv_quant = self.kv_quant

        # Request observatory (serving/reqtrace.py), same contract as
        # the lanes engine: RequestObservatory | None (auto unless
        # GROVE_REQTRACE=0) | False. The scheduler gets the same
        # reference so preemption boundaries stamp from the victim
        # path itself — unconditional, never sampled away.
        self.reqtrace = None
        if reqtrace is not False:
            from grove_tpu.serving import reqtrace as reqtrace_mod
            if reqtrace is not None:
                self.reqtrace = reqtrace
            elif reqtrace_mod.enabled():
                self.reqtrace = reqtrace_mod.RequestObservatory()
        self._sched.reqtrace = self.reqtrace

        # With sharing on, pay the ONE copy-on-write executable at
        # bring-up (a null→null block copy): it is workload-independent
        # and shape-static, so building it here keeps the steady-state
        # lowering set identical to the cache-off engine's — the
        # decode_smoke pin counts it at construction, never mid-traffic.
        if self._prefix is not None:
            self._resolve_cow(None)

    # ---- jit construction (one executable per shape bucket) ----

    def _wrap(self, name: str, jitted):
        if self.xprof is not None:
            return self.xprof.compile.wrap(name, jitted)
        return jitted

    def _pools(self) -> tuple:
        """The KV pool arrays a dispatch threads through, in signature
        order: (k, v) bf16 or (k, v, k_scale, v_scale) int8."""
        if self.kv.quantized:
            return (self.kv.k, self.kv.v, self.kv.k_scale,
                    self.kv.v_scale)
        return (self.kv.k, self.kv.v)

    def _set_pools(self, outs) -> None:
        """Rebind self.kv from a dispatch's returned pool arrays (the
        inverse of ``_pools``)."""
        if self.kv.quantized:
            k, v, ks, vs = outs
            self.kv = PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)
        else:
            k, v = outs
            self.kv = PagedKV(k=k, v=v)

    @property
    def _n_pools(self) -> int:
        return 4 if self.kv.quantized else 2

    def _get_step(self, B: int, W: int):
        key = (B, W, self._sampling)
        fn = self._step_jits.get(key)
        if fn is not None:
            return fn
        from grove_tpu.parallel import sharding as shardlib
        cfg = self.cfg
        sampler_cfg = self._sampler
        quant = self.kv_quant == "int8"

        if quant:
            def step_greedy(params, tokens, kv_k, kv_v, ks, vs, tables,
                            lengths):
                logits, kv_k, kv_v, ks, vs = llama.decode_step_paged(
                    cfg, params, tokens, kv_k, kv_v, tables, lengths,
                    k_scale=ks, v_scale=vs)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, kv_k, kv_v, ks, vs, lengths + 1

            def step_sampled(params, tokens, kv_k, kv_v, ks, vs, tables,
                             lengths, key):
                logits, kv_k, kv_v, ks, vs = llama.decode_step_paged(
                    cfg, params, tokens, kv_k, kv_v, tables, lengths,
                    k_scale=ks, v_scale=vs)
                key, sub = jax.random.split(key)
                nxt = sample_tokens(logits, sub, sampler_cfg)
                return nxt, kv_k, kv_v, ks, vs, lengths + 1, key
        else:
            def step_greedy(params, tokens, kv_k, kv_v, tables, lengths):
                logits, kv_k, kv_v = llama.decode_step_paged(
                    cfg, params, tokens, kv_k, kv_v, tables, lengths)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, kv_k, kv_v, lengths + 1

            def step_sampled(params, tokens, kv_k, kv_v, tables, lengths,
                             key):
                logits, kv_k, kv_v = llama.decode_step_paged(
                    cfg, params, tokens, kv_k, kv_v, tables, lengths)
                key, sub = jax.random.split(key)
                nxt = sample_tokens(logits, sub, sampler_cfg)
                return nxt, kv_k, kv_v, lengths + 1, key

        ins, outs = shardlib.paged_step_shardings(
            self.mesh, self.params, sampled=self._sampling, quant=quant)
        donate = (2, 3, 4, 5) if quant else (2, 3)
        fn = jax.jit(step_sampled if self._sampling else step_greedy,
                     donate_argnums=donate, in_shardings=ins,
                     out_shardings=outs)
        # Quantized executables carry a distinct name so decode_smoke's
        # lowering pin distinguishes the modes — GROVE_KV_QUANT=off must
        # reproduce the exact prior lowering set.
        suffix = ("_sampled" if self._sampling else "") \
            + ("_q8" if quant else "")
        fn = self._wrap(f"paged_step{suffix}[b{B},w{W}]", fn)
        self._step_jits[key] = fn
        return fn

    def _get_prefill(self, W: int):
        fn = self._prefill_jits.get(W)
        if fn is not None:
            return fn
        from grove_tpu.parallel import sharding as shardlib
        cfg = self.cfg
        quant = self.kv_quant == "int8"

        if quant:
            def chunk_fn(params, tokens, kv_k, kv_v, ks, vs, table,
                         offset, logit_idx, n_valid):
                return llama.prefill_chunk_paged(
                    cfg, params, tokens, kv_k, kv_v, table, offset,
                    logit_idx, n_valid, k_scale=ks, v_scale=vs)
        else:
            def chunk_fn(params, tokens, kv_k, kv_v, table, offset,
                         logit_idx, n_valid):
                return llama.prefill_chunk_paged(cfg, params, tokens,
                                                 kv_k, kv_v, table,
                                                 offset, logit_idx,
                                                 n_valid)

        ins, outs = shardlib.paged_prefill_shardings(
            self.mesh, self.params, quant=quant)
        donate = (2, 3, 4, 5) if quant else (2, 3)
        fn = jax.jit(chunk_fn, donate_argnums=donate, in_shardings=ins,
                     out_shardings=outs)
        suffix = "_q8" if quant else ""
        fn = self._wrap(
            f"paged_prefill{suffix}[c{self.prefill_chunk},w{W}]", fn)
        self._prefill_jits[W] = fn
        return fn

    def _get_spec(self, B: int, W: int):
        """The fused speculative executable for one shape bucket:
        draft k tokens (sequential small-model steps inside the jit),
        verify all of them in ONE k+1-wide paged-attention pass, commit
        the longest agreeing prefix + bonus (models/llama.
        spec_step_paged). One program per (batch, width) bucket —
        the ladder keeps the executable set finite exactly like the
        plain step's."""
        key = (B, W)
        fn = self._spec_jits.get(key)
        if fn is not None:
            return fn
        from grove_tpu.parallel import sharding as shardlib
        cfg, dcfg = self.cfg, self._draft_cfg
        K = self.spec_k
        quant = self.kv_quant == "int8"

        if self._self_draft and quant:
            def spec_fn(params, tokens, kv_k, kv_v, ks, vs,
                        tables, lengths, limit):
                return llama.spec_step_paged(
                    cfg, cfg, params, params, tokens, kv_k, kv_v,
                    None, None, tables, lengths, limit, K,
                    k_scale=ks, v_scale=vs, self_draft=True)
        elif self._self_draft:
            def spec_fn(params, tokens, kv_k, kv_v,
                        tables, lengths, limit):
                return llama.spec_step_paged(
                    cfg, cfg, params, params, tokens, kv_k, kv_v,
                    None, None, tables, lengths, limit, K,
                    self_draft=True)
        elif quant:
            def spec_fn(params, dparams, tokens, kv_k, kv_v, ks, vs,
                        dk, dv, tables, lengths, limit):
                return llama.spec_step_paged(
                    cfg, dcfg, params, dparams, tokens, kv_k, kv_v,
                    dk, dv, tables, lengths, limit, K,
                    k_scale=ks, v_scale=vs)
        else:
            def spec_fn(params, dparams, tokens, kv_k, kv_v, dk, dv,
                        tables, lengths, limit):
                return llama.spec_step_paged(
                    cfg, dcfg, params, dparams, tokens, kv_k, kv_v,
                    dk, dv, tables, lengths, limit, K)

        ins, outs = shardlib.paged_spec_shardings(
            self.mesh, self.params, self._draft_params, quant=quant,
            self_draft=self._self_draft)
        if self._self_draft:
            donate = (2, 3, 4, 5) if quant else (2, 3)
        else:
            donate = (3, 4, 5, 6, 7, 8) if quant else (3, 4, 5, 6)
        fn = jax.jit(spec_fn, donate_argnums=donate, in_shardings=ins,
                     out_shardings=outs)
        suffix = "_q8" if quant else ""
        fn = self._wrap(f"paged_spec{suffix}[b{B},w{W},k{K}]", fn)
        self._spec_jits[key] = fn
        return fn

    def _get_draft_prefill(self, W: int):
        """Chunked prefill through the DRAFT model: same tokens, same
        block table, writing the draft pool so the drafter has its own
        KV history to decode from. Logits are discarded — the target's
        chunk produces the first token. The draft pool is never
        quantized (it is already small; quantizing it would buy bytes
        nobody is short of and cost draft accuracy)."""
        fn = self._draft_prefill_jits.get(W)
        if fn is not None:
            return fn
        from grove_tpu.parallel import sharding as shardlib
        dcfg = self._draft_cfg

        def chunk_fn(dparams, tokens, dk, dv, table, offset, logit_idx,
                     n_valid):
            return llama.prefill_chunk_paged(dcfg, dparams, tokens, dk,
                                             dv, table, offset,
                                             logit_idx, n_valid)

        ins, outs = shardlib.paged_prefill_shardings(
            self.mesh, self._draft_params)
        fn = jax.jit(chunk_fn, donate_argnums=(2, 3), in_shardings=ins,
                     out_shardings=outs)
        fn = self._wrap(
            f"draft_prefill[c{self.prefill_chunk},w{W}]", fn)
        self._draft_prefill_jits[W] = fn
        return fn

    def warmup(self, batches: list[int] | None = None,
               widths: list[int] | None = None,
               prefill_widths: list[int] | None = None) -> int:
        """Pre-compile bucket executables by dispatching over the NULL
        block: tables all point at block 0, lengths are 0, so the
        garbage lands in the one block no sequence ever owns — live
        state is untouched by design. Returns the number of executables
        built. A deployment calls this at startup so the first real
        traffic never pays an XLA build (the decode bench uses it to
        pin zero compiles across the measured window).

        ``prefill_widths`` defaults to ``widths`` (and both to the full
        ladder); pass ``[]`` to skip prefill builds when ``widths``
        describes a decode-only trajectory — prefill and decode cross
        DIFFERENT width ranges for the same run, and an unused
        executable is a real XLA build wasted."""
        built = 0
        # Warmup scatters land in the null block only — nothing live
        # exists to collide with, witnessed through the same tripwire
        # every real dispatch routes through.
        self._cow_guard(())
        n_pool = self._n_pools
        for B in batches or self._sched.batch_buckets:
            for W in widths or self._sched.width_buckets:
                # Commit-ness mirrors the steady state exactly (or the
                # warm entry would not be THE entry): tokens/lengths
                # committed, tables host-fed.
                toks = jax.device_put(np.zeros((B,), np.int32), self._rep)
                tables = np.zeros((B, W), np.int32)
                lens = jax.device_put(np.zeros((B,), np.int32), self._rep)
                if self.spec_decode:
                    # Spec engines decode ONLY through the fused spec
                    # executable — building plain steps here would add
                    # dead programs to the lowering pin.
                    if (B, W) not in self._spec_jits:
                        built += 1
                    fn = self._get_spec(B, W)
                    limit = np.zeros((B,), np.int32)
                    if self._self_draft:
                        outs = fn(self.params, toks, *self._pools(),
                                  tables, lens, limit)
                    else:
                        outs = fn(self.params, self._draft_params, toks,
                                  *self._pools(), self.draft_kv.k,
                                  self.draft_kv.v, tables, lens, limit)
                        self.draft_kv = PagedKV(k=outs[-2], v=outs[-1])
                    self._set_pools(outs[3:3 + n_pool])
                    continue
                if (B, W, self._sampling) not in self._step_jits:
                    built += 1
                fn = self._get_step(B, W)
                if self._sampling:
                    res = fn(self.params, toks, *self._pools(), tables,
                             lens, self._rng)
                    self._rng = res[-1]
                else:
                    res = fn(self.params, toks, *self._pools(), tables,
                             lens)
                self._set_pools(res[1:1 + n_pool])
        if prefill_widths is None:
            prefill_widths = widths or self._sched.width_buckets
        for W in prefill_widths:
            if W not in self._prefill_jits:
                built += 1
            fn = self._get_prefill(W)
            toks = np.zeros((1, self.prefill_chunk), np.int32)
            table = np.zeros((1, W), np.int32)
            res = fn(self.params, toks, *self._pools(), table,
                     np.int32(0), np.int32(0), np.int32(0))
            self._set_pools(res[1:])
            if self.spec_decode and not self._self_draft:
                if W not in self._draft_prefill_jits:
                    built += 1
                dfn = self._get_draft_prefill(W)
                _, dk, dv = dfn(self._draft_params, toks,
                                self.draft_kv.k, self.draft_kv.v, table,
                                np.int32(0), np.int32(0), np.int32(0))
                self.draft_kv = PagedKV(k=dk, v=dv)
        jax.block_until_ready(self.kv.k)
        return built

    def decode_width_buckets(self, start_tokens: int,
                             end_tokens: int) -> list[int]:
        """The width buckets a sequence crosses decoding from
        ``start_tokens`` to ``end_tokens`` in cache — what a caller
        passes to ``warmup(widths=...)`` to pre-build exactly the
        executables a known-length run will touch (the full ladder is
        overkill when the trajectory is known: a fixed-batch bench
        crossing 3 width buckets should not compile 6)."""
        bs = self.block_size
        ladder = self._sched.width_buckets
        lo = pick_bucket(max(1, -(-start_tokens // bs)), ladder)
        hi = pick_bucket(max(1, -(-end_tokens // bs)), ladder)
        return [w for w in ladder if lo <= w <= hi]

    # ---- request intake ----

    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32) -> int:
        prompt = np.asarray(prompt, np.int32)
        assert len(prompt) < self.max_len, \
            (f"prompt of {len(prompt)} tokens cannot fit max_len="
             f"{self.max_len} with room to generate")
        req = Request(rid=self._next_rid, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      enqueue_ts=time.time())
        self._next_rid += 1
        self._queue.append(req)
        rt = self.reqtrace
        if rt is not None:
            rt.note_enqueue(req.rid, ts=req.enqueue_ts,
                            prompt_len=len(prompt),
                            max_new_tokens=max_new_tokens)
        self._report_metric()
        return req.rid

    @property
    def queue_depth(self) -> int:
        """Requests not yet (re)admitted: the submit queue plus
        preempted sequences awaiting recompute."""
        return len(self._queue) + len(self._sched.preempted)

    @property
    def kv_lane_utilization(self) -> float:
        """Fraction of the KV block pool in use — the paged analog of
        the lanes gauge (1.0 = allocator dry, admissions defer)."""
        return self._alloc.utilization

    @property
    def _active(self) -> np.ndarray:
        """Liveness mask (run_load compatibility): one True per
        sequence currently prefilling or decoding, plus one while
        undrained window tokens or completions are pending — a driver
        stepping only while "active" must keep ticking until the last
        request's bookkeeping lands (the 2365/2366 clean-exit leak)."""
        n = self._sched.live
        if n == 0 and (self._pending or self._finishing):
            n = 1
        return np.ones((n,), bool)

    @property
    def cache(self) -> PagedKV:
        """The KV pool (xprof.memory_snapshot reads .k/.v through
        this, same as the lanes engine's contiguous cache)."""
        return self.kv

    def _report_metric(self) -> None:
        if self.metric_hook is not None:
            self.metric_hook(self.queue_depth)
        if self.telemetry is not None:
            self.telemetry.sample_gauges(self.queue_depth,
                                         self.kv_lane_utilization)
            if self._prefix is not None:
                self.telemetry.sample_prefix(self.prefix_stats())
            if self.spec_decode:
                self.telemetry.sample_spec(self.spec_stats())
            if self.handoff_stats["requests"]:
                self.telemetry.sample_handoff(self.handoff_view())
            if self.reqtrace is not None \
                    and self.reqtrace.finished_total:
                self.telemetry.sample_phases(
                    self.reqtrace.phase_stats())
        if self.xprof is not None:
            self.xprof.observe_memory(self, self.telemetry)
            if self.spec_decode:
                self.xprof.spec = self.spec_stats()
            if self.handoff_stats["requests"]:
                self.xprof.handoff = self.handoff_view()

    def prefix_stats(self) -> dict:
        """Prefix-cache gauges for the slo digest (hit-rate,
        cached-blocks, reclaimed-bytes — the PR 16 telemetry riders).
        Empty dict with the cache off."""
        if self._prefix is None:
            return {}
        p = self._prefix.payload()
        return {"hit_rate": p["hit_rate"],
                "cached_blocks": p["cached_blocks"],
                "cached_bytes": p["cached_blocks"] * self._block_bytes,
                "reclaimed_bytes":
                    p["reclaimed_total"] * self._block_bytes,
                "tokens_matched_total": p["tokens_matched_total"],
                "cow_copies": self.cow_copies}

    def spec_stats(self) -> dict:
        """Speculative-decoding acceptance gauges (the slo digest and
        /debug/xprof riders). Empty dict with spec off. Counters
        accumulate at drain time only — between drains they lag the
        device by at most one window, the same staleness every other
        token counter here carries.

        ``accepted_per_dispatch`` is the headline multiplier: mean
        tokens COMMITTED per sequence per fused dispatch (bonus
        included) — 1.0 is non-speculative parity, spec_k+1 the
        ceiling."""
        if not self.spec_decode:
            return {}
        st = self._spec_stats
        drafted, accepted = st["draft_tokens"], st["accepted_tokens"]
        rows = st["rows"]
        return {"spec_k": self.spec_k,
                "draft_tokens": drafted,
                "accepted_tokens": accepted,
                "committed_tokens": st["committed_tokens"],
                "dispatches": st["dispatches"],
                "rows": rows,
                "acceptance_rate":
                    accepted / drafted if drafted else 0.0,
                "accepted_per_dispatch":
                    st["committed_tokens"] / rows if rows else 0.0,
                "per_bucket": {
                    f"b{B},w{W}": dict(v)
                    for (B, W), v in st["per_bucket"].items()}}

    def handoff_view(self) -> dict:
        """Block-handoff accounting for the slo digest and /debug/xprof
        (GROVE_DISAGG consumer-side riders). ``ms_per_request`` is the
        mean host wall one adoption's copy dispatches cost;
        ``bytes_per_request`` the mean bytes a request's cold suffix
        moved (shared prefix blocks never move — they are the
        ``shared_blocks`` count)."""
        st = self.handoff_stats
        n = st["requests"]
        return {"requests": n,
                "blocks": st["blocks"],
                "shared_blocks": st["shared_blocks"],
                "bytes": st["bytes"],
                "deferred": st["deferred"],
                "seconds": st["seconds"],
                "ms_per_request": st["seconds"] * 1e3 / n if n else 0.0,
                "bytes_per_request": st["bytes"] / n if n else 0.0,
                "block_bytes": self._block_bytes}

    def _stamp_admit(self, req: Request, now: float,
                     admit: float | None = None) -> None:
        _stamp_admit_impl(req, now, admit, self._ttft_compat,
                          self.telemetry)
        rt = self.reqtrace
        if rt is not None:
            rt.note_admit(req.rid, ts=req.admit_ts)

    def _complete(self, req: Request) -> None:
        _complete_impl(req, self.completed, self.telemetry)
        rt = self.reqtrace
        if rt is not None:
            rt.note_done(req.rid, ts=req.done_ts)

    # ---- disaggregated handoff (the consumer side) ----

    def _get_handoff(self):
        """The one cross-pool block-copy executable (serving/handoff.py
        protocol): traced null-padded src/dst id VECTORS at the fixed
        max table width → ONE shape-static program moving a whole
        payload per dispatch, ``paged_handoff_copy`` in the compile
        tracker. Built lazily on first adoption (or the disagg
        facade's warmup) so mono engines never carry it. Only the
        DESTINATION pools are donated — the producer keeps serving
        from the source pool."""
        if self._handoff_jit is None:
            from grove_tpu.parallel import sharding as shardlib
            quant = self.kv.quantized
            ins, outs = shardlib.paged_handoff_shardings(self.mesh,
                                                         quant=quant)
            if quant:
                fn = jax.jit(llama.paged_block_copy_q,
                             donate_argnums=(0, 1, 2, 3),
                             in_shardings=ins, out_shardings=outs)
            else:
                fn = jax.jit(llama.paged_block_copy,
                             donate_argnums=(0, 1),
                             in_shardings=ins, out_shardings=outs)
            self._handoff_jit = self._wrap("paged_handoff_copy", fn)
        return self._handoff_jit

    def warmup_handoff(self, source) -> int:
        """Pre-build the handoff copy against ``source``'s pool with a
        null→null copy (the CoW prebuild recipe): the executable is
        paid before traffic, so decode_smoke's pin counts it at warmup,
        never mid-stream. Returns executables built (0 or 1)."""
        built = int(self._handoff_jit is None)
        fn = self._get_handoff()
        pad = np.full((self.max_blocks_per_seq,), NULL_BLOCK, np.int32)
        outs = fn(*self._pools(), *source._pools(), pad, pad)
        self._set_pools(outs)
        return built

    def adopt(self, payload: HandoffPayload) -> bool:
        """Adopt one finished prefill from another engine's pool: the
        tentpole handoff (docs/design/disaggregated-serving.md). Gate
        on a free decode slot, match the tokens against the LOCAL
        prefix tree (full-block hits join shared — those blocks never
        transfer), adopt fresh blocks for the cold suffix, device-copy
        them src-pool → dst-pool, and join the sequence straight into
        the decode batch. False = backpressure (nothing changed hands;
        the producer retries next pump).

        Refcount contract: source block refs stay with the payload
        until ``release()`` at the END — a mid-adoption failure leaves
        both allocators exactly as they were. The final handed-off
        block is never prefix-shared (match caps at len(tokens) - 1),
        so decode's first write always lands in a refcount-1 adopted
        block and the ``_cow_guard`` holds with no CoW at adoption."""
        sched = self._sched
        if sched.slots_free <= 0:
            self.handoff_stats["deferred"] += 1
            return False
        tokens = np.asarray(payload.tokens, np.int32)
        shared: list[int] = []
        matched = 0
        if self._prefix is not None:
            shared, matched, partial = self._prefix.match(tokens)
            if partial is not None:
                # A mid-block hit would need CoW *and* a partial copy
                # on top — the handoff only reuses FULL blocks. Drop
                # the caller ref; the block falls back to cached.
                src_b, k = partial
                self._alloc.free([src_b])
                matched -= k
        n_shared = len(shared)
        cold = len(payload.blocks) - n_shared
        got = self._alloc.adopt(cold)
        if got is None:
            if shared:
                self._alloc.free(shared)
            self.handoff_stats["deferred"] += 1
            return False
        x = self.xprof
        sampled = x is not None and x.should_sample()
        if sampled:
            jax.block_until_ready(self.kv.k)
        t0 = time.perf_counter()
        fn = self._get_handoff()
        # One dispatch per payload: the cold (src, dst) pairs padded
        # to the fixed table width with null→null no-ops.
        srcv = np.full((self.max_blocks_per_seq,), NULL_BLOCK, np.int32)
        dstv = np.full((self.max_blocks_per_seq,), NULL_BLOCK, np.int32)
        srcv[:cold] = payload.blocks[n_shared:]
        dstv[:cold] = got
        outs = fn(*self._pools(), *payload.source._pools(), srcv, dstv)
        self._set_pools(outs)
        if sampled:
            jax.block_until_ready(self.kv.k)
        dt = time.perf_counter() - t0
        if sampled:
            x.record("handoff", dt)
        seq = PagedSeq(req=payload.req, tokens=tokens,
                       blocks=SeqBlocks(self._alloc, shared + got),
                       order=0, pos=payload.pos,
                       n_generated=payload.n_generated,
                       recompute=payload.recompute,
                       last_token=payload.first_token,
                       prefix_matched=matched)
        sched.adopt_running(seq)
        self._composition_dirty = True
        moved_bytes = cold * self._block_bytes
        rt = self.reqtrace
        if rt is not None:
            # The trace rode the payload across the seam: under the
            # shared disagg recorder this is a no-op, with per-tier
            # recorders it splices — either way the rid's timeline is
            # one trace. Adoption closes the handoff span (detach →
            # remap/copy → here) and opens this tier's decode segment;
            # a recompute replay closes its preempt window instead.
            rt.adopt_trace(payload.trace)
            if matched:
                rt.note_prefix(payload.req.rid, n_shared,
                               len(payload.blocks), matched)
            if payload.recompute:
                rt.note_resume(payload.req.rid)
            else:
                rt.note_handoff(payload.req.rid, payload.created_ts,
                                blocks=cold, nbytes=moved_bytes,
                                shared=n_shared)
                rt.note_decode_start(payload.req.rid)
        st = self.handoff_stats
        st["requests"] += 1
        st["blocks"] += cold
        st["shared_blocks"] += n_shared
        st["bytes"] += moved_bytes
        st["seconds"] += dt
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        GLOBAL_METRICS.inc("grove_handoff_blocks_total", float(cold))
        GLOBAL_METRICS.inc("grove_handoff_bytes_total",
                           float(moved_bytes))
        if sampled:
            # Only synced walls enter the histogram — an unsynced dt
            # times dispatch enqueue, not the transfer.
            GLOBAL_METRICS.observe("grove_handoff_seconds", dt)
        payload.release()
        self._report_metric()
        return True

    # ---- admission ----

    def admit_from_queue(self, prefiller=None) -> int:
        """Admit queued work into the scheduler: preempted sequences
        re-enter first (recompute), then fresh requests FIFO, each
        gated on a free slot + the allocator's first-chunk grant.
        ``prefiller`` is accepted for lanes-engine call-site
        compatibility (tools/loadgen.run_load) and ignored — chunked
        prefill is in-engine here."""
        admitted = 0
        while self._sched.preempted:
            seq = self._sched.preempted.popleft()
            if self._sched.readmit(seq) is None:
                self._sched.preempted.appendleft(seq)
                break
            admitted += 1
        while self._queue:
            req = self._queue[0]
            popped = time.time()  # queue-exit, before any prefill work
            if self._sched.admit(
                    req, req.prompt[:req.prompt_len]) is None:
                break
            self._queue.popleft()
            if not req.admit_ts:
                req.admit_ts = popped
            rt = self.reqtrace
            if rt is not None:
                # Queue exit stamps here (real time, not the
                # retroactive _stamp_admit at prefill completion) so
                # queue_wait never absorbs chunked-prefill wall.
                rt.note_admit(req.rid, ts=req.admit_ts)
            admitted += 1
        if admitted:
            self._report_metric()
        return admitted

    def admit_prompts(self, prompts, max_new_tokens: int | None = None,
                      lengths=None) -> None:
        """Bench-path bulk admission: submit a [b, s] batch and drive
        chunked prefill to completion so every row is decoding. The
        lanes engine prefills this in one batched dispatch; here each
        prompt advances chunk-by-chunk (the steady-state machinery is
        the thing being benchmarked)."""
        prompts_np = np.asarray(prompts)
        b, s = prompts_np.shape
        lengths_np = (np.full((b,), s, np.int32) if lengths is None
                      else np.asarray(lengths, np.int32))
        for i in range(b):
            n = int(lengths_np[i])
            new = (max_new_tokens if max_new_tokens is not None
                   else self.max_len - n)
            self.submit(prompts_np[i, :n], max_new_tokens=new)
        self.admit_from_queue()
        stalled = 0
        while self._sched.has_prefill_work() or self._queue \
                or self._sched.preempted:
            before = self._admit_progress()
            if self._sched.has_prefill_work():
                self._prefill_tick()
            elif self._sched.running:
                # Slots full with prompts still queued (a batch larger
                # than the engine's slot count): decode the live set so
                # completions free slots — without this the loop would
                # spin forever waiting on admissions that can't happen.
                if self.spec_decode:
                    self._spec_tick()
                else:
                    self._decode_tick()
            self.admit_from_queue()
            stalled = stalled + 1 if self._admit_progress() == before \
                else 0
            if stalled > 4 * self.batch + 16:
                raise RuntimeError(
                    "admit_prompts stalled: KV pool too small for the "
                    f"batch ({self._alloc.payload()})")

    def _admit_progress(self) -> tuple:
        """Monotone progress signature for admit_prompts' stall guard:
        prefill positions, decode positions, completions, admissions —
        if a full iteration moves none of these, nothing ever will."""
        return (sum(sq.pos for sq in self._sched.prefilling),
                sum(sq.pos for sq in self._sched.running),
                len(self.completed), self._sched.admitted_total)

    # ---- the tick loop ----

    def step(self) -> None:
        """One engine tick: at most one prefill chunk (continuous
        batching's admission lane) followed by one decode dispatch over
        the compacted batch. No device syncs on this path — windows
        drain in ``_drain`` (host-sync-in-step-loop lint rule)."""
        if self._sched.has_prefill_work():
            self._prefill_tick()
        if self._sched.running:
            if self.spec_decode:
                self._spec_tick()
            else:
                self._decode_tick()
        elif self._pending or self._finishing:
            # The decode set emptied with a window in flight: fold it
            # in now — nothing else will (the last completion must not
            # wait for traffic that may never come).
            self._drain()
        self.ticks += 1

    def run(self, steps: int) -> None:
        """Drive ``steps`` ticks, then drain + hard-sync (timed-loop
        honesty: callers measure completed work, not queued dispatch)."""
        for _ in range(steps):
            self.step()
        self.sync()

    def sync(self) -> None:
        self._drain()
        if self._tokens is not None:
            np.asarray(self._tokens)

    # ---- copy-on-write (the write-to-shared-block lint contract) ----

    def _get_cow(self):
        """The one CoW executable: copy a block's K/V across the pool.
        Traced src/dst scalars → ONE shape-static program for every
        copy, built at engine construction (never mid-traffic), tracked
        as ``paged_cow_copy`` so the decode_smoke pin counts it."""
        if self._cow_jit is None:
            from grove_tpu.parallel import sharding as shardlib
            kv_sh = shardlib.paged_kv_sharding(self.mesh)
            rep = shardlib.replicated(self.mesh)
            if self.kv.quantized:
                # Scales ride the copy: an int8 payload without its
                # per-slot scale row dequantizes to garbage.
                sc_sh = shardlib.paged_scale_sharding(self.mesh)

                def cow(k, v, ks, vs, src, dst):
                    return (k.at[:, dst].set(k[:, src]),
                            v.at[:, dst].set(v[:, src]),
                            ks.at[:, dst].set(ks[:, src]),
                            vs.at[:, dst].set(vs[:, src]))

                jitted = jax.jit(
                    cow, donate_argnums=(0, 1, 2, 3),
                    in_shardings=(kv_sh, kv_sh, sc_sh, sc_sh, rep, rep),
                    out_shardings=(kv_sh, kv_sh, sc_sh, sc_sh))
            else:
                def cow(k, v, src, dst):
                    return (k.at[:, dst].set(k[:, src]),
                            v.at[:, dst].set(v[:, src]))

                jitted = jax.jit(cow, donate_argnums=(0, 1),
                                 in_shardings=(kv_sh, kv_sh, rep, rep),
                                 out_shardings=(kv_sh, kv_sh))
            self._cow_jit = self._wrap("paged_cow_copy", jitted)
        return self._cow_jit

    def _resolve_cow(self, seq) -> None:
        """Copy-on-write barrier — THE helper every prefill scatter
        dispatch routes through first (write-to-shared-block lint
        rule). A sequence that matched a prefix MID-BLOCK shares the
        divergence block read-only; before its first chunk writes into
        that table slot, the shared contents are device-copied into the
        fresh block the scheduler granted (the table already points at
        the copy) and the source reference drops. ``seq=None`` is the
        construction-time prebuild: a null→null copy that pays the
        executable before any traffic."""
        if seq is None:
            self._set_pools(self._get_cow()(*self._pools(),
                                            np.int32(NULL_BLOCK),
                                            np.int32(NULL_BLOCK)))
            return
        if seq.cow_src < 0:
            return
        src, dst = seq.cow_src, seq.cow_dst
        seq.cow_src = seq.cow_dst = -1
        self._set_pools(self._get_cow()(*self._pools(),
                                        np.int32(src), np.int32(dst)))
        self._alloc.free([src])
        self.cow_copies += 1

    def _cow_guard(self, seqs, span: int = 1) -> None:
        """Exclusive-write tripwire ahead of the decode scatter (the
        lint rule's decode half): every block the next dispatch can
        write — positions [pos + inflight, pos + inflight + span) —
        must be refcount-1. Non-speculative decode has inflight 0 and
        span 1: exactly the single next-token block. By construction
        decode always writes a fresh suffix/CoW block — a trip here
        means the sharing bookkeeping is corrupt, and raising now
        beats the silent KV corruption a shared-block write would
        smear over every other holder."""
        bs = self.block_size
        for seq in seqs:
            start = seq.pos + seq.inflight
            end = min(start + span, len(seq.blocks.blocks) * bs)
            for p in range(start, end):
                b = seq.blocks.blocks[p // bs]
                if self._alloc.refcount(b) > 1:
                    raise RuntimeError(
                        f"decode write into shared block {b} (refcount "
                        f"{self._alloc.refcount(b)}) — copy-on-write "
                        "was bypassed")

    # ---- chunked prefill ----

    def _prefill_tick(self) -> None:
        seq = self._sched.next_prefill()
        if seq is None:
            if not self._sched.prefilling:
                return
            # OOM at the prefill head. Decode has ABSOLUTE priority
            # for the pool (the vLLM ordering): with anything running,
            # the head simply waits — completions free blocks, and
            # running progress is guaranteed (decode-side OOM preempts
            # among running and reclaims from prefilling, never the
            # other way). Preempting running work to feed a prefill
            # ping-pongs forever once two near-complete sequences
            # cannot coexist — the tight-pool storm test caught
            # exactly that livelock. With NOTHING running, the blocks
            # are pinned by other prefilling sequences that can never
            # advance past the FIFO head — evict the newest back to
            # the queue instead of deadlocking on completions that
            # cannot come.
            if not self._sched.running:
                head = self._sched.prefilling[0]
                victim = self._sched.evict_newest_prefilling(protect=head)
                if victim is not None:
                    self._requeue_prefill_victim(victim)
                    self._report_metric()
            return
        # Shared-block write safety: a pending mid-block prefix hit is
        # copied into its fresh block BEFORE this chunk's scatter can
        # land there (the write-to-shared-block lint contract).
        self._resolve_cow(seq)
        c = self.prefill_chunk
        pos, total = seq.pos, seq.prompt_len
        valid = min(c, total - pos)
        toks = np.zeros((1, c), np.int32)
        toks[0, :valid] = seq.tokens[pos:pos + valid]
        W = pick_bucket(len(seq.blocks.blocks), self._sched.width_buckets)
        table = pad_tables([seq.blocks.blocks], W)
        fn = self._get_prefill(W)
        x = self.xprof
        sampled = x is not None and x.should_sample()
        rt = self.reqtrace
        traced = rt is not None and rt.should_sample()
        if traced:
            tr0 = time.perf_counter()
        if sampled:
            jax.block_until_ready(self.kv.k)
            t0 = time.perf_counter()
        res = fn(self.params, toks, *self._pools(), table,
                 np.int32(pos), np.int32(max(0, valid - 1)),
                 np.int32(valid))
        logits = res[0]
        self._set_pools(res[1:])
        if self.spec_decode and not self._self_draft:
            # The draft model replays the SAME chunk into its own pool
            # (same tokens, same table — block IDs are shared) so it
            # has KV history to draft from. Runs for recompute replays
            # too; prefix-cache hits skip straight past matched blocks,
            # leaving stale draft KV there — an acceptance-rate cost
            # only, never a correctness one (verification is always
            # the target's). Self-draft skips this entirely: the
            # drafter reads the target pool the chunk above just wrote.
            dfn = self._get_draft_prefill(W)
            _, dk, dv = dfn(self._draft_params, toks, self.draft_kv.k,
                            self.draft_kv.v, table, np.int32(pos),
                            np.int32(max(0, valid - 1)), np.int32(valid))
            self.draft_kv = PagedKV(k=dk, v=dv)
        if sampled:
            jax.block_until_ready(logits)
            x.record("prefill", time.perf_counter() - t0, tokens=valid)
        if traced:
            # Decoration only (accumulate=False): an unsynced chunk
            # wall times dispatch enqueue, and the sampled subset never
            # feeds phase attribution — the admit→done boundaries do.
            rt.note_chunk(seq.req.rid, W, time.perf_counter() - tr0,
                          valid)
        seq.pos += valid
        if seq.prefill_done:
            self._finish_prefill(seq, logits)

    def _requeue_prefill_victim(self, victim) -> None:
        """Re-queue a sequence evicted from the prefill queue. A
        recompute victim carries generated history in its tokens and
        must re-enter through the preempted path (readmit restores
        n_generated); requeueing its bare Request would replay only
        the prompt and re-stamp TTFT — the output-corruption bug a
        review pass caught."""
        if victim.recompute:
            self._sched.preempted.appendleft(victim)
        else:
            self._queue.appendleft(victim.req)

    def _sample_first(self, logits) -> int:
        """Sample the prefill-produced first token (the sampler state a
        disaggregated handoff materializes and ships)."""
        if self._sampling:
            self._rng, sub = jax.random.split(self._rng)
            return int(np.asarray(
                sample_tokens(logits, sub, self._sampler))[0])
        return int(np.asarray(jnp.argmax(logits, axis=-1))[0])

    def _finish_prefill(self, seq, logits) -> None:
        """The chunk that PRODUCES the first token just ran: sample it,
        stamp TTFT here — at token emission, not at batch-wide prefill
        completion (the chunked-prefill TTFT satellite; both
        GROVE_TTFT_COMPAT modes regression-tested)."""
        tok = self._sample_first(logits)
        req = seq.req
        if seq.recompute:
            # Recompute replays history; the sampled token is the next
            # DECODE token, not a first token — no stamp rewrite.
            req.generated.append(tok)
            if self.telemetry is not None:
                self.telemetry.add_tokens(1)
        else:
            self._stamp_admit(req, time.time(), admit=req.admit_ts or None)
            req.generated.append(tok)
        seq.n_generated = len(req.generated)
        seq.last_token = tok
        rt = self.reqtrace
        if rt is not None:
            if seq.recompute:
                # Recompute replay finished: the preempt_recompute
                # window closes and decode resumes.
                rt.note_resume(req.rid)
            else:
                rt.note_prefill_done(req.rid)
                rt.note_decode_start(req.rid)
        self._sched.promote(seq)
        self._composition_dirty = True
        if seq.finished():
            self._sched.retire(seq)
            self._complete(req)
        self._report_metric()

    # ---- decode ----

    def _decode_tick(self) -> None:
        sched = self._sched
        # Cache-full truncation (the lanes engine's _lane_has_room
        # analog): a sequence whose next write would land past max_len
        # completes NOW — letting it grow would push its block table
        # past the width ladder's top bucket and crash the dispatch.
        full = [s for s in sched.running if s.pos + 1 > self.max_len]
        if full:
            self._drain()
            for s in full:
                sched.retire(s)
                self._complete(s.req)
            self._composition_dirty = True
            self._report_metric()
            if not sched.running:
                return
        # Capacity: a block grant does NOT change composition, so the
        # cheap path needs no drain; only a shortfall (preemption) or a
        # finished/joined sequence forces one.
        needy = [s for s in sched.running if not s.blocks.ensure(s.pos + 1)]
        if needy:
            self._drain()
            if sched.ensure_decode_capacity():
                self._composition_dirty = True
                self._report_metric()
            stuck = [s for s in sched.running
                     if s.blocks.capacity < s.pos + 1]
            for s in stuck:
                # Before truncating, reclaim pool from the PREFILL
                # queue: preempt_newest only sees running sequences,
                # but blocks pinned by prefilling ones are just as
                # reclaimable (their occupants re-queue without losing
                # produced tokens).
                while s.blocks.capacity < s.pos + 1:
                    victim = sched.evict_newest_prefilling()
                    if victim is None:
                        break
                    self._requeue_prefill_victim(victim)
                    s.blocks.ensure(s.pos + 1)
                if s.blocks.capacity >= s.pos + 1:
                    continue
                # Truly un-growable: the pool cannot back one more
                # token — truncate rather than livelock.
                sched.retire(s)
                self._complete(s.req)
                self._composition_dirty = True
            if not sched.running:
                return
        sig = tuple(len(s.blocks.blocks) for s in self._run_order)
        if self._composition_dirty:
            self._recompose()
        elif sig != self._tables_sig:
            self._refresh_tables()
        if not sched.running:
            return
        B, W = self._cur_shape
        self._cow_guard(self._run_order)
        fn = self._get_step(B, W)
        x = self.xprof
        sampled = x is not None and x.should_sample()
        if sampled:
            jax.block_until_ready(self._tokens)
            t0 = time.perf_counter()
        n_pool = self._n_pools
        if self._sampling:
            res = fn(self.params, self._tokens, *self._pools(),
                     self._tables_dev, self._lengths_dev, self._rng)
            self._rng = res[-1]
        else:
            res = fn(self.params, self._tokens, *self._pools(),
                     self._tables_dev, self._lengths_dev)
        tokens, lengths = res[0], res[1 + n_pool]
        if sampled:
            jax.block_until_ready(tokens)
            x.record("sample" if self._sampling else "step",
                     time.perf_counter() - t0,
                     tokens=len(self._run_order))
        self._set_pools(res[1:1 + n_pool])
        self._tokens, self._lengths_dev = tokens, lengths
        # Each pending window remembers ITS composition: joins/leaves
        # between windows then need no drain — the fold-in maps each
        # window's columns through its own snapshot.
        self._pending.append((tokens, self._run_order))
        self.steps += 1
        for seq in self._run_order:
            if seq.req.done:
                continue
            seq.pos += 1
            seq.n_generated += 1
            if seq.finished() and seq in sched.running:
                # Count-based completion: no token values needed, so
                # blocks free IMMEDIATELY; the window tokens drain
                # later into req.generated.
                sched.retire(seq)
                self._finishing.append(seq)
                self._composition_dirty = True
        if len(self._pending) >= self.host_sync_interval:
            self._drain()

    def _spec_tick(self) -> None:
        """The speculative decode tick: one fused dispatch advances
        every running sequence by 1..spec_k+1 tokens. The committed
        count is DATA-DEPENDENT and lives on device until the window
        drains, so all host bookkeeping here is conservative:
        ``seq.inflight`` grows by the full span per dispatch (the upper
        bound on device length), capacity/full checks use
        ``pos + inflight``, and the true counts fold into ``pos`` at
        ``_drain``. No device syncs on this path (the
        host-sync-in-step-loop lint rule covers it by name)."""
        sched = self._sched
        span = self.spec_k + 1
        # Cache-full: if the NEXT dispatch could write past max_len for
        # any sequence (conservatively: its device length may already
        # be pos + inflight), drain to learn the real positions, then
        # retire the truly-full. Surviving sequences re-enter with
        # inflight 0 and exact pos — the dispatched limit vector then
        # clamps their commits at max_len, which is precisely the
        # sequential engine's one-token-at-the-edge behavior.
        if any(s.pos + s.inflight + span > self.max_len
               for s in sched.running):
            self._drain()
            full = [s for s in sched.running if s.pos + 1 > self.max_len]
            for s in full:
                sched.retire(s)
                self._complete(s.req)
            if full:
                self._composition_dirty = True
                self._report_metric()
            if not sched.running:
                return
        # Capacity: every row needs room for a full span past its
        # conservative device length. ensure_decode_capacity degrades
        # to a single-token grant under pressure before preempting —
        # the limit vector turns the shortfall into fewer committed
        # tokens, not an eviction.
        # The ensure target caps at max_len: a near-the-edge sequence
        # (pos + span past max_len but not yet full) must not grow its
        # table past the width ladder — the limit vector truncates its
        # commit instead, and the full-check above retires it next tick.
        needy = [s for s in sched.running
                 if not s.blocks.ensure(min(s.pos + s.inflight + span,
                                            self.max_len))]
        if needy:
            self._drain()
            if sched.ensure_decode_capacity(tokens_per_tick=span):
                self._composition_dirty = True
                self._report_metric()
            stuck = [s for s in sched.running
                     if s.blocks.capacity < s.pos + 1]
            for s in stuck:
                while s.blocks.capacity < s.pos + 1:
                    victim = sched.evict_newest_prefilling()
                    if victim is None:
                        break
                    self._requeue_prefill_victim(victim)
                    s.blocks.ensure(s.pos + 1)
                if s.blocks.capacity >= s.pos + 1:
                    continue
                sched.retire(s)
                self._complete(s.req)
                self._composition_dirty = True
            if not sched.running:
                return
        sig = tuple(len(s.blocks.blocks) for s in self._run_order)
        if self._composition_dirty:
            self._recompose()
        elif sig != self._tables_sig:
            self._refresh_tables()
        if not sched.running:
            return
        B, W = self._cur_shape
        self._cow_guard(self._run_order, span=span)
        # Per-row commit ceiling: what the granted blocks (and max_len)
        # can hold. Live rows always satisfy limit >= device length + 1
        # (the capacity pass above guarantees at least one more slot),
        # so row 0 of the verify chunk — the sequence's own next token
        # — is never rerouted to the null block. Padded rows get 0:
        # every write nulls out and their lengths stay frozen.
        limit = np.zeros((B,), np.int32)
        for i, s in enumerate(self._run_order):
            limit[i] = min(self.max_len,
                           len(s.blocks.blocks) * self.block_size)
        fn = self._get_spec(B, W)
        x = self.xprof
        sampled = x is not None and x.should_sample()
        if sampled:
            jax.block_until_ready(self._tokens)
            t0 = time.perf_counter()
        n_pool = self._n_pools
        if self._self_draft:
            res = fn(self.params, self._tokens, *self._pools(),
                     self._tables_dev, self._lengths_dev, limit)
        else:
            res = fn(self.params, self._draft_params, self._tokens,
                     *self._pools(), self.draft_kv.k, self.draft_kv.v,
                     self._tables_dev, self._lengths_dev, limit)
        out_tokens, tokens, lengths = res[0], res[1], res[2]
        if sampled:
            jax.block_until_ready(tokens)
            x.record("step", time.perf_counter() - t0,
                     tokens=len(self._run_order))
        self._set_pools(res[3:3 + n_pool])
        if not self._self_draft:
            self.draft_kv = PagedKV(k=res[-2], v=res[-1])
        self._tokens, self._lengths_dev = tokens, lengths
        self._pending.append((out_tokens, self._run_order, (B, W)))
        self.steps += 1
        for seq in self._run_order:
            if seq.req.done:
                continue
            seq.inflight += span
        if len(self._pending) >= self.host_sync_interval:
            self._drain()

    def _recompose(self) -> None:
        """Rebuild the device-resident decode state after sequences
        joined or left: drain pending windows (their snapshots carry
        the survivors' current tokens to the host), compact the running
        set into slots [0, n), and ship fresh token/length/table
        buffers at the new buckets. Measured on the CPU mesh this beats
        a drain-free eager-gather variant — the per-recompose device
        scatter cost more than the window sync it avoided."""
        self._drain()
        running = self._sched.running
        self._run_order = tuple(running)
        self._composition_dirty = False
        if not running:
            self._tokens = None
            self._lengths_dev = None
            self._tables_dev = None
            self._cur_shape = None
            self._tables_sig = ()
            return
        B, W = self._sched.decode_shape()
        self._cur_shape = (B, W)
        toks = np.zeros((B,), np.int32)
        lens = np.zeros((B,), np.int32)
        for i, s in enumerate(running):
            toks[i] = s.last_token
            lens[i] = s.pos
        self._tokens = jax.device_put(toks, self._rep)
        self._lengths_dev = jax.device_put(lens, self._rep)
        self._push_tables(B, W)

    def _refresh_tables(self) -> None:
        """A running sequence grew a block (same composition): only the
        table buffer is stale; tokens/lengths stay device-chained. The
        width bucket may step up — batch bucket is unchanged."""
        B = self._cur_shape[0]
        W = pick_bucket(
            max(len(s.blocks.blocks) for s in self._run_order),
            self._sched.width_buckets)
        self._cur_shape = (B, W)
        self._push_tables(B, W)

    def _push_tables(self, B: int, W: int) -> None:
        rows = pad_tables([s.blocks.blocks for s in self._run_order], W)
        full = np.zeros((B, W), np.int32)
        full[:len(self._run_order)] = rows
        # Kept as a host array: the jit commits it on dispatch. Every
        # step call then passes tables the same way (host-fed), so the
        # arg keys ONE executable per bucket — mixing committed and
        # host-fed tables would key two.
        self._tables_dev = full
        self._tables_sig = tuple(len(s.blocks.blocks)
                                 for s in self._run_order)

    def _drain(self) -> None:
        """Fold pending window tokens into their requests: ONE chain
        wait per window (the first fetch), everything after is
        already-materialised. Runs once per host_sync_interval or at a
        composition change — never per step."""
        if not self._pending:
            return
        x = self.xprof
        if x is not None:
            t0 = time.perf_counter()
        entries = [(np.asarray(e[0]),) + tuple(e[1:])
                   for e in self._pending]
        if x is not None:
            x.record("host_transfer", time.perf_counter() - t0)
        self._pending.clear()
        appended = 0
        spec_seqs: dict = {}   # insertion-ordered dedupe
        spec_accepted = spec_drafted = 0
        st = self._spec_stats
        rt = self.reqtrace
        # Per-window acceptance decoration, thinned by the sampling
        # gate (per-seq aggregation over this drain's folded windows).
        spec_traced = rt is not None and rt.should_sample()
        spec_note: dict = {}
        for entry in entries:
            if len(entry) == 2:
                arr, order = entry
                for i, seq in enumerate(order):
                    req = seq.req
                    if req.done or \
                            len(req.generated) >= req.max_new_tokens:
                        continue
                    tok = int(arr[i])
                    req.generated.append(tok)
                    seq.last_token = tok
                    appended += 1
                continue
            # Speculative window: [B, k+1] rows, committed tokens
            # left-packed, −1 past the commit point. Row length IS the
            # device's data-dependent commit count — fold it into pos
            # (host truth catches up to device truth here).
            arr, order, bucket = entry
            pb = st["per_bucket"].setdefault(
                bucket, {"accepted_tokens": 0, "draft_tokens": 0,
                         "committed_tokens": 0, "dispatches": 0,
                         "rows": 0})
            pb["dispatches"] += 1
            st["dispatches"] += 1
            for i, seq in enumerate(order):
                req = seq.req
                if req.done:
                    continue
                row = arr[i]
                toks = row[row >= 0]
                n = int(toks.shape[0])
                seq.pos += n
                spec_seqs[id(seq)] = seq
                spec_accepted += max(0, n - 1)
                spec_drafted += self.spec_k
                pb["accepted_tokens"] += max(0, n - 1)
                pb["draft_tokens"] += self.spec_k
                pb["committed_tokens"] += n
                pb["rows"] += 1
                st["accepted_tokens"] += max(0, n - 1)
                st["draft_tokens"] += self.spec_k
                st["committed_tokens"] += n
                st["rows"] += 1
                if spec_traced:
                    agg = spec_note.setdefault(id(seq), [seq, 0, 0])
                    agg[1] += max(0, n - 1)
                    agg[2] += self.spec_k
                for t in toks:
                    if len(req.generated) >= req.max_new_tokens:
                        # Overshoot past max_new: pos already advanced
                        # (the KV for these tokens is real and
                        # consistent) but the request is done — the
                        # sequence retires below, blocks free, excess
                        # tokens drop.
                        break
                    req.generated.append(int(t))
                    appended += 1
                seq.n_generated = len(req.generated)
                if n:
                    seq.last_token = int(toks[-1])
        if spec_seqs:
            if spec_drafted:
                from grove_tpu.runtime.metrics import GLOBAL_METRICS
                GLOBAL_METRICS.inc("grove_spec_accepted_tokens",
                                   float(spec_accepted))
                GLOBAL_METRICS.inc("grove_spec_draft_tokens",
                                   float(spec_drafted))
            retired = False
            for seq in spec_seqs.values():
                # Every inflight window for this sequence just folded
                # (a drain consumes ALL pending entries) — pos is
                # device-exact again.
                seq.inflight = 0
                if not seq.req.done and seq.finished() \
                        and seq in self._sched.running:
                    self._sched.retire(seq)
                    self._complete(seq.req)
                    self._composition_dirty = True
                    retired = True
            if retired:
                self._report_metric()
        if spec_note:
            for seq, acc, drafted in spec_note.values():
                rt.note_spec_window(seq.req.rid, self.steps, acc,
                                    drafted)
        if self.telemetry is not None:
            self.telemetry.add_tokens(appended)
        if self._finishing:
            for seq in self._finishing:
                self._complete(seq.req)
            self._finishing = []
            self._report_metric()

    def payload(self) -> dict:
        """Debug view: scheduler + allocator state (the /debug twins
        ride the xprof surface; this is the engine-side snapshot)."""
        return {"engine": "paged", "slots": self.batch,
                "max_len": self.max_len,
                "block_size": self.block_size,
                "prefill_chunk": self.prefill_chunk,
                "queue_depth": self.queue_depth,
                "steps": self.steps, "ticks": self.ticks,
                "completed": len(self.completed),
                "prefix_cache": self._prefix is not None,
                "cow_copies": self.cow_copies,
                "kv_quant": self.kv_quant,
                "spec_decode": self.spec_decode,
                "spec": self.spec_stats(),
                "handoff": self.handoff_view(),
                "schedule": self._sched.payload()}


class PrefillEngine(PagedDecodeEngine):
    """The prefill tier of disaggregated serving (GROVE_DISAGG=1):
    chunked prefill over its OWN block pool and bucket ladder, no
    decode leg at all. A finished prefill detaches from the scheduler
    with its blocks still live and lands in ``outbox`` as a
    ``HandoffPayload`` — the facade pumps the outbox into the decode
    engine's ``adopt``. TTFT is stamped HERE, at handoff-producing
    prefill completion (the same token-emission moment the mono engine
    stamps, so the stamp semantics don't move with the split).

    Requests whose ``max_new_tokens`` is 1 complete on this tier — the
    prefill-sampled token is their whole output, exactly where the
    mono engine completes them — so the facade merges both engines'
    ``completed`` lists."""

    def __init__(self, *args, **kwargs):
        kwargs["spec_decode"] = False   # speculation is a decode-tier
        super().__init__(*args, **kwargs)   # concern; prefill drafts nothing
        self.outbox: deque[HandoffPayload] = deque()
        self.handoffs_produced = 0

    def step(self) -> None:
        """One prefill tick. No decode leg: this engine's running set
        is empty by construction (sequences detach at promotion time),
        which is the whole disaggregation point — the decode tier's
        TPOT never waits on a prompt chunk."""
        if self._sched.has_prefill_work():
            self._prefill_tick()
        self.ticks += 1

    def warmup(self, batches: list[int] | None = None,
               widths: list[int] | None = None,
               prefill_widths: list[int] | None = None) -> int:
        """Pre-build ONLY prefill executables (the base warmup's empty
        ``batches`` list means "full decode ladder", which would bloat
        this tier's lowering pin with dead decode programs)."""
        built = 0
        self._cow_guard(())
        if prefill_widths is None:
            prefill_widths = widths or self._sched.width_buckets
        for W in prefill_widths:
            if W not in self._prefill_jits:
                built += 1
            fn = self._get_prefill(W)
            toks = np.zeros((1, self.prefill_chunk), np.int32)
            table = np.zeros((1, W), np.int32)
            res = fn(self.params, toks, *self._pools(), table,
                     np.int32(0), np.int32(0), np.int32(0))
            self._set_pools(res[1:])
        jax.block_until_ready(self.kv.k)
        return built

    def _finish_prefill(self, seq, logits) -> None:
        """Prefill completion on the disaggregated tier: sample the
        first token and stamp exactly as the mono engine does, then
        detach the sequence WITHOUT freeing its blocks — ownership
        moves to the HandoffPayload until the decode side adopts (or
        this engine dies and the payload dies with its pool)."""
        tok = self._sample_first(logits)
        req = seq.req
        if seq.recompute:
            # Recompute replay: the sampled token is the next DECODE
            # token, not a first token — no stamp rewrite (the mono
            # recompute branch, verbatim).
            req.generated.append(tok)
            if self.telemetry is not None:
                self.telemetry.add_tokens(1)
        else:
            self._stamp_admit(req, time.time(), admit=req.admit_ts or None)
            req.generated.append(tok)
        seq.n_generated = len(req.generated)
        seq.last_token = tok
        if seq.finished():
            # One-token requests never reach the decode tier: the mono
            # engine completes them at _finish_prefill, so does this.
            self._sched.detach_prefill_head(seq)
            self._sched._release_seq(seq)
            self._complete(req)
            self._report_metric()
            return
        self._sched.detach_prefill_head(seq)
        rt = self.reqtrace
        if rt is not None and not seq.recompute:
            # Prefill phase closes at detach; the handoff span runs
            # from the payload's created_ts to adoption on the decode
            # tier (a recompute replay closes its preempt window at
            # adoption instead).
            rt.note_prefill_done(req.rid)
        self.outbox.append(HandoffPayload(
            rid=req.rid, req=req, tokens=seq.tokens, first_token=tok,
            blocks=list(seq.blocks.blocks), pos=seq.pos,
            n_generated=seq.n_generated, recompute=seq.recompute,
            source=self, block_bytes=self._block_bytes,
            trace=rt.live_trace(req.rid) if rt is not None else None))
        self.handoffs_produced += 1
        self._report_metric()

    def _release_handoff(self, payload: HandoffPayload) -> None:
        """Drop a payload's block references (HandoffPayload.release).
        The prompt's full blocks were registered into this tier's
        prefix tree at detach time, so the unref parks them cached —
        the producer keeps its warm prefix across handoffs."""
        self._alloc.free(payload.blocks)

    def accept_recompute(self, seq: PagedSeq) -> None:
        """Take a decode-tier preemption victim for re-prefill: in
        disagg mode ALL prefill — including recompute — runs on this
        tier, so the decode tick stays 100% decode. The victim arrives
        block-less (the decode scheduler released its table); a carrier
        seq re-enters through the preempted queue, whose readmit path
        restores n_generated/preemptions from it."""
        assert not seq.blocks.blocks, "recompute victim still holds blocks"
        carrier = PagedSeq(req=seq.req, tokens=seq.tokens,
                           blocks=SeqBlocks(self._alloc), order=-1,
                           n_generated=seq.n_generated, recompute=True,
                           preemptions=seq.preemptions)
        self._sched.preempted.append(carrier)

    @property
    def queue_depth(self) -> int:
        """Queued + preempted + produced-but-unadopted: an outbox
        payload is still this tier's responsibility until the decode
        side takes it."""
        return super().queue_depth + len(self.outbox)


class DisaggServing:
    """The GROVE_DISAGG=1 serving pair behind one engine interface:
    a ``PrefillEngine`` front door streaming finished KV blocks to a
    ``PagedDecodeEngine`` through the ``serving/handoff.py`` protocol
    (router-less for now — the prefill tier IS the front door, the
    samples/disagg-tiered.yaml PCSG shape). Drivers built for one
    engine (tools/loadgen.run_load, the benches, the smokes) work
    unchanged: submit routes to prefill, step runs prefill tick →
    outbox pump → decode tick, and the liveness/queue/completed
    surfaces merge both tiers."""

    def __init__(self, prefill: PrefillEngine,
                 decode: PagedDecodeEngine) -> None:
        assert prefill.kv_quant == decode.kv_quant, \
            "handoff cannot cross quant modes (no requantize by design)"
        assert prefill.block_size == decode.block_size, \
            "handoff is a block-id remap; block geometry must match"
        assert not decode.spec_decode, \
            "disagg + speculative decoding is not wired yet"
        self.prefill = prefill
        self.decode = decode
        self.telemetry = decode.telemetry
        # One recorder spans the seam (make_disagg hands both tiers
        # the same instance, like the shared telemetry); the decode
        # tier's is authoritative for the facade surface.
        self.reqtrace = decode.reqtrace
        self.ticks = 0

    # -- engine interface (run_load/bench/smoke drivers) --

    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32) -> int:
        return self.prefill.submit(prompt,
                                   max_new_tokens=max_new_tokens)

    def admit_from_queue(self, prefiller=None) -> int:
        # Decode-tier preemption victims re-prefill on the prefill
        # tier (recompute is prefill work); then fresh admissions.
        moved = 0
        d = self.decode._sched.preempted
        while d:
            self.prefill.accept_recompute(d.popleft())
            moved += 1
        return self.prefill.admit_from_queue() + moved

    def step(self) -> None:
        self.prefill.step()
        self._pump()
        self.decode.step()
        self.ticks += 1
        if self.telemetry is not None:
            # The per-engine gauges see only their own half; the facade
            # is the one place the COMBINED load signal exists.
            self.telemetry.sample_gauges(self.queue_depth,
                                         self.kv_lane_utilization)

    def _pump(self) -> None:
        """Move finished prefills into the decode tier, in order. A
        refused adoption (no slot / allocator backpressure) leaves the
        payload at the outbox head for the next tick — blocks stay
        owned by the payload, nothing leaks on either side."""
        out = self.prefill.outbox
        while out:
            if not self.decode.adopt(out[0]):
                break
            out.popleft()

    def run(self, steps: int) -> None:
        for _ in range(steps):
            self.step()
        self.sync()

    def sync(self) -> None:
        self.prefill.sync()
        self.decode.sync()

    def warmup(self, batches: list[int] | None = None,
               widths: list[int] | None = None,
               prefill_widths: list[int] | None = None) -> int:
        """Pre-build both tiers' ladders plus the handoff copy: the
        prefill tier compiles only prefill programs, the decode tier
        only decode programs — each pin is the union a mono engine
        would split."""
        built = self.prefill.warmup(
            prefill_widths=(prefill_widths if prefill_widths is not None
                            else widths))
        built += self.decode.warmup(batches=batches, widths=widths,
                                    prefill_widths=[])
        built += self.decode.warmup_handoff(self.prefill)
        return built

    # -- merged surfaces --

    @property
    def completed(self) -> list:
        """Both tiers' completions (max_new_tokens == 1 requests finish
        on the prefill tier, everything else on decode)."""
        return self.prefill.completed + self.decode.completed

    @property
    def queue_depth(self) -> int:
        return self.prefill.queue_depth + self.decode.queue_depth

    @property
    def kv_lane_utilization(self) -> float:
        """The tighter pool is the backpressure signal."""
        return max(self.prefill.kv_lane_utilization,
                   self.decode.kv_lane_utilization)

    @property
    def _active(self) -> np.ndarray:
        n = (self.prefill._sched.live + len(self.prefill.outbox)
             + self.decode._sched.live)
        if n == 0 and (self.decode._pending or self.decode._finishing
                       or self.prefill._queue
                       or self.prefill._sched.preempted
                       or self.decode._sched.preempted):
            n = 1
        return np.ones((n,), bool)

    @property
    def xprof(self):
        """The decode tier's observatory (each tier keeps its own —
        separately pinned lowering sets are the point; the prefill
        tier's is ``self.prefill.xprof``)."""
        return self.decode.xprof

    @property
    def cache(self) -> PagedKV:
        return self.decode.kv

    @property
    def params(self):
        return self.decode.params

    def handoff_view(self) -> dict:
        return self.decode.handoff_view()

    def replace_prefill(self, prefill: PrefillEngine) -> int:
        """Disaster recovery (chaos: prefill-replica-kill): swap in a
        fresh prefill engine after the old tier died. Un-adopted work —
        queued requests, mid-prefill sequences, outbox payloads whose
        blocks died with the old pool — re-enters the new tier's queue
        with rids intact; produced-but-unshipped first tokens are
        discarded so the replay regenerates them (greedy re-prefill is
        deterministic: bitwise-identical output, the chaos invariant).
        Decode-tier recompute victims keep their generated history and
        re-enter through the recompute path. Returns requests rescued.
        The old engine's allocator state is NOT consulted — a killed
        replica can't be."""
        old = self.prefill
        fresh: list[Request] = []
        carriers: list[PagedSeq] = []

        def _carrier(req, tokens, n_generated, preemptions=0):
            carriers.append(PagedSeq(
                req=req, tokens=np.asarray(tokens, np.int32),
                blocks=SeqBlocks(prefill._alloc), order=-1,
                n_generated=n_generated, recompute=True,
                preemptions=preemptions))

        for p in old.outbox:
            if p.recompute:
                # The replay's decode history is REAL output (including
                # the unshipped token _finish_prefill appended) — it
                # must survive: rebuild the replay input from it.
                _carrier(p.req, np.concatenate([
                    p.req.prompt[:p.req.prompt_len],
                    np.asarray(p.req.generated, np.int32)]),
                    len(p.req.generated))
            else:
                fresh.append(p.req)
        for s in old._sched.prefilling:
            if s.recompute:
                _carrier(s.req, s.tokens, s.n_generated, s.preemptions)
            else:
                fresh.append(s.req)
        fresh.extend(old._queue)
        for req in fresh:
            # Replay from scratch: stamps and produced first tokens
            # belonged to work the dead tier never shipped. Greedy
            # re-prefill regenerates them bitwise-identically.
            req.generated = []
            req.done = False
            req.admit_ts = req.first_token_ts = req.done_ts = 0.0
            req.cached_tokens = 0
            prefill._queue.append(req)
        carriers.extend(old._sched.preempted)
        for c in carriers:
            prefill._sched.preempted.append(c)
        # Completions already made are history, not state — carry them.
        prefill.completed.extend(old.completed)
        prefill._next_rid = max(prefill._next_rid, old._next_rid)
        # Trace continuity across the swap: the replacement tier joins
        # the facade's recorder (rids persist, so rescued requests keep
        # appending to the SAME trace — the chaos-recovery invariant
        # tests/test_reqtrace.py pins). Off stays off uniformly.
        if prefill.reqtrace is not self.reqtrace:
            prefill.reqtrace = self.reqtrace
            prefill._sched.reqtrace = self.reqtrace
        self.prefill = prefill
        self.decode.warmup_handoff(prefill)
        return len(fresh) + len(carriers)

    def payload(self) -> dict:
        return {"engine": "disagg", "ticks": self.ticks,
                "handoff": self.decode.handoff_view(),
                "outbox": len(self.prefill.outbox),
                "prefill": self.prefill.payload(),
                "decode": self.decode.payload()}


def engine_mode() -> str:
    """GROVE_ENGINE=paged|lanes (default paged). ``lanes`` restores the
    seed fixed-lane engine byte-for-byte — the escape hatch every
    rebuild in this repo ships with."""
    mode = os.environ.get("GROVE_ENGINE", "paged")
    if mode not in ("paged", "lanes"):
        raise ValueError(f"GROVE_ENGINE={mode!r} (expected paged|lanes)")
    return mode


def disagg_mode() -> bool:
    """GROVE_DISAGG=1 splits paged serving into a PrefillEngine →
    PagedDecodeEngine pair over the block handoff (default 0: the mono
    PagedDecodeEngine, byte-for-byte the prior behavior). Only the
    paged engine disaggregates — GROVE_ENGINE=lanes ignores this."""
    return os.environ.get("GROVE_DISAGG", "0") == "1"


def make_disagg(cfg: LlamaConfig, key_or_params, *, batch: int = 8,
                mesh=None, prefill_slots: int | None = None,
                prefill_num_blocks: int | None = None,
                telemetry=None, xprof=None, reqtrace=None,
                **common) -> DisaggServing:
    """Build the disaggregated pair: params are resolved ONCE and
    shared (both tiers serve the same model; in a real deployment each
    tier device_puts onto its own slice), each tier gets its OWN block
    pool and Observatory (separately pinned lowering sets are the
    point), and the telemetry is shared — SLO stamps span the seam.

    ``prefill_slots``/``prefill_num_blocks`` size the prefill tier
    independently (the disagg premise: prompt-heavy chips want deeper
    pools and fewer concurrent slots than token-heavy chips); both
    default to the decode tier's geometry."""
    if isinstance(key_or_params, jax.Array) \
            and key_or_params.dtype == jnp.uint32:
        params = llama.init_params(cfg, key_or_params)
    else:
        params = key_or_params
    common.pop("spec_decode", None)  # decode-tier feature, not wired
    common.pop("spec_k", None)
    common.pop("draft_params", None)
    # ONE request recorder for both tiers (the telemetry pattern): a
    # trace follows its rid across the handoff seam with no splice.
    # False when tracing is off so neither tier auto-creates its own.
    if reqtrace is None:
        from grove_tpu.serving import reqtrace as reqtrace_mod
        reqtrace = (reqtrace_mod.RequestObservatory()
                    if reqtrace_mod.enabled() else False)
    pre_kwargs = dict(common)
    if prefill_num_blocks is not None:
        pre_kwargs["num_blocks"] = prefill_num_blocks
    pre = PrefillEngine(cfg, params, batch=prefill_slots or batch,
                        mesh=mesh, telemetry=telemetry,
                        reqtrace=reqtrace, **pre_kwargs)
    dec = PagedDecodeEngine(cfg, params, batch=batch, mesh=mesh,
                            telemetry=telemetry, xprof=xprof,
                            reqtrace=reqtrace, **common)
    return DisaggServing(pre, dec)


def make_engine(cfg: LlamaConfig, key_or_params, *, batch: int = 8,
                max_len: int | None = None,
                host_sync_interval: int = 8,
                sampler: SamplerConfig | None = None,
                quant: str | None = None,
                metric_hook=None, telemetry=None, xprof=None,
                reqtrace=None, mesh=None, mode: str | None = None,
                **paged_kwargs):
    """Engine factory honoring GROVE_ENGINE (and, for the paged
    engine, GROVE_DISAGG). Paged-only knobs (block_size, num_blocks,
    prefill_chunk) pass through ``paged_kwargs`` and are ignored by
    the lanes engine."""
    mode = mode or engine_mode()
    common = dict(batch=batch, max_len=max_len,
                  host_sync_interval=host_sync_interval, sampler=sampler,
                  quant=quant, metric_hook=metric_hook,
                  telemetry=telemetry, xprof=xprof, reqtrace=reqtrace)
    if mode == "lanes":
        return DecodeEngine(cfg, key_or_params, **common)
    if disagg_mode():
        common.pop("xprof")
        common.pop("reqtrace")
        return make_disagg(cfg, key_or_params, mesh=mesh, xprof=xprof,
                           reqtrace=reqtrace, **common, **paged_kwargs)
    return PagedDecodeEngine(cfg, key_or_params, mesh=mesh,
                             **common, **paged_kwargs)
