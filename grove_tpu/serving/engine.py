"""Serving engines: continuous-batching decode + disaggregated prefill.

The workload half of the framework: what runs inside the pods that the
control plane gang-schedules. The reference operator runs third-party
engines (vLLM/SGLang — README.md:35-41); here the engine is first-party
and TPU-shaped:

- fixed decode batch lanes (static shapes; one compiled decode step),
- prefill and decode as separate jitted programs so they can live in
  separate pods (disaggregated serving): ``PrefillWorker`` returns the
  per-sequence KV slab; ``DecodeEngine.insert`` splices it into a free
  lane (the KV-transfer seam — over ICI/DCN in multi-host deployments),
- donated cache buffers (no per-step reallocation),
- a queue-depth metric hook feeding the control plane's autoscaler.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.models import llama
from grove_tpu.models.llama import LlamaConfig
from grove_tpu.ops.kvcache import KVCache


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Token sampling: temperature 0 = greedy argmax; otherwise
    temperature-scaled categorical over the top_k logits (0 = full
    vocab). Compiled into the decode step (static branch)."""

    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0


def sample_tokens(logits: jnp.ndarray, key: jax.Array,
                  cfg: SamplerConfig) -> jnp.ndarray:
    """logits [b, vocab] -> tokens [b] per the sampler config."""
    if cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / cfg.temperature
    if cfg.top_k > 0 and cfg.top_k < scaled.shape[-1]:
        kth = jax.lax.top_k(scaled, cfg.top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # [s] int32 (may be right-padded)
    max_new_tokens: int = 32
    generated: list[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # True prompt length (≠ len(prompt) for padded rows). Lets the engine
    # do all cache-capacity math on the host: after g generated tokens
    # the lane's next write lands at prompt_len + g - 1.
    prompt_len: int = -1
    # SLO stamps (serving/slo.py): host wall-clock, 0.0 = never reached.
    # enqueue/admit/first-token are exact; done is observed at window
    # drain, so it can trail the true completion by interval-1 steps.
    enqueue_ts: float = 0.0
    admit_ts: float = 0.0
    first_token_ts: float = 0.0
    done_ts: float = 0.0

    def __post_init__(self):
        if self.prompt_len < 0:
            self.prompt_len = len(self.prompt)


@dataclasses.dataclass
class PrefillResult:
    """Everything decode needs to continue a sequence: the KV slab and the
    first sampled token (the disaggregation transfer payload)."""

    k: jnp.ndarray        # [layers, s_pad, n_kv, d]
    v: jnp.ndarray        # [layers, s_pad, n_kv, d]
    length: int
    next_token: int


class PrefillWorker:
    """The prefill side of disaggregated serving (chips optimised for
    throughput over long prompts)."""

    def __init__(self, cfg: LlamaConfig, params, batch: int = 1,
                 max_prompt: int | None = None,
                 sampler: SamplerConfig | None = None,
                 quant: str | None = None,
                 prefill_chunk: int | None = None):
        self.cfg = cfg
        self.params = params
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        if quant == "int8":
            from grove_tpu.serving.quant import quantize_params
            self.params = quantize_params(self.params)
        self.batch = batch
        self.max_prompt = max_prompt or cfg.max_seq_len
        self.sampler = sampler or SamplerConfig()
        self._rng = jax.random.PRNGKey(self.sampler.seed)
        # Chunked prefill (llama.prefill_chunked): bounds the attention
        # working set for long prompts — the prefill worker's whole job
        # is long prompts, so this is its natural posture. One-shot stays
        # the default (single executable, exact ragged-lengths logits).
        if prefill_chunk:
            assert self.max_prompt % prefill_chunk == 0, \
                (self.max_prompt, prefill_chunk)
        self.prefill_chunk = prefill_chunk

        def run(params, tokens, lengths, cache):
            return llama.prefill(cfg, params, tokens, cache, lengths)

        self._prefill = jax.jit(run, donate_argnums=(3,))
        self._cache = KVCache.create(cfg.n_layers, batch, self.max_prompt,
                                     cfg.n_kv_heads, cfg.head_dim, cfg.dtype)

    def prefill(self, prompts: list[np.ndarray]) -> list[PrefillResult]:
        """Prefill up to ``batch`` prompts (right-padded to one length)."""
        assert 0 < len(prompts) <= self.batch
        s_pad = self.max_prompt
        toks = np.zeros((self.batch, s_pad), np.int32)
        lengths = np.zeros((self.batch,), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
            lengths[i] = len(p)
        if self.prefill_chunk:
            logits, cache = llama.prefill_chunked(
                self.cfg, self.params, jnp.asarray(toks), self._cache,
                chunk=self.prefill_chunk, lengths=jnp.asarray(lengths))
        else:
            logits, cache = self._prefill(self.params, jnp.asarray(toks),
                                          jnp.asarray(lengths), self._cache)
        self._cache = cache
        if self.sampler.temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            next_tokens = np.asarray(sample_tokens(logits, sub, self.sampler))
        else:
            next_tokens = np.asarray(jnp.argmax(logits, axis=-1))
        out = []
        for i in range(len(prompts)):
            out.append(PrefillResult(
                k=cache.k[:, i], v=cache.v[:, i],
                length=int(lengths[i]), next_token=int(next_tokens[i])))
        return out


class DecodeEngine:
    """Continuous-batching decode over fixed lanes.

    Two operating modes:
    - standalone: ``admit_prompts`` prefills in-engine (single-pod serving,
      also the bench path);
    - disaggregated: ``insert`` splices a PrefillResult produced elsewhere.
    """

    def __init__(self, cfg: LlamaConfig, key_or_params, batch: int = 8,
                 max_len: int | None = None,
                 metric_hook: Callable[[int], None] | None = None,
                 host_sync_interval: int = 8,
                 sampler: SamplerConfig | None = None,
                 quant: str | None = None,
                 telemetry=None,
                 xprof=None):
        self.cfg = cfg
        # Init-only: the sampled step closes over this config at compile
        # time, so later mutation cannot take effect (and is rejected).
        self._sampler = sampler or SamplerConfig()
        if isinstance(key_or_params, jax.Array) and key_or_params.dtype == jnp.uint32:
            self.params = llama.init_params(cfg, key_or_params)
        else:
            self.params = key_or_params
        # Weight-only int8 (serving/quant.py): decode is HBM-bound on the
        # weight read, so this is ~the bandwidth win it looks like.
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        self.quant = quant
        if quant == "int8":
            from grove_tpu.serving.quant import quantize_params
            self.params = quantize_params(self.params)
        self.batch = batch
        self.max_len = max_len or cfg.max_seq_len
        self.metric_hook = metric_hook
        # Optional serving/slo.EngineTelemetry: request-lifecycle stamps
        # and latency histograms, all host-side (None = zero overhead;
        # the JIT path is identical either way).
        self.telemetry = telemetry
        # Completion bookkeeping needs sampled tokens on the host; fetching
        # every step would serialise dispatch behind a device→host sync.
        # Tokens accumulate on device and drain every ``host_sync_interval``
        # steps (a finished lane decodes at most interval-1 wasted steps).
        self.host_sync_interval = max(1, host_sync_interval)
        self.cache = KVCache.create(cfg.n_layers, batch, self.max_len,
                                    cfg.n_kv_heads, cfg.head_dim, cfg.dtype)
        self._tokens = jnp.zeros((batch,), jnp.int32)
        self._active = np.zeros((batch,), bool)
        self._requests: list[Request | None] = [None] * batch
        self._queue: deque[Request] = deque()
        self._pending_tokens: list[jnp.ndarray] = []
        # Steps already pending when a lane was (re)admitted: tokens from
        # before the admission belong to the previous occupant, not the
        # new request.
        self._lane_window_start = np.zeros((batch,), np.int32)
        self._next_rid = 0
        self.completed: list[Request] = []
        self.steps = 0

        sampler_cfg = self._sampler
        self._sampling = sampler_cfg.temperature > 0.0
        self._rng = jax.random.PRNGKey(sampler_cfg.seed)

        def step_greedy(params, tokens, cache):
            logits, cache = llama.decode_step(cfg, params, tokens, cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        def step_sampled(params, tokens, cache, key):
            logits, cache = llama.decode_step(cfg, params, tokens, cache)
            key, sub = jax.random.split(key)
            return sample_tokens(logits, sub, sampler_cfg), cache, key

        # The greedy 3-ary step stays the public compiled surface
        # (benchmarks, raw loops); sampling engines use the key-threaded
        # variant internally and only compile it when actually sampling.
        self._step = jax.jit(step_greedy, donate_argnums=(2,))
        self._step_sampled = jax.jit(step_sampled, donate_argnums=(2,))

        # Block decode: host_sync_interval steps fused into ONE executable
        # via lax.scan, window tokens [K, b] stacked on device. One
        # dispatch + one async fetch per window instead of K dispatches —
        # the difference between dispatch-bound and HBM-bound decode on
        # high-latency transports (the tunnelled PJRT relay most of all).
        K = self.host_sync_interval

        def block_greedy(params, tokens, cache):
            def body(carry, _):
                t, c = carry
                nt, c = step_greedy(params, t, c)
                return (nt, c), nt
            (t, c), window = jax.lax.scan(body, (tokens, cache), None,
                                          length=K)
            return t, c, window

        def block_sampled(params, tokens, cache, key):
            def body(carry, _):
                t, c, k = carry
                nt, c, k = step_sampled(params, t, c, k)
                return (nt, c, k), nt
            (t, c, key), window = jax.lax.scan(body, (tokens, cache, key),
                                               None, length=K)
            return t, c, window, key

        self._step_block = jax.jit(block_greedy, donate_argnums=(2,))
        self._step_block_sampled = jax.jit(block_sampled, donate_argnums=(2,))

        def pf(params, tokens, lengths, cache):
            return llama.prefill(cfg, params, tokens, cache, lengths)

        self._prefill = jax.jit(pf, donate_argnums=(3,))

        # TTFT stamp semantics: by default admit_ts is queue-exit
        # (pre-prefill) and first_token_ts is prefill completion — the
        # split the flight recorder's direct prefill timing enables.
        # GROVE_TTFT_COMPAT=1 restores the historical fused stamp
        # (admit == first-token, both post-prefill).
        self._ttft_compat = os.environ.get("GROVE_TTFT_COMPAT", "0") == "1"

        # Data-plane observatory (serving/xprof.py): compile tracking
        # on the jitted callables, sampled device timings, memory
        # gauges — all host-side. ``xprof`` may be an Observatory (the
        # caller names the scope), None (auto-create unless
        # GROVE_XPROF=0), or False (explicitly off). With the
        # observatory off, every attribute below stays the raw jit and
        # the hot path is exactly the pre-observatory shape.
        self.xprof = None
        if xprof is not False:
            from grove_tpu.serving import xprof as xprof_mod
            if xprof is not None:
                self.xprof = xprof
                self.xprof.cfg = cfg
                self.xprof.batch = batch
                self.xprof.max_len = self.max_len
            elif xprof_mod.enabled():
                self.xprof = xprof_mod.Observatory(
                    cfg=cfg, batch=batch, max_len=self.max_len)
        if self.xprof is not None:
            wrap = self.xprof.compile.wrap
            self._prefill = wrap("prefill", self._prefill)
            self._step = wrap("step", self._step)
            self._step_sampled = wrap("step_sampled", self._step_sampled)
            self._step_block = wrap("step_block", self._step_block)
            self._step_block_sampled = wrap("step_block_sampled",
                                            self._step_block_sampled)

    @property
    def sampler(self) -> SamplerConfig:
        return self._sampler

    # ---- compiled-callable access (benchmarks, custom loops) ----

    def compiled_prefill(self):
        """The jitted prefill: (params, tokens[b,s], lengths[b], cache) ->
        (last-token logits [b, vocab], cache). Stable public surface for
        callers that drive the compiled programs without lane bookkeeping."""
        return self._prefill

    def compiled_step(self):
        """The jitted decode step: (params, tokens[b], cache) ->
        (next tokens [b], cache). Cache argument is donated."""
        return self._step

    def compiled_step_block(self):
        """The jitted K-step decode block (K = host_sync_interval):
        (params, tokens[b], cache) -> (tokens[b], cache, window[K, b]).
        One dispatch decodes K tokens per lane; cache is donated."""
        return self._step_block, self.host_sync_interval

    # ---- request intake ----

    def submit(self, prompt: np.ndarray, max_new_tokens: int = 32) -> int:
        req = Request(rid=self._next_rid, prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=max_new_tokens,
                      enqueue_ts=time.time())
        self._next_rid += 1
        self._queue.append(req)
        self._report_metric()
        return req.rid

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def kv_lane_utilization(self) -> float:
        """Fraction of decode lanes occupied — the KV-headroom signal
        (1.0 = no free lane to admit into)."""
        return float(np.count_nonzero(self._active)) / self.batch

    def _report_metric(self) -> None:
        if self.metric_hook is not None:
            self.metric_hook(len(self._queue))
        if self.telemetry is not None:
            self.telemetry.sample_gauges(len(self._queue),
                                         self.kv_lane_utilization)
        if self.xprof is not None:
            self.xprof.observe_memory(self, self.telemetry)

    def _stamp_admit(self, req: Request, now: float,
                     admit: float | None = None) -> None:
        """Admission stamps. ``now`` is when the first token existed
        (the prefill's sampled token, post-prefill); ``admit`` is when
        the request left the queue (pre-prefill). Historically one
        stamp covered both, which conflated queue-exit with prefill
        completion in the queue-wait histogram — the flight recorder
        times prefill directly now, so the stamps split.
        GROVE_TTFT_COMPAT=1 (or a path with no queue-exit time) fuses
        them back to the old derivation. A request that never went
        through submit() gets enqueue = admit: zero queue wait. Both
        admission paths append the prefill token right after stamping,
        so it is counted here — the drain only sees decode tokens."""
        if self._ttft_compat or admit is None or admit > now:
            admit = now
        req.admit_ts = admit
        if not req.enqueue_ts:
            req.enqueue_ts = admit
        req.first_token_ts = now
        if self.telemetry is not None:
            self.telemetry.add_tokens(1)

    def _complete(self, req: Request) -> None:
        """Shared completion bookkeeping (window drain + lane retire):
        stamp done, record, and fold the request into the telemetry."""
        req.done = True
        req.done_ts = time.time()
        self.completed.append(req)
        if self.telemetry is not None:
            self.telemetry.observe_request(req)

    # ---- standalone mode (bench path) ----

    def admit_prompts(self, prompts: jnp.ndarray,
                      max_new_tokens: int | None = None,
                      lengths: jnp.ndarray | None = None) -> None:
        """Prefill a full batch [batch, s] into the lanes.

        ``lengths`` [batch] gives true per-lane prompt lengths for ragged
        (right-padded) batches; defaults to s for all lanes. With
        ``max_new_tokens`` each lane gets a tracked Request, so the full
        completion bookkeeping runs (the real serving path); without it,
        lanes decode untracked (raw-throughput loops).
        """
        b, s = prompts.shape
        assert b == self.batch
        if lengths is None:
            lengths = jnp.full((b,), s, jnp.int32)
        else:
            lengths = jnp.asarray(lengths, jnp.int32)
        x = self.xprof
        admit_wall = time.time()  # queue-exit: prefill not yet started
        if x is not None:
            t0 = time.perf_counter()
        logits, self.cache = self._prefill(self.params, prompts, lengths,
                                           self.cache)
        if self._sampling:
            self._rng, sub = jax.random.split(self._rng)
            self._tokens = sample_tokens(logits, sub, self._sampler)
        else:
            self._tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if x is not None:
            jax.block_until_ready(self._tokens)
            x.record("prefill", time.perf_counter() - t0, tokens=b)
        self._active[:] = True
        if max_new_tokens is not None:
            prompts_np = np.asarray(prompts)
            lengths_np = np.asarray(lengths)
            first = np.asarray(self._tokens)
            self._lane_window_start[:] = len(self._pending_tokens)
            now = time.time()
            for i in range(b):
                req = Request(rid=self._next_rid, prompt=prompts_np[i],
                              max_new_tokens=max_new_tokens,
                              prompt_len=int(lengths_np[i]))
                self._next_rid += 1
                self._requests[i] = req
                self._stamp_admit(req, now, admit=admit_wall)
                # Count the prefill-sampled token like insert() does —
                # both admission paths account tokens identically.
                req.generated.append(int(first[i]))
            self._report_metric()

    # ---- disaggregated mode ----

    def free_lanes(self) -> list[int]:
        return [i for i in range(self.batch) if not self._active[i]]

    def release_lane(self, lane: int,
                     zero_kv: bool = True) -> "Request | None":
        """Retire a lane's occupant and free the lane (public API for
        callers that drive lane turnover themselves — the disagg bench,
        an external router doing its own completion policy). The KV
        length is zeroed so the lane's next occupant starts from a
        clean cache row, exactly as completion bookkeeping does;
        ``zero_kv=False`` skips that device write for the retire-then-
        immediately-insert hand-off pattern, where insert() stamps the
        lane's length anyway. Returns the retired Request (marked done)
        or None for an untracked/empty lane. Idempotent on free lanes."""
        occupant = self._requests[lane]
        if occupant is not None:
            # Tokens this lane already decoded belong to the retiring
            # request: drain pending windows first, exactly as the
            # completion path does — otherwise up to interval-1 decoded
            # tokens would vanish from the returned Request.
            self._drain()
        req = self._requests[lane]  # the drain may have completed it
        if req is not None:
            self._complete(req)
            self._requests[lane] = None
        if self._active[lane]:
            self._active[lane] = False
            if zero_kv:
                lengths = self.cache.lengths.at[lane].set(0)
                self.cache = self.cache._replace(lengths=lengths)
            self._report_metric()
        return occupant

    def insert(self, lane: int, result: PrefillResult,
               request: Request | None = None) -> None:
        """Splice a prefilled sequence into a free lane (KV handoff)."""
        assert not self._active[lane], f"lane {lane} busy"
        s = result.k.shape[1]
        k = self.cache.k.at[:, lane, :s].set(result.k.astype(self.cache.k.dtype))
        v = self.cache.v.at[:, lane, :s].set(result.v.astype(self.cache.v.dtype))
        lengths = self.cache.lengths.at[lane].set(result.length)
        self.cache = KVCache(k=k, v=v, lengths=lengths)
        self._tokens = self._tokens.at[lane].set(result.next_token)
        self._active[lane] = True
        self._requests[lane] = request
        self._lane_window_start[lane] = len(self._pending_tokens)
        if request is not None:
            request.prompt_len = result.length
            # A request pre-stamped at queue-exit (admit_from_queue)
            # keeps that admit; bare inserts fuse admit = first-token.
            self._stamp_admit(request, time.time(),
                              admit=request.admit_ts or None)
            request.generated.append(result.next_token)

    def admit_from_queue(self, prefiller: PrefillWorker) -> int:
        """Move queued requests through the prefiller into free lanes."""
        admitted = 0
        lanes = self.free_lanes()
        while lanes and self._queue:
            take = min(len(lanes), prefiller.batch, len(self._queue))
            popped = time.time()  # queue-exit, before the prefill runs
            reqs = [self._queue.popleft() for _ in range(take)]
            for r in reqs:
                r.admit_ts = popped
            x = self.xprof
            if x is not None:
                # The worker's jit is NOT one of this engine's wrapped
                # callables, so compile detection watches its cache
                # size directly — a grown cache means this wall was an
                # XLA build, recorded as a compile and kept out of the
                # device-time histogram.
                cache_size = getattr(getattr(prefiller, "_prefill", None),
                                     "_cache_size", None)
                before = cache_size() if cache_size is not None else -1
                t0 = time.perf_counter()
            results = prefiller.prefill([r.prompt for r in reqs])
            if x is not None:
                # prefill() fetches the sampled tokens to host, so the
                # wall here is completed device time, not dispatch.
                dt = time.perf_counter() - t0
                compiled = (cache_size is not None
                            and cache_size() != before)
                if compiled:
                    x.compile.note_external_compile("worker_prefill", dt)
                else:
                    x.recorder.record("prefill", dt, tokens=take)
            for req, res in zip(reqs, results):
                self.insert(lanes.pop(0), res, req)
                admitted += 1
        self._report_metric()
        return admitted

    # ---- decode ----

    def step(self) -> None:
        """One decode step across all lanes (inactive lanes compute too —
        static shapes beat per-lane control flow on TPU)."""
        x = self.xprof
        sampled = x is not None and x.should_sample()
        if sampled:
            # Drain the pending dispatch chain first, then time this
            # step with synced ends: the delta is device time for ONE
            # step, not queued backlog.
            jax.block_until_ready(self._tokens)
            t0 = time.perf_counter()
        if self._sampling:
            self._tokens, self.cache, self._rng = self._step_sampled(
                self.params, self._tokens, self.cache, self._rng)
        else:
            self._tokens, self.cache = self._step(self.params, self._tokens,
                                                  self.cache)
        if sampled:
            jax.block_until_ready(self._tokens)
            x.record("sample" if self._sampling else "step",
                     time.perf_counter() - t0, tokens=self.batch)
        self.steps += 1
        if any(r is not None for r in self._requests):
            self._pending_tokens.append(self._tokens)
            if len(self._pending_tokens) >= self.host_sync_interval:
                self._drain()

    def _lane_has_room(self, req: Request, n: int) -> bool:
        """Host-side capacity check (no device fetch): after g generated
        tokens the lane's next write lands at prompt_len + g - 1, so n
        more steps fit iff that stays within max_len. write_row clamps
        silently past max_len — completing the lane a window early
        prevents the clamp from corrupting the cache tail."""
        return req.prompt_len + len(req.generated) - 1 + n <= self.max_len

    def _drain(self) -> None:
        """Process accumulated single-step tokens: one host fetch per
        window."""
        if not self._pending_tokens:
            return
        if self.xprof is not None:
            t0 = time.perf_counter()
        toks = np.asarray(jnp.stack(self._pending_tokens))  # [w, batch]
        if self.xprof is not None:
            self.xprof.record("host_transfer", time.perf_counter() - t0)
        self._pending_tokens.clear()
        self._process_window(toks, offsets=self._lane_window_start)
        self._lane_window_start[:] = 0

    def _process_window(self, toks: np.ndarray,
                        offsets: np.ndarray | None = None) -> None:
        """Completion bookkeeping over a [w, batch] token window.
        ``offsets[i]`` = rows belonging to lane i's previous occupant
        (single-step path; block windows never contain them)."""
        freed = False
        appended = 0
        for i, req in enumerate(self._requests):
            if req is None or not self._active[i]:
                continue
            start = int(offsets[i]) if offsets is not None else 0
            for t in toks[start:, i]:
                req.generated.append(int(t))
                appended += 1
                if len(req.generated) >= req.max_new_tokens:
                    break
            if len(req.generated) >= req.max_new_tokens or \
                    not self._lane_has_room(req, self.host_sync_interval):
                self._complete(req)
                self._requests[i] = None
                self._active[i] = False
                freed = True
                lengths = self.cache.lengths.at[i].set(0)
                self.cache = self.cache._replace(lengths=lengths)
        if self.telemetry is not None:
            self.telemetry.add_tokens(appended)
        if freed:
            self._report_metric()

    def sync(self) -> None:
        # Drain outstanding bookkeeping, then a tiny host fetch that
        # hard-syncs the dispatch chain (some remote PJRT transports
        # complete block_until_ready early).
        self._drain()
        np.asarray(self._tokens)

    def run(self, steps: int) -> None:
        """Decode ``steps`` steps with block dispatch (throughput mode):
        full windows go through the fused K-step executable — one
        dispatch per window, window tokens accumulating ON DEVICE — and
        bookkeeping drains with a single concatenated fetch at the end
        (on high-RTT transports every mid-run fetch would stall the
        dispatch chain for a round trip). The remainder decodes through
        single steps. Completion is therefore observed per ``run`` call,
        not per window: callers wanting tighter completion latency call
        ``step()`` (latency mode) or ``run`` in smaller chunks. Lane
        admission happens between calls, never inside one."""
        K = self.host_sync_interval
        self._drain()  # single-step leftovers use the offset bookkeeping
        tracked = any(r is not None for r in self._requests)
        if tracked:
            # Deferred bookkeeping can't free lanes mid-run, so cap the
            # block phase at the steps every tracked lane has room for;
            # the rest goes through the draining single-step path.
            safe = min((self.max_len - req.prompt_len
                        - len(req.generated) + 1
                        for req in self._requests if req is not None),
                       default=steps)
            block_steps = min(steps, max(0, safe))
        else:
            block_steps = steps
        steps -= (block_steps // K) * K
        windows: list[jnp.ndarray] = []
        x = self.xprof
        for _ in range(block_steps // K):
            sampled = x is not None and x.should_sample()
            if sampled:
                jax.block_until_ready(self._tokens)
                t0 = time.perf_counter()
            if self._sampling:
                self._tokens, self.cache, window, self._rng = \
                    self._step_block_sampled(self.params, self._tokens,
                                             self.cache, self._rng)
            else:
                self._tokens, self.cache, window = self._step_block(
                    self.params, self._tokens, self.cache)
            if sampled:
                jax.block_until_ready(self._tokens)
                x.record("sample" if self._sampling else "step",
                         time.perf_counter() - t0, steps=K,
                         tokens=K * self.batch)
            self.steps += K
            if tracked:
                windows.append(window)
        fetched = False
        if windows:
            # This fetch doubles as the hard sync for the block phase:
            # it waits on the last window's compute, and its final row
            # IS the current token state — no second round trip needed.
            if x is not None:
                t0 = time.perf_counter()
            toks = np.asarray(windows[0] if len(windows) == 1
                              else jnp.concatenate(windows, axis=0))
            if x is not None:
                x.record("host_transfer", time.perf_counter() - t0)
            self._process_window(toks)
            fetched = True
        for _ in range(steps):
            self.step()
        if steps or not fetched:
            self.sync()
