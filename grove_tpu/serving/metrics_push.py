"""Workload-side metric reporting — the autoscaling feedback loop.

Engines running inside pods push their scaling signal (queue depth, rps)
to the control plane's HTTP API using only the injected environment:

- ``GROVE_CONTROL_PLANE`` — the serve daemon URL (injected by the node
  agent when the cluster runs in serve mode),
- ``GROVE_API_CA`` — CA bundle pinning an https control plane (injected
  alongside the URL when serve runs with --tls),
- ``GROVE_PCSG_NAME`` / ``GROVE_PCLQ_NAME`` — which object the metric
  scales.

Zero dependencies beyond urllib; failures are swallowed (metrics are
advisory — a serving engine must never crash because the control plane
blinked).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

ENV_CONTROL_PLANE = "GROVE_CONTROL_PLANE"
ENV_CA = "GROVE_API_CA"


def push_metric(metric: str, value: float, *, kind: str | None = None,
                name: str | None = None, namespace: str | None = None,
                server: str | None = None) -> bool:
    """Report a metric for this pod's scaling scope.

    Defaults from the injected env: scaling group if the pod belongs to
    one (scaling whole model instances), else its clique. Returns True
    when the control plane accepted the sample.
    """
    server = server or os.environ.get(ENV_CONTROL_PLANE, "")
    if not server:
        return False
    if kind is None or name is None:
        pcsg = os.environ.get("GROVE_PCSG_NAME", "")
        if pcsg:
            kind, name = "PodCliqueScalingGroup", pcsg
        else:
            kind, name = "PodClique", os.environ.get("GROVE_PCLQ_NAME", "")
    if not name:
        return False
    payload = json.dumps({
        "kind": kind, "name": name, "metric": metric, "value": value,
        "namespace": namespace or os.environ.get("GROVE_NAMESPACE", "default"),
        # Per-reporter samples: the registry sums fresh samples across
        # reporters instead of last-write-wins.
        "reporter": os.environ.get("GROVE_POD_NAME", "_default"),
    }).encode()
    headers = {"Content-Type": "application/json"}
    # Workload identity: the kubelet injects GROVE_API_TOKEN alongside the
    # control-plane URL; without it, a server running with
    # require_token_for_metrics rejects the push as anonymous (401).
    token = os.environ.get("GROVE_API_TOKEN", "")
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"{server}/metrics/push", data=payload, method="POST",
        headers=headers)
    try:
        ctx = None
        if server.startswith("https"):
            import ssl
            # Inside the try: a missing/unreadable CA file must degrade
            # to a skipped push, not crash the engine's metrics loop.
            ctx = ssl.create_default_context(
                cafile=os.environ.get(ENV_CA) or None)
        with urllib.request.urlopen(req, timeout=2, context=ctx) as resp:
            return resp.status == 200
    except (OSError, ValueError):
        # URLError, SSLError, FileNotFoundError are all OSError;
        # ValueError covers a malformed CA bundle path/content.
        return False


def queue_depth_hook(**kwargs):
    """A DecodeEngine ``metric_hook``: reports the engine's queue depth.

    Pushes happen on a background thread (latest value wins) — the hook
    itself never blocks the decode loop, even when the control plane is
    slow or down.
    """
    import queue
    import threading

    latest: "queue.Queue[float]" = queue.Queue(maxsize=1)

    def pump() -> None:
        while True:
            depth = latest.get()
            # Coalesce to the most recent value.
            try:
                while True:
                    depth = latest.get_nowait()
            except queue.Empty:
                pass
            push_metric("queue_depth", depth, **kwargs)

    threading.Thread(target=pump, name="metrics-push", daemon=True).start()

    def hook(depth: float) -> None:
        try:
            latest.put_nowait(depth)
        except queue.Full:
            try:
                latest.get_nowait()
            except queue.Empty:
                pass
            try:
                latest.put_nowait(depth)
            except queue.Full:
                pass

    return hook
