"""Workload-side metric reporting — the autoscaling feedback loop.

Engines running inside pods push their scaling signal (queue depth, rps)
to the control plane's HTTP API using only the injected environment:

- ``GROVE_CONTROL_PLANE`` — the serve daemon URL (injected by the node
  agent when the cluster runs in serve mode),
- ``GROVE_API_CA`` — CA bundle pinning an https control plane (injected
  alongside the URL when serve runs with --tls),
- ``GROVE_PCSG_NAME`` / ``GROVE_PCLQ_NAME`` — which object the metric
  scales.

Zero dependencies beyond urllib; failures are swallowed (metrics are
advisory — a serving engine must never crash because the control plane
blinked).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

ENV_CONTROL_PLANE = "GROVE_CONTROL_PLANE"
ENV_CA = "GROVE_API_CA"


def _scope(kind: str | None, name: str | None,
           namespace: str | None) -> tuple[str, str, str] | None:
    """Resolve the scaling scope from args or the injected env:
    scaling group if the pod belongs to one (scaling whole model
    instances), else its clique. None = nothing to report against."""
    if kind is None or name is None:
        pcsg = os.environ.get("GROVE_PCSG_NAME", "")
        if pcsg:
            kind, name = "PodCliqueScalingGroup", pcsg
        else:
            kind, name = "PodClique", os.environ.get("GROVE_PCLQ_NAME", "")
    if not name:
        return None
    return (kind, name,
            namespace or os.environ.get("GROVE_NAMESPACE", "default"))


def push_metric(metric: str, value: float, *, kind: str | None = None,
                name: str | None = None, namespace: str | None = None,
                server: str | None = None) -> bool:
    """Report one metric for this pod's scaling scope. Returns True
    when the control plane accepted the sample."""
    scope = _scope(kind, name, namespace)
    if scope is None:
        return False
    kind, name, namespace = scope
    return _post({
        "kind": kind, "name": name, "metric": metric, "value": value,
        "namespace": namespace,
        # Per-reporter samples: the registry aggregates fresh samples
        # across reporters instead of last-write-wins.
        "reporter": os.environ.get("GROVE_POD_NAME", "_default"),
    }, server)


def push_samples(samples: list[dict], *, kind: str | None = None,
                 name: str | None = None, namespace: str | None = None,
                 server: str | None = None) -> bool:
    """Batched push: ONE POST carrying every sample in ``samples``
    (each ``{"metric", "value"}`` with an optional ``"agg"`` —
    sum|max|avg — telling the registry how to combine reporters).

    This is how an engine ships its whole SLO digest (queue depth, KV
    utilization, TTFT/TPOT percentiles — serving/slo.samples_for_push)
    per reporting tick: the single-metric ``push_metric`` would cost
    one control-plane round trip per signal."""
    scope = _scope(kind, name, namespace)
    if scope is None or not samples:
        return False
    kind, name, namespace = scope
    return _post({
        "kind": kind, "name": name, "namespace": namespace,
        "reporter": os.environ.get("GROVE_POD_NAME", "_default"),
        "samples": [{"metric": s["metric"], "value": s["value"],
                     **({"agg": s["agg"]} if s.get("agg") else {})}
                    for s in samples],
    }, server)


def _post(payload_dict: dict, server: str | None) -> bool:
    server = server or os.environ.get(ENV_CONTROL_PLANE, "")
    if not server:
        return False
    payload = json.dumps(payload_dict).encode()
    headers = {"Content-Type": "application/json"}
    # Workload identity: the kubelet injects GROVE_API_TOKEN alongside the
    # control-plane URL; without it, a server running with
    # require_token_for_metrics rejects the push as anonymous (401).
    token = os.environ.get("GROVE_API_TOKEN", "")
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"{server}/metrics/push", data=payload, method="POST",
        headers=headers)
    try:
        ctx = None
        if server.startswith("https"):
            import ssl
            # Inside the try: a missing/unreadable CA file must degrade
            # to a skipped push, not crash the engine's metrics loop.
            ctx = ssl.create_default_context(
                cafile=os.environ.get(ENV_CA) or None)
        with urllib.request.urlopen(req, timeout=2, context=ctx) as resp:
            return resp.status == 200
    except (OSError, ValueError):
        # URLError, SSLError, FileNotFoundError are all OSError;
        # ValueError covers a malformed CA bundle path/content.
        return False


def start_telemetry_pump(telemetry, interval: float = 2.0, stop=None,
                         **kwargs):
    """Background thread pushing an EngineTelemetry's full SLO digest
    (serving/slo.samples_for_push) every ``interval`` seconds as ONE
    batched POST — the digest twin of ``queue_depth_hook``. ``stop``
    (a threading.Event) ends the pump; push failures are swallowed like
    every other metrics path (advisory, never crash the engine).
    Returns the started thread."""
    import threading

    from grove_tpu.serving.slo import samples_for_push

    stop = stop or threading.Event()

    def pump() -> None:
        while not stop.is_set():
            try:
                push_samples(samples_for_push(telemetry), **kwargs)
            except Exception:  # noqa: BLE001 - advisory path
                pass
            stop.wait(interval)

    t = threading.Thread(target=pump, name="slo-push", daemon=True)
    t.stop_event = stop
    t.start()
    return t


def queue_depth_hook(**kwargs):
    """A DecodeEngine ``metric_hook``: reports the engine's queue depth.

    Pushes happen on a background thread (latest value wins) — the hook
    itself never blocks the decode loop, even when the control plane is
    slow or down.
    """
    import queue
    import threading

    latest: "queue.Queue[float]" = queue.Queue(maxsize=1)

    def pump() -> None:
        while True:
            depth = latest.get()
            # Coalesce to the most recent value.
            try:
                while True:
                    depth = latest.get_nowait()
            except queue.Empty:
                pass
            push_metric("queue_depth", depth, **kwargs)

    threading.Thread(target=pump, name="metrics-push", daemon=True).start()

    def hook(depth: float) -> None:
        try:
            latest.put_nowait(depth)
        except queue.Full:
            try:
                latest.get_nowait()
            except queue.Empty:
                pass
            try:
                latest.put_nowait(depth)
            except queue.Full:
                pass

    return hook
