"""Checkpoint/resume for the workload stack (orbax-backed).

The reference has no workload checkpointing (SURVEY.md §5 — the engine's
job); since grove-tpu ships the engine, it ships the checkpointing too:
param save/restore with sharding-aware loading (restored leaves land
directly on the serving mesh), plus serving-engine warm restart.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def save_params(path: str, params: Any, step: int = 0) -> str:
    """Save a param pytree; returns the checkpoint directory."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckpt:
        target = os.path.join(path, str(step))
        ckpt.save(target, params)
    return target


def load_params(path: str, step: int = 0,
                like: Any | None = None) -> Any:
    """Restore a param pytree. ``like`` (a pytree of arrays or
    ShapeDtypeStructs with shardings) makes restoration land shards
    directly on the target mesh — no host round-trip."""
    path = os.path.abspath(os.path.join(path, str(step)))
    with ocp.StandardCheckpointer() as ckpt:
        if like is None:
            return ckpt.restore(path)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            like)
        return ckpt.restore(path, abstract)


def latest_step(path: str) -> int | None:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = [int(d) for d in os.listdir(path) if d.isdigit()]
    return max(steps) if steps else None


# ---- engine warm restart (the disruption contract's checkpoint path) ----


def save_engine(path: str, engine: Any, step: int | None = None) -> str:
    """Checkpoint a serving engine's params; returns the checkpoint
    directory. ``step`` defaults to one past the latest existing step
    so repeated barriers (a roll's per-victim checkpoints, storm
    coalescing) never clobber the previous durable state."""
    if step is None:
        prev = latest_step(path)
        step = 0 if prev is None else prev + 1
    return save_params(path, engine.params, step=step)


def warm_restart(path: str, engine: Any, step: int | None = None) -> int:
    """Restore the latest (or given) checkpoint onto a serving engine
    in place — restored leaves land directly on the engine's current
    mesh via the sharding-aware loader, so the relanded replica of an
    evacuated gang resumes serving without re-downloading or
    re-sharding weights. Returns the step restored."""
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint steps under {path!r} to warm-restart from")
    engine.params = load_params(path, step, like=engine.params)
    return step


def engine_responder(engine: Any, path: str):
    """Build a disruption-barrier checkpoint responder for ``engine``
    (grove_tpu/disruption): register it with
    ``disruption.register_responder(gang_name, engine_responder(e, d))``
    and every planned eviction of the gang — defrag migration, rolling
    update, spot reclaim — flushes the engine's params durably before
    its pods are drained; the relanded replica ``warm_restart``s from
    the same directory. Raising propagates to the reclaim controller's
    retry/backoff loop, so a transiently failing save is retried until
    the deadline."""

    def respond(_notice) -> None:
        save_engine(path, engine)

    return respond
