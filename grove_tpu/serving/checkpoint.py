"""Checkpoint/resume for the workload stack (orbax-backed).

The reference has no workload checkpointing (SURVEY.md §5 — the engine's
job); since grove-tpu ships the engine, it ships the checkpointing too:
param save/restore with sharding-aware loading (restored leaves land
directly on the serving mesh), plus serving-engine warm restart.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def save_params(path: str, params: Any, step: int = 0) -> str:
    """Save a param pytree; returns the checkpoint directory."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckpt:
        target = os.path.join(path, str(step))
        ckpt.save(target, params)
    return target


def load_params(path: str, step: int = 0,
                like: Any | None = None) -> Any:
    """Restore a param pytree. ``like`` (a pytree of arrays or
    ShapeDtypeStructs with shardings) makes restoration land shards
    directly on the target mesh — no host round-trip."""
    path = os.path.abspath(os.path.join(path, str(step)))
    with ocp.StandardCheckpointer() as ckpt:
        if like is None:
            return ckpt.restore(path)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            like)
        return ckpt.restore(path, abstract)


def latest_step(path: str) -> int | None:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = [int(d) for d in os.listdir(path) if d.isdigit()]
    return max(steps) if steps else None
