from grove_tpu.serving.engine import (DecodeEngine, PagedDecodeEngine,
                                      PrefillResult, PrefillWorker,
                                      engine_mode, make_engine)

__all__ = ["DecodeEngine", "PagedDecodeEngine", "PrefillResult",
           "PrefillWorker", "engine_mode", "make_engine"]
