from grove_tpu.serving.engine import DecodeEngine, PrefillResult, PrefillWorker

__all__ = ["DecodeEngine", "PrefillResult", "PrefillWorker"]
