"""Data-plane observatory — XLA compile/step/memory telemetry for the
serving engine (docs/design/data-plane-observability.md).

The control plane has been observable end to end since PR 6 (write
attribution, deploy milestones, serving SLO digests), but the JAX
execution layer underneath ``DecodeEngine`` was a black box: a slow
round could not say whether the framework was slow, the backend was
degraded, or the backend never existed (the BENCH_r01–r05 blind-zero
era). This module gives the engine the same depth the store got —
three instruments, all host-side, NOTHING on the JIT path:

- **CompileTracker** wraps the engine's jitted callables
  (``compiled_prefill``/``compiled_step``/``compiled_step_block``)
  and records compile wall time and recompile events into
  ``grove_compile_seconds{fn}`` / ``grove_recompiles_total{fn,reason}``.
  Detection rides ``jit.__wrapped__``-free introspection: the jit
  cache size before/after each dispatch (a grown cache IS a compile),
  classified as first / shape-change / cache-evict from the argument
  signature. A recompile burst inside a sliding window raises a
  recompile-storm warning (the shape-churn failure mode that silently
  eats serving throughput).
- **FlightRecorder** is a bounded ring sampling every Nth decode step
  with host-side ``block_until_ready`` device timings, split into
  prefill / step / sample / host_transfer phases, feeding the
  pinned-bucket ``grove_device_step_seconds{phase}`` histograms plus
  MFU / HBM-utilization estimates from the model's FLOP/byte counts
  against the chip roofline (on the CPU backend the roofline is the
  v5e datasheet and the payload stamps the numbers as model-derived
  estimates, never as measurements).
- **Memory accounting** reads live ``device.memory_stats()`` where the
  backend supports it (TPU) and falls back to model-derived byte
  counts (KV cache array sizes + live weight bytes) otherwise, feeding
  ``grove_hbm_bytes{kind}`` gauges and a KV-headroom signal the
  ``EngineTelemetry`` digest pushes alongside TTFT/TPOT.

Surfaces follow the house pattern: ``GET /debug/xprof/<ns>/<name>``
(server.py), ``Client.debug_xprof`` / ``HttpClient.debug_xprof``
twins, and ``grovectl engine-profile`` (phase breakdown with the
hottest phase starred, compile table, memory bar).

``GROVE_XPROF=0`` disables the observatory entirely: the engine's hot
path is then exactly the pre-observatory shape (no wrappers, no
sampling branches taken, no syncs). The overhead with it ON is pinned
<5% of engine tokens/sec by the dual estimator in tests/test_xprof.py.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import threading
import time
import weakref

logger = logging.getLogger("grove.xprof")

# Decode-step phases the flight recorder attributes device time to.
# "step" is the greedy decode dispatch (single or per-step normalized
# block), "sample" the key-threaded sampled variant, "host_transfer"
# the window drain's device→host fetch. The paged engine (PR 15) maps
# onto the same split: chunked-prefill dispatches sample into
# "prefill" (block_until_ready-bracketed, 1/N gated), bucketed decode
# dispatches into "step"/"sample" — one catalog for both engines, so
# /debug/xprof reads the same under GROVE_ENGINE=paged|lanes.
PHASES = ("prefill", "step", "sample", "host_transfer")

# Recompile-storm window: more than STORM_THRESHOLD non-first compiles
# inside STORM_WINDOW_S means shapes are churning (a dynamic-shape leak
# into the serving path) — warn loudly, once per window.
STORM_WINDOW_S = 60.0
STORM_THRESHOLD = 3

# Datasheet roofline defaults (v5e, per chip) — the same knobs bench.py
# honors, so utilization estimates agree across surfaces.
PEAK_FLOPS = float(os.environ.get("GROVE_PEAK_FLOPS", 197e12))
PEAK_HBM_BW = float(os.environ.get("GROVE_PEAK_HBM_BW", 819e9))


def enabled() -> bool:
    """The observatory kill switch, read at engine construction (same
    contract as GROVE_TRACE/GROVE_WRITE_OBS: 0 = the exact pre-feature
    hot path)."""
    return os.environ.get("GROVE_XPROF", "1") != "0"


# ---- model cost functions (shared with bench.py — one derivation) ----

def decode_flops_per_token(cfg, ctx: int) -> float:
    """Model FLOPs to decode one token at context length ``ctx``.

    Matmul weights count 2 FLOPs/param (multiply+add); attention adds
    the logits and value matmuls against the KV cache. Embedding lookup
    and norms are negligible.
    """
    c = cfg
    w_matmul = (c.n_layers * (c.d_model * c.n_heads * c.head_dim       # wq
                              + 2 * c.d_model * c.n_kv_heads * c.head_dim
                              + c.n_heads * c.head_dim * c.d_model     # wo
                              + 3 * c.d_model * c.d_ff)                # mlp
                + c.d_model * c.vocab_size)                            # head
    attn = 4 * ctx * c.n_layers * c.n_heads * c.head_dim
    return 2.0 * w_matmul + attn


def prefill_flops_per_token(cfg, prompt_len: int) -> float:
    """Model FLOPs per prompt token: weight matmuls plus causal
    attention at the average context (prompt_len / 2)."""
    c = cfg
    w_matmul = (c.n_layers * (c.d_model * c.n_heads * c.head_dim
                              + 2 * c.d_model * c.n_kv_heads * c.head_dim
                              + c.n_heads * c.head_dim * c.d_model
                              + 3 * c.d_model * c.d_ff)
                + c.d_model * c.vocab_size)
    attn = 4 * (prompt_len / 2) * c.n_layers * c.n_heads * c.head_dim
    return 2.0 * w_matmul + attn


def decode_hbm_bytes_per_token(cfg, cache_len: int, batch: int,
                               weight_bytes: float | None = None,
                               kv_quant: str = "off") -> float:
    """HBM bytes moved per decoded token: full weight read amortized
    over the batch, plus this lane's KV cache read and one-entry write.
    ``cache_len`` is the ALLOCATED cache length — the padded read is
    what the implementation actually moves, regardless of live context.
    ``weight_bytes`` overrides the bf16 weight size (int8 quantization
    halves the read; the roofline must use what actually crosses HBM).
    ``kv_quant`` does the same for the cache side: int8 paged KV moves
    int8 payload plus f32 scales. Both KV terms derive from serving/
    quant.kv_bytes_per_token_per_layer — the ONE shared derivation, so
    this roofline and the engine's block-byte gauges cannot drift."""
    from grove_tpu.serving.quant import kv_bytes_per_token_per_layer
    per_tok_layer = kv_bytes_per_token_per_layer(cfg, kv_quant)
    kv_read = cfg.n_layers * cache_len * per_tok_layer
    kv_write = cfg.n_layers * per_tok_layer
    weights = cfg.params_bytes if weight_bytes is None else weight_bytes
    return weights / batch + kv_read + kv_write


# ---- compile observability ----

@dataclasses.dataclass
class CompileEvent:
    fn: str
    seconds: float
    reason: str        # first | shape-change | cache-evict
    ts: float


def _arg_signature(args) -> tuple:
    """Abstract signature of a call's array leaves: (shape, dtype)
    tuples — exactly what jit keys its executable cache on. Computed
    only when a compile was detected (never on the steady path)."""
    import jax
    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        sig.append((tuple(shape) if shape is not None else type(leaf),
                    str(dtype)))
    return tuple(sig)


class CompileTracker:
    """Wraps jitted callables and attributes every executable build.

    The wrapper is transparent (same args, same returns, donation
    semantics untouched — it only *calls*); per dispatch it costs two
    ``_cache_size()`` reads and two clock reads. When the jit cache
    grew across a call, that call compiled, and its wall time is
    recorded as the compile time (dispatch cost is noise next to an
    XLA build)."""

    EVENT_CAPACITY = 256

    def __init__(self, metrics=None) -> None:
        self._lock = threading.Lock()
        self._metrics = metrics
        self._seen_sigs: dict[str, set] = {}
        self._compiles: dict[str, int] = collections.defaultdict(int)
        self._recompiles: dict[str, int] = collections.defaultdict(int)
        self._seconds: dict[str, float] = collections.defaultdict(float)
        self._last: dict[str, CompileEvent] = {}
        self.events: collections.deque[CompileEvent] = collections.deque(
            maxlen=self.EVENT_CAPACITY)
        # Non-first compile timestamps inside the storm window.
        self._storm_ring: collections.deque[float] = collections.deque(
            maxlen=64)
        self._storm_warned_at = 0.0
        self.storms = 0
        # True when the most recent wrapped call built an executable —
        # the flight recorder drops that dispatch's timing (its wall is
        # compile time, already recorded in grove_compile_seconds, and
        # would poison the device-step histogram).
        self.last_call_compiled = False

    def wrap(self, name: str, jitted):
        cache_size = getattr(jitted, "_cache_size", None)

        def wrapped(*args, **kwargs):
            before = cache_size() if cache_size is not None else -1
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            after = cache_size() if cache_size is not None else -1
            self.last_call_compiled = after != before
            if after != before or cache_size is None:
                # cache_size unavailable: fall back to signature-only
                # detection (a new signature implies a compile).
                self._on_compile(name, time.perf_counter() - t0,
                                 _arg_signature((args, kwargs)),
                                 confirmed=after != before)
            return out

        wrapped.__name__ = f"xprof_{name}"
        wrapped.__wrapped__ = jitted
        return wrapped

    def _on_compile(self, name: str, seconds: float, sig: tuple,
                    confirmed: bool) -> None:
        now = time.time()
        with self._lock:
            seen = self._seen_sigs.setdefault(name, set())
            if not confirmed and sig in seen:
                return  # signature-only mode: steady repeat, no compile
            if not seen:
                reason = "first"
            elif sig in seen:
                reason = "cache-evict"
            else:
                reason = "shape-change"
            seen.add(sig)
            self._compiles[name] += 1
            self._seconds[name] += seconds
            ev = CompileEvent(name, seconds, reason, now)
            self._last[name] = ev
            self.events.append(ev)
            storm = False
            if reason != "first":
                self._recompiles[name] += 1
                self._storm_ring.append(now)
                recent = [t for t in self._storm_ring
                          if now - t <= STORM_WINDOW_S]
                if (len(recent) > STORM_THRESHOLD
                        and now - self._storm_warned_at > STORM_WINDOW_S):
                    self._storm_warned_at = now
                    self.storms += 1
                    storm = True
        if self._metrics is not None:
            self._metrics.observe("grove_compile_seconds", seconds, fn=name)
            self._metrics.inc("grove_recompiles_total", fn=name,
                              reason=reason)
            if storm:
                self._metrics.inc("grove_recompile_storms_total")
        if storm:
            logger.warning(
                "recompile storm: >%d recompiles inside %.0fs (last: %s "
                "%.2fs, %s) — shapes are churning on the serving path",
                STORM_THRESHOLD, STORM_WINDOW_S, name, seconds, reason)

    def note_external_compile(self, name: str, seconds: float) -> None:
        """Record a compile observed OUTSIDE a wrapped callable (the
        engine watches a PrefillWorker's jit cache on the
        admit_from_queue path). One synthetic signature per name: the
        first build classifies ``first``, later ones ``cache-evict``
        (the external watcher cannot see argument shapes)."""
        self._on_compile(name, seconds, ("external",), confirmed=True)

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._compiles)

    def recompile_count(self) -> int:
        with self._lock:
            return sum(self._recompiles.values())

    def total_seconds(self) -> float:
        with self._lock:
            return sum(self._seconds.values())

    def payload(self) -> dict:
        with self._lock:
            fns = []
            for name in sorted(self._compiles):
                last = self._last.get(name)
                fns.append({
                    "fn": name,
                    "compiles": self._compiles[name],
                    "recompiles": self._recompiles.get(name, 0),
                    "total_seconds": round(self._seconds[name], 4),
                    "last_reason": last.reason if last else "",
                    "last_seconds": round(last.seconds, 4) if last else 0.0,
                })
            return {"fns": fns,
                    "total_seconds": round(sum(self._seconds.values()), 4),
                    "recompiles": sum(self._recompiles.values()),
                    "storms": self.storms}


# ---- decode-step flight recorder ----

@dataclasses.dataclass
class StepSample:
    ts: float
    phase: str
    seconds: float     # whole dispatch wall (device time: synced ends)
    steps: int         # decode steps covered (blocks: K)
    tokens: int        # tokens the dispatch produced


class FlightRecorder:
    """Bounded ring of sampled device timings (the PR 3 trace-ring
    shape, scoped to one engine). ``should_sample`` gates every hook:
    one modulo per step when enabled, nothing at all when the
    observatory is off."""

    def __init__(self, capacity: int = 1024, sample_every: int = 16,
                 metrics=None) -> None:
        self.capacity = capacity
        self.sample_every = max(1, sample_every)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._ring: collections.deque[StepSample] = collections.deque(
            maxlen=capacity)
        self.samples_total = 0
        self._dispatches = 0

    def should_sample(self) -> bool:
        """Every Nth DISPATCH (single step or fused K-step block) is
        sampled — counting dispatches, not steps, keeps the sync cost
        at 1/N of dispatches regardless of block size (counting steps
        would sample every block once K >= N)."""
        self._dispatches += 1
        return (self._dispatches - 1) % self.sample_every == 0

    def record(self, phase: str, seconds: float, steps: int = 1,
               tokens: int = 0) -> None:
        per_step = seconds / max(1, steps)
        with self._lock:
            self._ring.append(StepSample(time.time(), phase, seconds,
                                         steps, tokens))
            self.samples_total += 1
        if self._metrics is not None:
            self._metrics.observe("grove_device_step_seconds", per_step,
                                  phase=phase)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> list[StepSample]:
        with self._lock:
            return list(self._ring)

    def phase_stats(self) -> dict[str, dict]:
        """Per-phase stats over the ring: count, total device seconds,
        per-step p50/p95 ms, tokens. Computed at read time — the record
        path stays append-only."""
        out: dict[str, dict] = {}
        for s in self.snapshot():
            d = out.setdefault(s.phase, {"count": 0, "total_s": 0.0,
                                         "steps": 0, "tokens": 0,
                                         "_per_step": []})
            d["count"] += 1
            d["total_s"] += s.seconds
            d["steps"] += s.steps
            d["tokens"] += s.tokens
            d["_per_step"].append(s.seconds / max(1, s.steps))
        for d in out.values():
            vals = sorted(d.pop("_per_step"))
            d["total_s"] = round(d["total_s"], 6)
            d["p50_ms"] = round(vals[len(vals) // 2] * 1e3, 4)
            d["p95_ms"] = round(
                vals[min(len(vals) - 1, int(len(vals) * 0.95))] * 1e3, 4)
        return out


# ---- memory accounting ----

def memory_snapshot(engine) -> dict:
    """Byte accounting for one engine: live ``device.memory_stats()``
    where the backend supports it (source "device"), model-derived
    array/weight sizes otherwise (source "model-estimate" — the CPU
    backend returns no stats, and the payload must say the numbers are
    derived, not measured)."""
    from grove_tpu.serving.quant import params_bytes as live_params_bytes

    cache = engine.cache
    # PagedKV.pool_bytes includes the int8 dequant-scale pools; the
    # lanes engine's contiguous cache has no such property and falls
    # back to the raw payload arrays. A speculative engine's draft
    # pool is real HBM too.
    kv_bytes = int(getattr(cache, "pool_bytes", None)
                   or (cache.k.nbytes + cache.v.nbytes))
    draft = getattr(engine, "draft_kv", None)
    if draft is not None:
        kv_bytes += int(draft.k.nbytes + draft.v.nbytes)
    weight_bytes = int(live_params_bytes(engine.params))
    stats, limit, in_use = None, 0, 0
    try:
        dev = next(iter(engine.cache.k.devices()))
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — backends without the API
        stats = None
    if stats:
        in_use = int(stats.get("bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
    source = "device" if stats else "model-estimate"
    total = in_use if stats else kv_bytes + weight_bytes
    workspace = max(0, total - kv_bytes - weight_bytes)
    # KV headroom: how much the KV working set could still grow. With
    # live stats it is the device's free fraction; model-derived it is
    # the unused fraction of the allocated cache (lane occupancy).
    if stats and limit:
        headroom = max(0.0, 1.0 - total / limit)
    else:
        headroom = max(0.0, 1.0 - engine.kv_lane_utilization)
    return {"kv_cache_bytes": kv_bytes, "weight_bytes": weight_bytes,
            "workspace_bytes": workspace, "total_bytes": total,
            "limit_bytes": limit, "source": source,
            "kv_headroom": round(headroom, 4)}


# ---- the observatory ----

class Observatory:
    """One engine's data-plane instruments, bundled: compile tracker,
    flight recorder, memory gauges, roofline estimates. Construction
    is cheap; everything heavy happens only on sampled events."""

    MEMORY_MIN_INTERVAL_S = 0.25

    def __init__(self, cfg=None, batch: int = 1, max_len: int = 0,
                 capacity: int | None = None,
                 sample_every: int | None = None,
                 metrics=None, name: str | None = None,
                 namespace: str = "default") -> None:
        if metrics is None:
            from grove_tpu.runtime.metrics import GLOBAL_METRICS
            metrics = GLOBAL_METRICS
        if capacity is None:
            capacity = int(os.environ.get("GROVE_XPROF_RING", 1024))
        if sample_every is None:
            sample_every = int(os.environ.get("GROVE_XPROF_SAMPLE", 16))
        self.cfg = cfg
        self.batch = batch
        self.max_len = max_len
        self._metrics = metrics
        self.compile = CompileTracker(metrics=metrics)
        self.recorder = FlightRecorder(capacity=capacity,
                                       sample_every=sample_every,
                                       metrics=metrics)
        self.namespace = namespace
        self.name = name or _next_auto_name()
        self._last_memory: dict | None = None
        self._last_memory_ts = 0.0
        self._weight_bytes: int | None = None
        # Engine-pushed riders (set by the paged engine when the
        # corresponding feature is on, None otherwise).
        self.spec: dict | None = None   # engine.spec_stats() shape
        self.handoff: dict | None = None  # engine.handoff_view() shape
        self.kv_quant: str = "off"      # KV byte basis for the roofline
        register(self)

    # -- hooks the engine calls --

    def should_sample(self) -> bool:
        return self.recorder.should_sample()

    def record(self, phase: str, seconds: float, steps: int = 1,
               tokens: int = 0) -> None:
        if self.compile.last_call_compiled and phase != "host_transfer":
            return  # that wall was an XLA build, not a device step
        self.recorder.record(phase, seconds, steps=steps, tokens=tokens)

    def observe_memory(self, engine, telemetry=None) -> None:
        """Refresh the memory gauges from the engine's live state
        (rate-limited — admission and drain call this opportunistically,
        and a submit storm must not turn it into a syscall storm)."""
        now = time.time()
        if now - self._last_memory_ts < self.MEMORY_MIN_INTERVAL_S:
            return
        self._last_memory_ts = now
        mem = memory_snapshot(engine)
        self._last_memory = mem
        self._weight_bytes = mem["weight_bytes"]
        scope = f"{self.namespace}/{self.name}"
        for kind, key in (("kv_cache", "kv_cache_bytes"),
                          ("weights", "weight_bytes"),
                          ("workspace", "workspace_bytes"),
                          ("total", "total_bytes")):
            self._metrics.set("grove_hbm_bytes", float(mem[key]),
                              kind=kind, scope=scope)
        # getattr-guarded: tests pass telemetry doubles that only
        # implement the SLO hooks.
        push = getattr(telemetry, "sample_memory", None)
        if push is not None:
            push(mem)

    # -- derived views --

    def backend(self) -> dict:
        try:
            import jax
            dev = jax.devices()[0]
            platform, kind = dev.platform, dev.device_kind
        except Exception:  # noqa: BLE001 — backend init failed
            platform, kind = "unknown", "unknown"
        return {"platform": platform, "device_kind": kind,
                "estimated": platform not in ("tpu", "axon")}

    def throughput_estimate(self, stats: dict | None = None,
                            ) -> dict | None:
        """Tokens/sec over the ring's decode samples placed against the
        roofline. On non-TPU backends the peaks are still the v5e
        datasheet (comparable across rounds) and the whole block is
        stamped ``basis: model-estimate``. ``stats`` lets payload()
        reuse one phase_stats() snapshot instead of re-walking the
        ring under the recorder lock."""
        if self.cfg is None:
            return None
        if stats is None:
            stats = self.recorder.phase_stats()
        decode = [stats[p] for p in ("step", "sample") if p in stats]
        tokens = sum(d["tokens"] for d in decode)
        secs = sum(d["total_s"] for d in decode)
        if not tokens or secs <= 0:
            return None
        tps = tokens / secs
        ctx = max(1, self.max_len // 2)
        flops_tok = decode_flops_per_token(self.cfg, ctx)
        bytes_tok = decode_hbm_bytes_per_token(
            self.cfg, self.max_len or self.cfg.max_seq_len,
            max(1, self.batch), weight_bytes=self._weight_bytes,
            kv_quant=self.kv_quant)
        backend = self.backend()
        return {
            "tokens_per_sec_est": round(tps, 1),
            "mfu_est": round(tps * flops_tok / PEAK_FLOPS, 6),
            "hbm_util_est": round(tps * bytes_tok / PEAK_HBM_BW, 6),
            "basis": ("device-sampled vs v5e datasheet"
                      if not backend["estimated"]
                      else "model-estimate (CPU backend; v5e datasheet "
                           "roofline for cross-round comparability)"),
            "estimated": backend["estimated"],
        }

    def payload(self) -> dict:
        """The /debug/xprof payload (one shape for both client twins;
        ``render_engine_profile`` and grovectl render it)."""
        phases = self.recorder.phase_stats()
        hottest = max(phases, key=lambda p: phases[p]["total_s"]) \
            if phases else None
        return {
            "scope": {"namespace": self.namespace, "name": self.name},
            "backend": self.backend(),
            "sample_every": self.recorder.sample_every,
            "ring": {"len": len(self.recorder),
                     "capacity": self.recorder.capacity,
                     "samples_total": self.recorder.samples_total},
            "phases": phases,
            "hottest_phase": hottest,
            "compile": self.compile.payload(),
            "memory": self._last_memory,
            "kv_quant": self.kv_quant,
            "spec": self.spec,
            "handoff": self.handoff,
            "throughput": self.throughput_estimate(phases),
        }


# ---- per-process observatory registry (the debug_xprof surface) ----

_REGISTRY: "collections.OrderedDict[tuple[str, str], weakref.ref]" = \
    collections.OrderedDict()
_REGISTRY_CAPACITY = 64
_registry_lock = threading.Lock()
_auto_seq = [0]


def _next_auto_name() -> str:
    with _registry_lock:
        _auto_seq[0] += 1
        return f"engine-{_auto_seq[0]}"


def _zero_scope_gauges(scope: str, metrics) -> None:
    """Zero a dead/evicted scope's grove_hbm_bytes series: a retired
    engine's bytes must read 0, not linger at their last value (the
    set_gauge_family / kube-state-metrics convention; the hub keeps
    the zeroed series in the rendering, which is the standard
    Prometheus staleness shape)."""
    for kind in ("kv_cache", "weights", "workspace", "total"):
        metrics.set("grove_hbm_bytes", 0.0, kind=kind, scope=scope)


def register(obs: Observatory, name: str | None = None,
             namespace: str | None = None) -> None:
    """(Re)register an observatory under a scope. Engines auto-register
    as default/engine-N at construction; serving wrappers re-register
    under the scope name the control plane knows (the PCSG), so
    ``grovectl engine-profile <name>`` finds it. Weakly held and
    LRU-capped: a dead engine's entry evicts and its gauge series
    zero, never lingering at stale byte values."""
    if name is not None and name != obs.name and obs._last_memory:
        # Re-registration under a new scope: the gauges written under
        # the old scope would otherwise read stale forever.
        _zero_scope_gauges(f"{obs.namespace}/{obs.name}", obs._metrics)
    if name is not None:
        obs.name = name
    if namespace is not None:
        obs.namespace = namespace
    key = (obs.namespace, obs.name)
    # Zero this scope's gauges when the observatory is collected (the
    # finalizer must not hold obs — capture only the scope string).
    weakref.finalize(obs, _zero_scope_gauges,
                     f"{obs.namespace}/{obs.name}", obs._metrics)
    with _registry_lock:
        _REGISTRY.pop(key, None)
        _REGISTRY[key] = weakref.ref(obs)
        while len(_REGISTRY) > _REGISTRY_CAPACITY:
            _REGISTRY.popitem(last=False)


def observatory_for(name: str, namespace: str = "default",
                    ) -> Observatory | None:
    with _registry_lock:
        ref = _REGISTRY.get((namespace, name))
        obs = ref() if ref is not None else None
        if ref is not None and obs is None:
            del _REGISTRY[(namespace, name)]
        return obs


def scopes() -> list[tuple[str, str]]:
    with _registry_lock:
        return [k for k, ref in _REGISTRY.items() if ref() is not None]


# ---- rendering (grovectl engine-profile) ----

def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TB"


def render_engine_profile(payload: dict) -> list[str]:
    """Human rendering of one observatory payload: phase breakdown
    (hottest phase starred), compile table, memory bar."""
    out: list[str] = []
    scope = payload.get("scope") or {}
    backend = payload.get("backend") or {}
    out.append(f"engine:    {scope.get('namespace', '?')}/"
               f"{scope.get('name', '?')}")
    est = " (estimates are model-derived)" if backend.get("estimated") \
        else ""
    out.append(f"backend:   {backend.get('platform', '?')}:"
               f"{backend.get('device_kind', '?')}{est}")
    ring = payload.get("ring") or {}
    out.append(f"sampling:  every {payload.get('sample_every', '?')} "
               f"steps, ring {ring.get('len', 0)}/"
               f"{ring.get('capacity', 0)} "
               f"({ring.get('samples_total', 0)} samples total)")
    phases = payload.get("phases") or {}
    if phases:
        out.append("")
        out.append(f"  {'phase':<15}{'samples':>8}{'p50 ms':>10}"
                   f"{'p95 ms':>10}{'total s':>10}  ")
        hottest = payload.get("hottest_phase")
        for name in sorted(phases, key=lambda p: -phases[p]["total_s"]):
            d = phases[name]
            star = " *" if name == hottest else ""
            out.append(f"  {name:<15}{d['count']:>8}{d['p50_ms']:>10.3f}"
                       f"{d['p95_ms']:>10.3f}{d['total_s']:>10.3f}{star}")
    else:
        out.append("  (no device-time samples yet)")
    comp = payload.get("compile") or {}
    if comp.get("fns"):
        out.append("")
        out.append(f"  {'compiled fn':<22}{'compiles':>9}{'recompiles':>11}"
                   f"{'total s':>9}  last")
        for f in comp["fns"]:
            out.append(f"  {f['fn']:<22}{f['compiles']:>9}"
                       f"{f['recompiles']:>11}{f['total_seconds']:>9.2f}"
                       f"  {f['last_reason']} ({f['last_seconds']:.2f}s)")
        if comp.get("storms"):
            out.append(f"  RECOMPILE STORMS: {comp['storms']} — shapes "
                       "are churning on the serving path")
    mem = payload.get("memory")
    if mem:
        out.append("")
        out.append(f"memory ({mem['source']}):")
        total = max(1, mem["total_bytes"])
        for kind, key in (("kv_cache", "kv_cache_bytes"),
                          ("weights", "weight_bytes"),
                          ("workspace", "workspace_bytes")):
            b = mem[key]
            bar = "#" * min(40, int(40 * b / total))
            out.append(f"  {kind:<11}{_fmt_bytes(b):>12}  {bar}")
        limit = (f" / limit {_fmt_bytes(mem['limit_bytes'])}"
                 if mem.get("limit_bytes") else "")
        out.append(f"  {'total':<11}{_fmt_bytes(mem['total_bytes']):>12}"
                   f"{limit}  kv_headroom {mem['kv_headroom']:.2f}")
    spec = payload.get("spec")
    if spec:
        out.append("")
        rate = spec.get("acceptance_rate", 0.0)
        # <50% acceptance means more than half the draft compute is
        # thrown away — the speculation config IS the bottleneck
        # (shrink spec_k or improve the draft), so star it the way
        # the hottest phase is starred.
        star = "  * LOW ACCEPTANCE — speculation is the bottleneck" \
            if spec.get("draft_tokens", 0) and rate < 0.5 else ""
        out.append(f"speculation (k={spec.get('spec_k', '?')}): "
                   f"acceptance {rate * 100:.1f}%, "
                   f"{spec.get('accepted_per_dispatch', 0.0):.2f} "
                   f"tokens/dispatch "
                   f"({spec.get('accepted_tokens', 0)}/"
                   f"{spec.get('draft_tokens', 0)} drafts accepted)"
                   f"{star}")
        buckets = spec.get("per_bucket") or {}
        for key in sorted(buckets):
            b = buckets[key]
            acc = (b["accepted_tokens"] / b["draft_tokens"]
                   if b.get("draft_tokens") else 0.0)
            per = (b["committed_tokens"] / b["rows"]
                   if b.get("rows") else 0.0)
            out.append(f"  [{key}] acceptance {acc * 100:.1f}%, "
                       f"{per:.2f} tok/dispatch over "
                       f"{b.get('dispatches', 0)} dispatches")
    thr = payload.get("throughput")
    if thr:
        out.append("")
        tag = " [estimate]" if thr.get("estimated") else ""
        out.append(f"throughput: {thr['tokens_per_sec_est']:.1f} tok/s, "
                   f"MFU {thr['mfu_est'] * 100:.2f}%, "
                   f"HBM {thr['hbm_util_est'] * 100:.1f}%{tag}")
        out.append(f"  basis: {thr['basis']}")
    return out
