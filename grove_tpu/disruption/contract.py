"""The DisruptionNotice lifecycle — one barrier protocol for every
planned eviction.

A notice lives in its gang's ``ANNOTATION_DISRUPTION_NOTICE`` annotation
as JSON (the reuse-reservation-ref pattern: one pointer, one sanctioned
CAS write path, mirrored into ``PodGang.status.disruption`` and a
``DisruptionTarget`` condition by the scheduler's status writes). The
states:

- **posted**   — an evictor (defrag executor, rolling update, reclaim
                 controller) declared intent; ``deadline`` is absolute.
                 A second caller posting onto a gang that already
                 carries a live notice COALESCES onto it (same id, same
                 deadline — the workload checkpoints once no matter how
                 many reasons want it moved).
- **acked**    — the workload (or the auto-ack for gangs with no
                 registered checkpoint responder — nothing to flush
                 means nothing to wait for) confirmed its checkpoint is
                 durable. An ack AFTER the deadline is recorded but the
                 barrier still reads ``expired`` — the eviction already
                 proceeded and replaying the late ack would lie.
- **expired**  — the deadline passed unacked; eviction proceeds anyway
                 (the workload may delay, never veto) and is stamped
                 ``barrier=expired``.
- **evicted**  — ``note_evicted`` stamped the moment pods were deleted;
                 the chaos disruption-contract invariant checks that an
                 evicted gang's barrier reads acked or expired, never
                 pending/absent.
- **cleared**  — the evictor removed the notice once the gang is whole
                 again (or its operation aborted without evicting).

``GROVE_DISRUPTION=0`` (read live): ``post_notice`` returns None and
callers evict immediately — the exact pre-contract behavior.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import uuid
from typing import Callable

from grove_tpu.api import PodGang, constants as c
from grove_tpu.api.podgang import DisruptionNotice
from grove_tpu.disruption import disruption_enabled
from grove_tpu.runtime.errors import ConflictError, GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.runtime.timescale import scaled

log = get_logger("disruption")

# ---- checkpoint responder registry --------------------------------------

# (namespace, gang name) -> callable(notice_dict) -> None. Raising means
# "checkpoint failed, retry me"; returning means the checkpoint is
# durable and the notice may be acked. Process-local by design: the
# responder IS the in-process serving engine's hook (remote workloads
# ack over the wire by writing the annotation through the API).
_RESPONDERS: dict[tuple[str, str], Callable] = {}
_RESPONDERS_LOCK = threading.Lock()


def register_responder(gang_name: str, fn: Callable,
                       namespace: str = "default") -> None:
    """Register ``fn`` as the checkpoint hook for a gang. While
    registered, barriers on the gang wait for the reclaim controller to
    run it (retry/backoff until the deadline); without one, barriers
    auto-ack at post time."""
    with _RESPONDERS_LOCK:
        _RESPONDERS[(namespace, gang_name)] = fn


def unregister_responder(gang_name: str,
                         namespace: str = "default") -> None:
    with _RESPONDERS_LOCK:
        _RESPONDERS.pop((namespace, gang_name), None)


def responder_for(gang_name: str,
                  namespace: str = "default") -> Callable | None:
    with _RESPONDERS_LOCK:
        return _RESPONDERS.get((namespace, gang_name))


# ---- notice (de)serialization -------------------------------------------


def notice_of(gang: PodGang) -> DisruptionNotice | None:
    """Parse the gang's live notice; None when absent or undecodable
    (a corrupt annotation must degrade to 'no barrier', not wedge the
    eviction path behind a parse error forever)."""
    raw = gang.meta.annotations.get(c.ANNOTATION_DISRUPTION_NOTICE, "")
    if not raw:
        return None
    try:
        data = json.loads(raw)
        return DisruptionNotice(**{
            f.name: data.get(f.name, getattr(DisruptionNotice, f.name, ""))
            for f in dataclasses.fields(DisruptionNotice)
            if f.name in data})
    except (ValueError, TypeError):
        log.warning("gang %s/%s carries an undecodable disruption "
                    "notice; treating as absent",
                    gang.meta.namespace, gang.meta.name)
        return None


def _encode(notice: DisruptionNotice) -> str:
    return json.dumps(dataclasses.asdict(notice), sort_keys=True)


def barrier_state(notice: DisruptionNotice | None,
                  now: float | None = None) -> str:
    """absent | pending | acked | expired. An ack stamped past the
    deadline does not resurrect the barrier — the eviction already
    proceeded under ``expired`` and the state must keep saying so."""
    if notice is None:
        return "absent"
    now = time.time() if now is None else now
    if notice.acked_at and notice.acked_at <= notice.deadline:
        return "acked"
    if now > notice.deadline:
        return "expired"
    return "pending"


# ---- the one sanctioned write path --------------------------------------


def _mutate(client, gang_name: str, namespace: str,
            fn: Callable[[PodGang, DisruptionNotice | None],
                         "DisruptionNotice | None | bool"],
            retries: int = 6) -> DisruptionNotice | None:
    """CAS loop over the gang's notice annotation. ``fn`` sees the live
    gang + parsed notice and returns the notice to write (None =
    remove the annotation, False = abort without writing). Returns the
    written notice (or the live one on abort), None when the gang is
    gone or every retry conflicted."""
    for _ in range(retries):
        try:
            gang = client.get(PodGang, gang_name, namespace)
        except NotFoundError:
            return None
        current = notice_of(gang)
        out = fn(gang, current)
        if out is False:
            return current
        if out is None:
            if c.ANNOTATION_DISRUPTION_NOTICE not in gang.meta.annotations:
                return None
            gang.meta.annotations.pop(c.ANNOTATION_DISRUPTION_NOTICE, None)
        else:
            encoded = _encode(out)
            if gang.meta.annotations.get(
                    c.ANNOTATION_DISRUPTION_NOTICE) == encoded:
                return out
            gang.meta.annotations[c.ANNOTATION_DISRUPTION_NOTICE] = encoded
        try:
            client.update(gang)
            return out if out is not None else None
        except ConflictError:
            continue
        except GroveError as e:
            log.warning("disruption notice write on %s/%s failed: %s",
                        namespace, gang_name, e)
            return None
    return None


def post_notice(client, gang_name: str, namespace: str, reason: str,
                deadline_s: float) -> DisruptionNotice | None:
    """Declare eviction intent. Returns the LIVE notice — fresh, or the
    existing one when a barrier is already up (double-notice
    coalescing: one checkpoint covers every reason that wants the gang
    moved). A coalescing caller can SHORTEN the deadline but never
    extend it — a re-post must not grant a stay of execution, and a
    spot reclaim joining an earlier roll/defrag notice must keep its
    withdrawal-clamped deadline or the gang dies with the slice while
    the barrier still reads pending. None when the contract is disabled
    (GROVE_DISRUPTION=0) or the gang is gone — callers distinguish the
    two through :func:`request_barrier`."""
    if not disruption_enabled():
        return None
    posted = {"fresh": False}

    def mutate(gang: PodGang, current: DisruptionNotice | None):
        if current is not None and not current.evicted_at:
            deadline = min(current.deadline,
                           time.time() + scaled(deadline_s))
            coalesced = dataclasses.replace(
                current, coalesced=current.coalesced + 1,
                deadline=deadline)
            posted["fresh"] = False
            return coalesced
        notice = DisruptionNotice(
            id=uuid.uuid4().hex[:12], reason=reason,
            requested_at=time.time(),
            deadline=time.time() + scaled(deadline_s))
        if responder_for(gang_name, namespace) is None and \
                not gang.meta.annotations.get(
                    c.ANNOTATION_CHECKPOINT_REQUIRED):
            # No checkpoint responder and no out-of-process one
            # declared: nothing to flush, nothing to wait for — the
            # barrier auto-acks at post time (the no-serving-engine
            # case; also what keeps pure control-plane workloads
            # eviction-latency-free). A checkpoint-required gang waits
            # for its remote workload's wire ack (or the deadline).
            notice.acked_at = time.time()
            notice.ack_source = "auto"
        posted["fresh"] = True
        return notice

    notice = _mutate(client, gang_name, namespace, mutate)
    if notice is not None and posted["fresh"]:
        GLOBAL_METRICS.inc("grove_disruption_notices_total", reason=reason)
        if notice.ack_source == "auto":
            GLOBAL_METRICS.inc("grove_disruption_acks_total", source="auto")
        log.info("disruption notice %s on %s/%s (%s): deadline in %.1fs%s",
                 notice.id, namespace, gang_name, reason,
                 notice.deadline - time.time(),
                 " [auto-acked]" if notice.ack_source == "auto" else "")
    return notice


def ack_notice(client, gang_name: str, namespace: str, notice_id: str,
               source: str = "workload") -> bool:
    """The workload's checkpoint acknowledgment. True iff the ack is
    now recorded on the identified notice (repeat acks are True
    no-ops); False when the notice is gone or superseded. Late acks
    (past the deadline) are recorded — they are evidence — but the
    barrier keeps reading expired."""
    recorded = {"new": False, "late": False}

    def mutate(gang: PodGang, current: DisruptionNotice | None):
        if current is None or current.id != notice_id:
            return False
        if current.acked_at:
            return False            # already acked: no write needed
        now = time.time()
        recorded["new"] = True
        recorded["late"] = now > current.deadline
        return dataclasses.replace(current, acked_at=now, ack_source=source)

    out = _mutate(client, gang_name, namespace, mutate)
    if out is None:
        return False
    if recorded["new"]:
        GLOBAL_METRICS.inc("grove_disruption_acks_total", source=source)
        GLOBAL_METRICS.observe("grove_disruption_barrier_wait_seconds",
                               max(0.0, out.acked_at - out.requested_at))
        if recorded["late"]:
            log.warning("late ack on notice %s (%s/%s): deadline passed "
                        "%.1fs earlier — eviction already proceeded",
                        notice_id, namespace, gang_name,
                        out.acked_at - out.deadline)
    return out.id == notice_id and bool(out.acked_at)


def note_evicted(client, gang_name: str, namespace: str,
                 notice_id: str) -> str:
    """Stamp the moment eviction proceeded, freezing the barrier
    verdict (acked|expired) onto the notice — the record the chaos
    disruption-contract invariant audits. Returns the stamped barrier
    state ("" when the notice vanished)."""
    stamped = {"barrier": "", "reason": ""}

    def mutate(gang: PodGang, current: DisruptionNotice | None):
        if current is None or current.id != notice_id:
            return False
        if current.evicted_at:
            stamped["barrier"] = current.barrier
            return False
        state = barrier_state(current)
        stamped["barrier"] = state
        stamped["reason"] = current.reason
        return dataclasses.replace(current, evicted_at=time.time(),
                                   barrier=state)

    _mutate(client, gang_name, namespace, mutate)
    if stamped["reason"]:
        GLOBAL_METRICS.inc("grove_disruption_evictions_total",
                           reason=stamped["reason"],
                           barrier=stamped["barrier"])
        if stamped["barrier"] == "expired":
            GLOBAL_METRICS.inc("grove_disruption_expired_total",
                               reason=stamped["reason"])
    return stamped["barrier"]


def clear_notice(client, gang_name: str, namespace: str,
                 notice_id: str) -> bool:
    """Remove the notice once its eviction's story ends (gang whole
    again, or the operation aborted without evicting). CAS on id: a
    successor notice posted since must not be cleared by a stale
    caller."""

    def mutate(gang: PodGang, current: DisruptionNotice | None):
        if current is None:
            return False
        if current.id != notice_id:
            return False
        return None

    _mutate(client, gang_name, namespace, mutate)
    return True


def request_barrier(client, gang_name: str, namespace: str, reason: str,
                    deadline_s: float) -> tuple[str, DisruptionNotice | None]:
    """The caller-facing one-liner: post (or join) the gang's notice
    and report the barrier verdict. Outcomes callers act on:

    - ``("disabled", None)`` — GROVE_DISRUPTION=0: evict immediately,
      the pre-contract shape;
    - ``("gone", None)`` — the gang no longer exists: the eviction is
      moot;
    - ``("retry", None)`` — the notice write lost every CAS round to
      other writers: NOT a license to evict; try again next pass (a
      contended annotation must never silently strip the barrier);
    - ``("pending"|"acked"|"expired", notice)`` — the barrier proper.
    """
    if not disruption_enabled():
        return "disabled", None
    notice = post_notice(client, gang_name, namespace, reason, deadline_s)
    if notice is None:
        try:
            client.get(PodGang, gang_name, namespace)
        except NotFoundError:
            return "gone", None
        return "retry", None
    return barrier_state(notice), notice
