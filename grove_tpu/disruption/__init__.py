"""Disruption contract + spot-slice reclamation (ROADMAP items 3/5).

Every *planned* eviction in this control plane — a defrag migration
draining its victim, a rolling update taking down a ready pod, a
spot-slice reclaim evacuating a dying slice — now routes through ONE
barrier protocol (the reference operator's gang-termination-delay /
rolling semantics, SURVEY.md §4, generalized to TPU slice granularity):

- ``contract``  — the DisruptionNotice lifecycle: post (CAS onto the
                  gang's annotation), workload ack, deadline expiry,
                  eviction stamping, clearing. One pointer, one write
                  path, like reuse-reservation-ref.
- ``reclaim``   — the ReclaimController: turns a spot-reclamation
                  notice on a slice's nodes (``ANNOTATION_RECLAIM_AT``,
                  surfaced/cordoned by controllers/nodelifecycle.py)
                  into gang-atomic evacuations — notice → checkpoint
                  barrier → pinned SliceReservation on surviving
                  capacity (the defrag hold→drain→rebind machinery) →
                  reland → ready. It also *drives* the barrier for all
                  three callers: registered checkpoint responders
                  (serving/checkpoint.py warm-restart path) run with
                  retry/backoff until ack or deadline.

``GROVE_DISRUPTION=0`` (read live, per decision) disables the CONTRACT:
post_notice returns None and every caller evicts immediately — exactly
the pre-contract behavior. The reclaim controller itself stays active
(abandoning a dying slice is not an acceptable "off"); only its barrier
degrades to immediate. See docs/design/disruption-contract.md.
"""

from __future__ import annotations

import os

DISRUPTION_ENV = "GROVE_DISRUPTION"

# Notice reasons — the three sanctioned planned-eviction callers.
REASON_DEFRAG = "defrag-migration"
REASON_ROLLING = "rolling-update"
REASON_RECLAIM = "spot-reclaim"


def disruption_enabled() -> bool:
    """The contract kill switch, read per decision (incident mitigation
    and tests flip it live, like GROVE_DEFRAG)."""
    return os.environ.get(DISRUPTION_ENV, "1") != "0"


def reclaim_hold_name(gang_name: str) -> str:
    """Deterministic SliceReservation name for a reclaim evacuation of
    ``gang_name`` (one evacuation per gang at a time by construction;
    distinct from defrag-/roll- so the three hold owners never collide)."""
    return f"reclaim-{gang_name}"


from grove_tpu.disruption.contract import (  # noqa: E402
    ack_notice,
    barrier_state,
    clear_notice,
    notice_of,
    note_evicted,
    post_notice,
    register_responder,
    request_barrier,
    responder_for,
    unregister_responder,
)

__all__ = [
    "DISRUPTION_ENV",
    "REASON_DEFRAG",
    "REASON_RECLAIM",
    "REASON_ROLLING",
    "ack_notice",
    "barrier_state",
    "clear_notice",
    "disruption_enabled",
    "note_evicted",
    "notice_of",
    "post_notice",
    "reclaim_hold_name",
    "register_responder",
    "request_barrier",
    "responder_for",
    "unregister_responder",
]
