"""ReclaimController — spot-slice reclamation as a first-class event.

On GKE spot, a slice's nodes vanish *together*. The reclamation notice
arrives ahead of the withdrawal (``ANNOTATION_RECLAIM_AT`` on each
node, stamped by the cloud integration or the chaos spot-reclaim
injector; ``controllers/nodelifecycle.py`` cordons the nodes the moment
it sees one). This controller turns that notice into gang-atomic
evacuations instead of letting the gangs die with the slice:

1. **Notice**: every gang with a pod on reclaim-noticed capacity gets a
   ``DisruptionNotice`` (reason ``spot-reclaim``, deadline clamped to
   the node's advertised withdrawal instant) through the one contract
   every planned eviction shares (disruption/contract.py).
2. **Barrier**: registered checkpoint responders (the serving engine's
   warm-restart hook, serving/checkpoint.py) run with retry/backoff
   until they ack or the deadline expires — the workload may delay,
   never veto. Gangs with no responder auto-ack at post time.
3. **Hold**: a ``SliceReservation`` pinned to surviving capacity chosen
   by the real gang planner (``plan_gang`` with the multislice
   DCN-spread penalties — replicas spread before they pack), wired to
   the gang via the reuse-reservation-ref annotation exactly like a
   defrag migration hold.
4. **Drain → reland**: pods deleted gang-atomically (stamped
   ``barrier=acked|expired`` first — the record the chaos
   disruption-contract invariant audits), the PodCliques recreate them
   gated, the scheduler relands them pinned to the hold; the evacuation
   completes when the gang is Ready again
   (``grove_disruption_reclaim_to_ready_seconds``).

Degradations are graceful by construction: no surviving capacity fits →
drain unpinned and let self-heal land the gang when capacity returns; a
hold's TTL expires mid-evacuation (the reservation controller deletes
it AND clears the gang's annotation, the PR 9 precedent) → the
evacuation RE-HOLDS and continues rather than stranding a half-drained
gang; the deadline passes unacked → evict anyway, stamped expired.

Surfaces: ``GET /debug/disruption`` + ``Client/HttpClient
.debug_disruption`` twins + ``grovectl disruptions`` render
:meth:`payload`; ``grove_disruption_*`` metric families.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref

from grove_tpu.api import Node, Pod, PodGang, SliceReservation, \
    constants as c
from grove_tpu.api.config import DisruptionConfig
from grove_tpu.api.meta import is_condition_true, new_meta
from grove_tpu.api.reservation import ReservationPhase, SliceReservationSpec
from grove_tpu.defrag import release_hold, set_reservation_ref
from grove_tpu.disruption import (
    REASON_RECLAIM,
    barrier_state,
    clear_notice,
    disruption_enabled,
    note_evicted,
    notice_of,
    post_notice,
    reclaim_hold_name,
    responder_for,
)
from grove_tpu.disruption.contract import ack_notice
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.events import EventRecorder
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.runtime.timescale import TIME_SCALE, scaled

# store (weakly) -> its controller, so the in-process Client resolves
# debug_disruption without a manager reference (the defrag pattern).
_CONTROLLERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def reclaim_for(store) -> "ReclaimController | None":
    return _CONTROLLERS.get(store)


def reclaim_noticed_nodes(nodes: list[Node]) -> list[Node]:
    """The nodes carrying a live spot-reclamation notice (shared with
    controllers/nodelifecycle.py, which cordons them)."""
    return [n for n in nodes
            if n.meta.annotations.get(c.ANNOTATION_RECLAIM_AT)]


def _reclaim_at(node: Node) -> float:
    try:
        return float(node.meta.annotations.get(
            c.ANNOTATION_RECLAIM_AT, "0"))
    except ValueError:
        return 0.0


class _Evacuation:
    """One gang's evacuation state."""

    __slots__ = ("gang", "namespace", "source_slices", "state", "barrier",
                 "notice_id", "reservation", "target_slices", "pinned",
                 "started_at", "hold_at", "drained_at", "finished_at",
                 "outcome", "reholds", "pods_moved", "chips")

    def __init__(self, gang: str, namespace: str,
                 source_slices: list[str]) -> None:
        self.gang = gang
        self.namespace = namespace
        self.source_slices = sorted(source_slices)
        self.state = "Barrier"      # Barrier | Holding | Relanding
        self.barrier = ""           # verdict stamped at drain
        self.notice_id = ""
        self.reservation = ""
        self.target_slices: list[str] = []
        self.pinned = False
        self.started_at = time.time()
        self.hold_at: float | None = None
        self.drained_at: float | None = None
        self.finished_at: float | None = None
        self.outcome = ""           # evacuated | aborted:<reason>
        self.reholds = 0
        self.pods_moved = 0
        self.chips = 0

    def to_dict(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}


def render_disruptions(payload: dict, now: float | None = None
                       ) -> list[str]:
    """Human-readable disruption ledger — what ``grovectl disruptions``
    prints. Works on the wire dict so the CLI renders identically from
    the debug endpoint and the in-process twin."""
    now = time.time() if now is None else now
    cnt = payload.get("counters", {})
    lines = [
        "disruption contract: " + (
            "enabled" if payload.get("contract_enabled")
            else "DISABLED (GROVE_DISRUPTION=0 — evictions proceed "
                 "without barriers)"),
        f"  notices: {cnt.get('notices', 0)} posted, "
        f"{cnt.get('acks_driven', 0)} acks driven "
        f"({cnt.get('ack_failures', 0)} checkpoint failures retried), "
        f"{cnt.get('expired', 0)} expired",
        f"  evacuations: {cnt.get('started', 0)} started, "
        f"{cnt.get('completed', 0)} completed, "
        f"{cnt.get('aborted', 0)} aborted, "
        f"{cnt.get('reholds', 0)} re-holds after TTL expiry",
    ]
    notices = payload.get("notices") or []
    if notices:
        lines.append(f"  live notices ({len(notices)}):")
        for n in notices:
            age = now - n.get("requested_at", now)
            left = n.get("deadline", now) - now
            lines.append(
                f"    {n.get('gang', '?'):30s} {n.get('reason', '?'):16s} "
                f"{n.get('state', '?'):8s} age {age:5.1f}s "
                + (f"deadline in {left:.1f}s" if left > 0
                   else f"deadline passed {-left:.1f}s ago")
                + (f" (coalesced x{n['coalesced']}"
                   f")" if n.get("coalesced") else ""))
    inflight = payload.get("inflight") or []
    if inflight:
        lines.append(f"  evacuations in flight ({len(inflight)}):")
        for e in inflight:
            age = now - e.get("started_at", now)
            lines.append(
                f"    {e.get('gang', '?'):30s} {e.get('state', '?'):10s} "
                f"{age:5.1f}s  {e.get('source_slices', [])} -> "
                f"{e.get('target_slices') or 'unpinned'}"
                + (f" (re-held x{e['reholds']})" if e.get("reholds")
                   else ""))
    recent = payload.get("recent") or []
    if recent:
        lines.append(f"  recent evacuations ({len(recent)}, newest first):")
        for e in recent[:8]:
            took = (e.get("finished_at") or now) - e.get("started_at", now)
            lines.append(
                f"    {e.get('outcome', '?'):20s} {e.get('gang', '?'):30s} "
                f"{e.get('source_slices', [])} -> "
                f"{e.get('target_slices') or 'unpinned'} "
                f"barrier={e.get('barrier') or '?'} "
                f"({e.get('pods_moved', 0)} pods, {took:.2f}s)")
    return lines


class ReclaimController:
    """Background evacuation runnable (one per manager). Also the
    barrier *coordinator*: its ack pass drives registered checkpoint
    responders for EVERY live notice (defrag's and the roll path's
    included), so one runnable owns the retry/backoff machinery."""

    RECENT_CAPACITY = 32

    def __init__(self, client, store,
                 config: DisruptionConfig | None = None) -> None:
        self.client = client
        self.store = store
        self.cfg = config or DisruptionConfig()
        self.log = get_logger("disruption.reclaim")
        self.recorder = EventRecorder(client, "reclaim")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Guards _active/_recent: the sweep thread mutates them,
        # payload() reads them from the HTTP server thread.
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "disruption")
        self._active: dict[tuple[str, str], _Evacuation] = {}
        self._recent: collections.deque = collections.deque(
            maxlen=self.RECENT_CAPACITY)
        # notice id -> (attempts, next retry at; monotonic) for the
        # responder retry/backoff schedule; _ack_inflight (under _lock)
        # holds the notice ids whose responder thread is running.
        self._ack_schedule: dict[str, tuple[int, float]] = {}
        self._ack_inflight: set[str] = set()
        self.counters = {"notices": 0, "acks_driven": 0, "ack_failures": 0,
                         "expired": 0, "started": 0, "completed": 0,
                         "aborted": 0, "reholds": 0}

    # ---- runnable lifecycle ---------------------------------------------

    def start(self) -> None:
        _CONTROLLERS[self.store] = self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="reclaim",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if _CONTROLLERS.get(self.store) is self:
            del _CONTROLLERS[self.store]

    def pause(self) -> None:
        """Leadership parking (grove_tpu/ha): a demoted replica must
        not evacuate — its writes would be fenced, and racing the real
        leader's evacuations would double-evict."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _run(self) -> None:
        from grove_tpu.store import writeobs
        writeobs.set_writer("reclaim")
        while not self._stop.is_set():
            if getattr(self, "_paused", False):
                self._stop.wait(self.cfg.sync_period_seconds)
                continue
            try:
                self.sweep()
            except Exception:   # noqa: BLE001 — loop survival barrier
                self.log.exception("reclaim sweep panicked")
            self._stop.wait(self.cfg.sync_period_seconds)

    # ---- the sweep -------------------------------------------------------

    def sweep(self) -> None:
        """One decision round: drive checkpoint responders, detect
        newly noticed capacity, advance every in-flight evacuation.
        Public so tests and tools can drive it synchronously."""
        gangs = self.client.list(PodGang, None)
        self._ack_pass(gangs)
        self._detect(gangs)
        with self._lock:
            active = list(self._active.values())
        for ev in active:
            try:
                self._advance(ev)
            except GroveError as e:
                self.log.warning("evacuation of %s/%s hiccuped: %s",
                                 ev.namespace, ev.gang, e)
        GLOBAL_METRICS.set("grove_disruption_inflight",
                           float(len(self._active)))

    # ---- barrier coordination (retry/backoff on checkpoint acks) --------

    def _ack_pass(self, gangs: list[PodGang]) -> None:
        """For every gang with a pending notice AND a registered
        checkpoint responder: run the responder — on its OWN thread,
        one in flight per notice, so a single slow checkpoint cannot
        starve the other gangs racing the same reclaim deadline — ack
        on success; retry with exponential backoff until the deadline
        on failure. Gangs without responders were auto-acked at post
        time (unless they declared an out-of-process checkpointer via
        the checkpoint-required annotation — those wait for the wire
        ack or the deadline)."""
        now = time.monotonic()
        live_ids = set()
        for gang in gangs:
            notice = notice_of(gang)
            if notice is None:
                continue
            live_ids.add(notice.id)
            if barrier_state(notice) != "pending":
                continue
            fn = responder_for(gang.meta.name, gang.meta.namespace)
            if fn is None:
                if gang.meta.annotations.get(
                        c.ANNOTATION_CHECKPOINT_REQUIRED):
                    continue    # a remote workload owns this ack
                # Responder unregistered since the post (engine shut
                # down mid-barrier): nothing left to flush — auto-ack.
                ack_notice(self.client, gang.meta.name,
                           gang.meta.namespace, notice.id, source="auto")
                self.counters["acks_driven"] += 1
                continue
            attempts, next_try = self._ack_schedule.get(notice.id, (0, 0.0))
            if now < next_try:
                continue
            with self._lock:
                if notice.id in self._ack_inflight:
                    continue    # this notice's responder is still running
                self._ack_inflight.add(notice.id)
            threading.Thread(
                target=self._run_responder, name=f"ack-{notice.id}",
                args=(fn, gang.meta.name, gang.meta.namespace, notice,
                      attempts), daemon=True).start()
        # Drop retry state for notices that no longer exist.
        for nid in list(self._ack_schedule):
            if nid not in live_ids:
                del self._ack_schedule[nid]

    def _run_responder(self, fn, gang_name: str, namespace: str,
                       notice, attempts: int) -> None:
        """One checkpoint attempt, off the sweep thread."""
        try:
            try:
                fn(notice)
            except Exception as e:  # noqa: BLE001 — a failing checkpoint
                # must be retried, not kill the coordinator
                backoff = min(
                    scaled(self.cfg.ack_retry_base_seconds) * (2 ** attempts),
                    scaled(self.cfg.ack_retry_max_seconds))
                self._ack_schedule[notice.id] = (attempts + 1,
                                                 time.monotonic() + backoff)
                self.counters["ack_failures"] += 1
                GLOBAL_METRICS.inc("grove_disruption_ack_failures_total",
                                   reason=notice.reason)
                self.log.warning(
                    "checkpoint responder for %s/%s failed (attempt %d, "
                    "retry in %.2fs): %s", namespace, gang_name,
                    attempts + 1, backoff, e)
                return
            if ack_notice(self.client, gang_name, namespace, notice.id,
                          source="workload"):
                self.counters["acks_driven"] += 1
                self._ack_schedule.pop(notice.id, None)
                self.log.info("checkpoint acked for %s/%s (notice %s, "
                              "attempt %d)", namespace, gang_name,
                              notice.id, attempts + 1)
        finally:
            with self._lock:
                self._ack_inflight.discard(notice.id)

    # ---- detection -------------------------------------------------------

    def _noticed_nodes(self) -> list[Node]:
        return reclaim_noticed_nodes(self.client.list(Node, None))

    def _detect(self, gangs: list[PodGang]) -> None:
        noticed = self._noticed_nodes()
        if not noticed:
            return
        noticed_names = {(n.meta.namespace, n.meta.name) for n in noticed}
        slice_of = {(n.meta.namespace, n.meta.name):
                    n.meta.labels.get(c.NODE_LABEL_SLICE, "")
                    for n in noticed}
        affected: dict[tuple[str, str], set[str]] = {}
        for p in self.client.list(Pod, None):
            if p.meta.deletion_timestamp is not None \
                    or not p.status.node_name:
                continue
            key = (p.meta.namespace, p.status.node_name)
            if key not in noticed_names:
                continue
            gname = p.meta.labels.get(c.LABEL_PODGANG_NAME, "")
            if gname:
                affected.setdefault(
                    (p.meta.namespace, gname), set()).add(slice_of[key])
        by_name = {(g.meta.namespace, g.meta.name): g for g in gangs}
        for key, slices in sorted(affected.items()):
            with self._lock:
                if key in self._active:
                    continue
                if len(self._active) >= self.cfg.max_concurrent_evacuations:
                    break               # the rest start next sweep(s)
                gang = by_name.get(key)
                if gang is None or gang.meta.deletion_timestamp is not None:
                    continue
                ev = _Evacuation(key[1], key[0], sorted(s for s in slices
                                                        if s))
                self._active[key] = ev
            self._start_evacuation(ev, gang, noticed)

    def _start_evacuation(self, ev: _Evacuation, gang: PodGang,
                          noticed: list[Node]) -> None:
        self.counters["started"] += 1
        GLOBAL_METRICS.inc("grove_disruption_evacuations_total")
        notice = self._post_reclaim_notice(ev, gang, noticed)
        self.log.info("reclaim: evacuating gang %s/%s off %s "
                      "(barrier %s)", ev.namespace, ev.gang,
                      ev.source_slices,
                      notice.id if notice is not None else ev.barrier
                      or "retrying")
        self._event(ev, "Normal", "SpotReclaimEvacuation",
                    f"slice(s) {ev.source_slices} under spot "
                    f"reclamation; evacuating gang "
                    + (f"behind checkpoint barrier {notice.id}"
                       if notice is not None else
                       "without a barrier (contract disabled)"
                       if ev.barrier == "disabled" else
                       "(notice post contended; retrying)"))

    def _post_reclaim_notice(self, ev: _Evacuation, gang: PodGang,
                             noticed: list[Node]):
        """Post (or re-post after write contention) the evacuation's
        notice. Deadline: the contract default, clamped to the earliest
        advertised withdrawal of THIS gang's noticed capacity — a
        barrier outliving its own hardware protects nothing, and
        another slice's (possibly stale) stamp must not cut this gang's
        checkpoint window. post_notice scales its argument, so the
        wall-clock remainder is divided back to pre-scale seconds."""
        from grove_tpu.disruption import request_barrier
        deadline_s = self.cfg.default_deadline_seconds
        own = set(ev.source_slices)
        stamps = [t for t in (
            _reclaim_at(n) for n in noticed
            if n.meta.labels.get(c.NODE_LABEL_SLICE, "") in own) if t > 0]
        if stamps:
            remaining = (min(stamps) - time.time()) / TIME_SCALE
            deadline_s = max(0.1, min(deadline_s, remaining))
        state, notice = request_barrier(self.client, ev.gang, ev.namespace,
                                        REASON_RECLAIM, deadline_s)
        if state in ("disabled", "gone"):
            # Pre-contract behavior (kill switch) or a moot eviction:
            # no barrier, straight to the hold — the switch strips the
            # CONTRACT, not the pinned evacuation itself.
            ev.barrier = "disabled"
            ev.state = "Holding"
            ev.hold_at = time.time()
            self._take_hold(ev, gang)
            return None
        if notice is None:
            return None     # "retry": the Barrier state re-posts
        if not ev.notice_id:
            self.counters["notices"] += 1
        ev.notice_id = notice.id
        return notice

    # ---- the per-evacuation state machine --------------------------------

    def _advance(self, ev: _Evacuation) -> None:
        try:
            gang = self.client.get(PodGang, ev.gang, ev.namespace)
        except NotFoundError:
            self._abort(ev, "victim-gone")
            return
        if ev.state == "Barrier":
            if not ev.notice_id:
                # The initial post lost every CAS round (contended
                # annotation): re-post — write contention must never
                # silently strip the barrier.
                if self._post_reclaim_notice(ev, gang,
                                             self._noticed_nodes()) is None:
                    return      # disabled path advanced, or retry again
            state = barrier_state(notice_of(gang))
            if state == "pending":
                return
            if state == "absent":
                # A POSTED notice vanished (operator clear / corrupt):
                # the capacity is still dying — proceed as expired.
                state = "expired"
            ev.barrier = state
            if state == "expired":
                self.counters["expired"] += 1
            ev.state = "Holding"
            ev.hold_at = time.time()
            self._take_hold(ev, gang)
            return
        if ev.state == "Holding":
            self._advance_holding(ev, gang)
            return
        if ev.state == "Relanding":
            self._advance_relanding(ev, gang)

    def _take_hold(self, ev: _Evacuation, gang: PodGang) -> None:
        """Pin surviving capacity for the reland. May leave the
        evacuation unpinned (no feasible target, or the gang's pointer
        is owned by an in-flight defrag/roll hold that the drain will
        supersede anyway) — graceful degradation, not failure."""
        target = self._plan_target(ev, gang)
        if target is None:
            ev.pinned = False
            self.log.warning(
                "reclaim: no surviving capacity fits gang %s/%s — "
                "draining unpinned (self-heal relands it when capacity "
                "returns)", ev.namespace, ev.gang)
            self._event(ev, "Warning", "SpotReclaimDegraded",
                        "no surviving capacity fits the gang; draining "
                        "unpinned — it relands when capacity returns")
            return
        slices, chips = target
        name = reclaim_hold_name(ev.gang)
        rsv = SliceReservation(
            meta=new_meta(name, namespace=ev.namespace, labels={
                c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                c.LABEL_HOLD_FOR_GANG: ev.gang,
            }),
            spec=SliceReservationSpec(
                slices=slices, chips=chips,
                ttl_seconds=scaled(self.cfg.hold_ttl_seconds)))
        try:
            self.client.create(rsv)
        except GroveError as e:
            self.log.warning("reclaim hold %s not created: %s", name, e)
        # CAS from unset or already-ours: a defrag/roll hold owning the
        # pointer means that machinery is mid-flight on this gang — the
        # drain below supersedes it, but never steal the pointer; the
        # evacuation just runs unpinned (its abort path will release).
        if set_reservation_ref(self.client, ev.gang, ev.namespace, name,
                               expect=("", name)):
            ev.reservation = name
            ev.target_slices = slices
            ev.chips = chips
            ev.pinned = True
        else:
            self._delete_reservation(name, ev.namespace)
            ev.pinned = False
            self.log.warning(
                "reclaim: gang %s/%s pointer owned by another hold "
                "(defrag/roll in flight); evacuating unpinned",
                ev.namespace, ev.gang)

    def _plan_target(self, ev: _Evacuation,
                     gang: PodGang) -> tuple[list[str], int] | None:
        """Choose surviving capacity with the real planner: the gang's
        own pack constraints (group-level slice packs included — the
        scheduler will enforce them at reland, so a target that ignored
        them would wedge), the multislice DCN-spread penalties (sibling
        PCS replicas' slices penalized so replicas spread before they
        pack), noticed capacity excluded. Planned over the FULL spec
        membership, not just live pods — mid-chaos a gang may be
        missing replicas, and a hold sized to the survivors would pin
        the healed gang onto capacity it cannot fit."""
        from grove_tpu.scheduler.backends import DEFAULT_LEVEL_LABELS, \
            build_host_views
        from grove_tpu.scheduler.placement import (
            GroupRequest,
            PodRequest,
            plan_gang,
            plan_gang_grouped,
        )
        noticed = {n.meta.name for n in self._noticed_nodes()}
        hosts = [h for h in build_host_views(self.client, None,
                                             DEFAULT_LEVEL_LABELS)
                 if h.name not in noticed]
        if not hosts:
            return None
        live = {p.meta.name: p for p in self.client.list(
            Pod, ev.namespace, selector={c.LABEL_PODGANG_NAME: ev.gang})
            if p.meta.deletion_timestamp is None}

        def chips_of(grp, pod_name: str) -> int:
            p = live.get(pod_name)
            if p is not None:
                return p.spec.tpu_chips
            # Group pods are same-shaped: borrow a live sibling's ask.
            for sib in grp.pod_names:
                sp = live.get(sib)
                if sp is not None:
                    return sp.spec.tpu_chips
            return 0

        def selector_of(grp) -> dict[str, str]:
            for sib in grp.pod_names:
                sp = live.get(sib)
                if sp is not None:
                    return {k: v for k, v in sp.spec.node_selector.items()
                            if k != c.LABEL_RESERVATION}
            return {}

        topo = gang.spec.topology
        pack_level = (topo.pack_level if topo else "slice") or "slice"
        required = topo.required if topo else True
        # DCN-spread: penalize slices already hosting sibling replicas
        # of the same PCS (scheduler/backends._spread_penalties logic
        # against a plain gang list — no pass snapshot here).
        penalties: dict[str, float] = {}
        pcs = gang.meta.labels.get(c.LABEL_PCS_NAME, "")
        if pcs:
            for other in self.client.list(
                    PodGang, ev.namespace,
                    selector={c.LABEL_PCS_NAME: pcs}):
                if other.meta.name != ev.gang \
                        and other.status.assigned_slice:
                    penalties[other.status.assigned_slice] = \
                        penalties.get(other.status.assigned_slice, 0.0) + 2.0
        grouped = any(grp.topology is not None and grp.topology.pack_level
                      for grp in gang.spec.groups)
        total_chips = 0
        if grouped:
            greqs = []
            for grp in gang.spec.groups:
                sel = selector_of(grp)
                reqs = [PodRequest(pn, chips_of(grp, pn), sel)
                        for pn in grp.pod_names]
                total_chips += sum(r.chips for r in reqs)
                greqs.append(GroupRequest(
                    reqs,
                    grp.topology.pack_level if grp.topology else "",
                    grp.topology.required if grp.topology else True))
            plan = plan_gang_grouped(greqs, hosts, pack_level=pack_level,
                                     required=required,
                                     spread_penalty=penalties)
        else:
            reqs = [PodRequest(pn, chips_of(grp, pn), selector_of(grp))
                    for grp in gang.spec.groups for pn in grp.pod_names]
            total_chips = sum(r.chips for r in reqs)
            plan = plan_gang(reqs, hosts, pack_level=pack_level,
                             required=required, spread_penalty=penalties)
        if plan is None or not total_chips:
            return None
        host_slice = {h.name: h.domains.get("slice", "") for h in hosts}
        slices = sorted({host_slice[hn] for hn in plan.assignments.values()
                         if host_slice.get(hn)})
        if not slices:
            return None
        # The reservation's free-chip bind gate is per-slice (the
        # defrag single-slice shape); a multi-slice target (pool-level
        # gang) binds ungated — the plan above already proved headroom.
        chips = total_chips if len(slices) == 1 else 0
        return slices, chips

    def _advance_holding(self, ev: _Evacuation, gang: PodGang) -> None:
        if not ev.pinned:
            self._drain(ev)
            return
        try:
            rsv = self.client.get(SliceReservation, ev.reservation,
                                  ev.namespace)
        except NotFoundError:
            # TTL expiry (which also cleared the gang's annotation —
            # the PR 9 precedent) or operator delete: REQUEUE the
            # evacuation by re-holding, never strand it half-done.
            if not self._rehold(ev, gang):
                self._drain(ev)     # out of re-holds: go unpinned
            return
        if rsv.status.phase == ReservationPhase.BOUND \
                and rsv.status.bound_slices:
            self._drain(ev)
            return
        if time.time() - (ev.hold_at or ev.started_at) > \
                scaled(self.cfg.hold_timeout_seconds):
            # The target's headroom vanished while we waited and the
            # slice underneath us is still dying: release the pin and
            # drain unpinned — late is worse than unpinned here.
            self._release(ev)
            ev.pinned = False
            self._event(ev, "Warning", "SpotReclaimDegraded",
                        f"hold {ev.reservation} never bound within "
                        f"{self.cfg.hold_timeout_seconds:.0f}s; draining "
                        "unpinned")
            self._drain(ev)

    def _rehold(self, ev: _Evacuation, gang: PodGang) -> bool:
        """Re-take a lost hold mid-evacuation. True while re-holding is
        still viable (the evacuation stays pinned), False when the
        attempt budget is spent."""
        if ev.reholds >= self.cfg.rehold_attempts:
            ev.pinned = False
            self.log.warning(
                "reclaim: hold for %s/%s lost %d time(s); continuing "
                "unpinned", ev.namespace, ev.gang, ev.reholds)
            return False
        ev.reholds += 1
        self.counters["reholds"] += 1
        GLOBAL_METRICS.inc("grove_disruption_reholds_total")
        self.log.warning(
            "reclaim: hold %s for gang %s/%s vanished (TTL expiry?); "
            "re-holding (attempt %d/%d) and requeueing the evacuation",
            ev.reservation, ev.namespace, ev.gang, ev.reholds,
            self.cfg.rehold_attempts)
        ev.hold_at = time.time()
        self._take_hold(ev, gang)
        return ev.pinned

    def _drain(self, ev: _Evacuation) -> None:
        """Gang-atomic eviction off the dying slice: the barrier
        verdict is stamped onto the notice FIRST (the disruption-
        contract invariant's audit record), then every pod goes in one
        round — mid-evacuation the gang only ever has FEWER pods than
        before, never a second live copy."""
        if ev.notice_id:
            stamped = note_evicted(self.client, ev.gang, ev.namespace,
                                   ev.notice_id)
            if stamped:
                ev.barrier = stamped
        pods = [p for p in self.client.list(
            Pod, ev.namespace, selector={c.LABEL_PODGANG_NAME: ev.gang})
            if p.meta.deletion_timestamp is None]
        for p in pods:
            try:
                self.client.delete(Pod, p.meta.name, p.meta.namespace)
            except (NotFoundError, GroveError):
                pass
        ev.pods_moved = len(pods)
        ev.drained_at = time.time()
        ev.state = "Relanding"
        self.log.info("reclaim: gang %s/%s drained (%d pods, barrier=%s)"
                      " -> reland on %s", ev.namespace, ev.gang,
                      len(pods), ev.barrier,
                      ev.target_slices if ev.pinned else "any capacity")

    def _advance_relanding(self, ev: _Evacuation, gang: PodGang) -> None:
        if is_condition_true(gang.status.conditions, c.COND_READY) \
                and self._fully_bound(gang):
            self._complete(ev)
            return
        if ev.pinned:
            try:
                self.client.get(SliceReservation, ev.reservation,
                                ev.namespace)
            except NotFoundError:
                # TTL expired mid-reland: the reservation controller
                # already cleared the gang's dangling annotation —
                # requeue by re-holding so the reland stays pinned (or
                # degrade to unpinned once the budget is spent).
                self._rehold(ev, gang)
        if time.time() - (ev.drained_at or ev.started_at) > \
                scaled(self.cfg.rebind_timeout_seconds):
            # Nothing left to do for this evacuation: release the pin
            # and leave the gang to the ordinary self-heal machinery
            # (its diagnosis explains what it is waiting for).
            self._abort(ev, "rebind-timeout")

    def _fully_bound(self, gang: PodGang) -> bool:
        expected = [pn for grp in gang.spec.groups for pn in grp.pod_names]
        pods = {p.meta.name: p for p in self.client.list(
            Pod, gang.meta.namespace,
            selector={c.LABEL_PODGANG_NAME: gang.meta.name})
            if p.meta.deletion_timestamp is None}
        return bool(expected) and all(
            pn in pods and pods[pn].status.node_name for pn in expected)

    # ---- completion / abort ----------------------------------------------

    def _complete(self, ev: _Evacuation) -> None:
        self._release(ev)
        if ev.notice_id:
            clear_notice(self.client, ev.gang, ev.namespace, ev.notice_id)
        duration = time.time() - ev.started_at
        ev.state, ev.outcome = "Done", "evacuated"
        ev.finished_at = time.time()
        self._finish(ev)
        self.counters["completed"] += 1
        GLOBAL_METRICS.inc("grove_disruption_evacuations_completed_total")
        GLOBAL_METRICS.observe("grove_disruption_reclaim_to_ready_seconds",
                               duration)
        self.log.info("reclaim: gang %s/%s relanded ready on %s in %.2fs "
                      "(barrier=%s, %d pods)", ev.namespace, ev.gang,
                      ev.target_slices or "surviving capacity", duration,
                      ev.barrier, ev.pods_moved)
        landed = ev.target_slices or "surviving capacity"
        self._event(ev, "Normal", "SpotReclaimCompleted",
                    f"relanded ready on {landed} in {duration:.2f}s "
                    f"(barrier={ev.barrier}, {ev.pods_moved} pods)")

    def _abort(self, ev: _Evacuation, reason: str) -> None:
        at_state = ev.state
        self._release(ev)
        if ev.notice_id and ev.drained_at is None:
            # Nothing was evicted: the notice must not linger as a
            # phantom barrier on the gang.
            clear_notice(self.client, ev.gang, ev.namespace, ev.notice_id)
        elif ev.notice_id:
            # Pods WERE evicted; clear the (stamped) notice so a future
            # planned eviction can post a fresh barrier — the stamped
            # eviction record already fed the counters.
            clear_notice(self.client, ev.gang, ev.namespace, ev.notice_id)
        ev.state, ev.outcome = "Aborted", f"aborted:{reason}"
        ev.finished_at = time.time()
        self._finish(ev)
        self.counters["aborted"] += 1
        GLOBAL_METRICS.inc("grove_disruption_evacuations_aborted_total",
                           reason=reason)
        self.log.warning("reclaim: evacuation of %s/%s aborted (%s) "
                         "at %s", ev.namespace, ev.gang, reason, at_state)
        self._event(ev, "Warning", "SpotReclaimAborted",
                    f"evacuation aborted ({reason}) at {at_state}; "
                    "hold released, self-heal owns the gang now")

    def _release(self, ev: _Evacuation) -> None:
        release_hold(self.client, ev.gang, ev.namespace, ev.reservation)

    def _delete_reservation(self, name: str, namespace: str) -> None:
        try:
            self.client.delete(SliceReservation, name, namespace)
        except (NotFoundError, GroveError):
            pass

    def _finish(self, ev: _Evacuation) -> None:
        with self._lock:
            self._recent.appendleft(ev.to_dict())
            self._active.pop((ev.namespace, ev.gang), None)

    def _event(self, ev: _Evacuation, etype: str, reason: str,
               message: str) -> None:
        try:
            gang = self.client.get(PodGang, ev.gang, ev.namespace)
        except (NotFoundError, GroveError):
            return
        self.recorder.event(gang, etype, reason, message)

    # ---- read surface ----------------------------------------------------

    def payload(self) -> dict:
        """The /debug/disruption wire shape (grovectl disruptions
        renders it; one shape in-process and over HTTP)."""
        notices = []
        try:
            for gang in self.client.list(PodGang, None):
                n = notice_of(gang)
                if n is None:
                    continue
                d = {"gang": f"{gang.meta.namespace}/{gang.meta.name}",
                     "state": barrier_state(n)}
                d.update({k: getattr(n, k) for k in (
                    "id", "reason", "requested_at", "deadline", "acked_at",
                    "ack_source", "evicted_at", "barrier", "coalesced")})
                notices.append(d)
        except GroveError:
            pass
        with self._lock:
            inflight = [e.to_dict() for e in self._active.values()]
            recent = list(self._recent)
        return {
            "contract_enabled": disruption_enabled(),
            "config": {
                "sync_period_seconds": self.cfg.sync_period_seconds,
                "default_deadline_seconds":
                    self.cfg.default_deadline_seconds,
                "max_concurrent_evacuations":
                    self.cfg.max_concurrent_evacuations,
                "rehold_attempts": self.cfg.rehold_attempts,
            },
            "counters": dict(self.counters),
            "notices": notices,
            "inflight": inflight,
            "recent": recent,
        }
