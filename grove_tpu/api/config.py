"""OperatorConfiguration — component-config for the control plane.

Parity with reference operator/api/config/v1alpha1/types.go:120-313:
per-controller concurrency, scheduler profiles with a default, topology-
aware-scheduling toggle, authorizer toggle, log settings. Loaded from a
YAML file by the CLI (`grove_tpu.cli`), defaulted and validated before use.
"""

from __future__ import annotations

import dataclasses

from grove_tpu.api import constants


@dataclasses.dataclass
class ControllerConcurrency:
    podcliqueset: int = 2
    podclique: int = 4
    podcliquescalinggroup: int = 2
    podgang: int = 2
    clustertopology: int = 1


@dataclasses.dataclass
class SchedulerProfile:
    name: str = ""          # profile name referenced by PCS spec
    backend: str = ""       # registered backend: "gang" | "simple" | "external"
    options: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TopologyAwareSchedulingConfig:
    enabled: bool = True


@dataclasses.dataclass
class AuthorizerConfig:
    enabled: bool = False
    exempt_actors: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServerAuthConfig:
    """HTTP API authentication (static bearer tokens → actor identities,
    the k8s --token-auth-file analog). Mutating verbs (POST /apply,
    DELETE) require an authenticated actor; the mapped identity flows
    into store admission, so admission/authorization.py guards the wire
    path the way the reference's authorization webhook guards kubectl
    (admission/pcs/authorization/handler.go:40)."""

    # token value -> actor identity (e.g. "system:grove-operator",
    # "user:alice"). Empty + allow_anonymous_mutations=False means no
    # remote mutations at all (grovectl serve generates a token).
    # Configuring any token auto-enables the authorizer (cluster.py) —
    # otherwise non-operator identities would be decorative.
    tokens: dict[str, str] = dataclasses.field(default_factory=dict)
    # Escape hatch for closed dev/test environments only.
    allow_anonymous_mutations: bool = False
    # Autoscaling signal ingestion (POST /metrics/push) stays open by
    # default: advisory, schema-validated, damped by the autoscaler, and
    # in-pod engines hold no secrets. Flip to require a token.
    require_token_for_metrics: bool = False
    # Reads (GET /api, /logs) are open by default; healthz/metrics are
    # always open (liveness probes must not need credentials).
    require_token_for_reads: bool = False


@dataclasses.dataclass
class ServerTlsConfig:
    """TLS for the HTTP API server (the reference's webhook cert
    machinery, cert.go:50-117: self-provisioned + rotated certs or a
    BYO secret). Off by default — the serve daemon binds loopback; flip
    on for anything that leaves the host."""

    enabled: bool = False
    mode: str = "self-managed"      # "self-managed" | "byo"
    # self-managed: CA + leaf are generated/rotated under cert_dir
    # (ca.crt is the file clients pin; rotation never changes it).
    cert_dir: str = "certs"
    validity_days: float = 30.0
    # Re-issue the leaf when less than this fraction of its validity
    # remains (reference rotates ahead of expiry for the same reason:
    # a restart must never be required to stay serveable).
    rotation_fraction: float = 0.2
    rotation_check_seconds: float = 3600.0
    sans: list[str] = dataclasses.field(
        default_factory=lambda: ["localhost", "127.0.0.1"])
    # byo: operator-supplied PEM files (validated: pair matches, not
    # expired). ca_file is advertised to clients, never loaded here.
    cert_file: str = ""
    key_file: str = ""
    ca_file: str = ""


@dataclasses.dataclass
class NodeLifecycleConfig:
    """Heartbeat-driven host-loss detection (node-lifecycle-controller
    analog; only acts on non-fake nodes that have heartbeated)."""

    enabled: bool = True
    # NotReady after this long without a heartbeat. Default = 3 missed
    # beats at the agent's default 5s cadence.
    grace_seconds: float = 15.0
    sync_period_seconds: float = 1.0


@dataclasses.dataclass
class ProfilingConfig:
    """Sampling-profiler surface (the reference's pprof endpoint toggle,
    api/config/v1alpha1/types.go:186). Off by default: profiling leaks
    code structure and costs a sampler thread per request window."""

    enabled: bool = False
    sample_interval_ms: float = 10.0
    max_window_seconds: float = 30.0


@dataclasses.dataclass
class LogConfig:
    level: str = "info"
    format: str = "text"    # "text" | "json"


@dataclasses.dataclass
class AutoscalerConfig:
    enabled: bool = True
    sync_period_seconds: float = 5.0
    # Downscale stabilization (k8s HPA analog): shrink only to the max
    # desired value seen over this window. Flap control matters more
    # here than in vanilla HPA — every PCSG flap is a gang
    # create/destroy cycle on TPU slices.
    scale_down_stabilization_seconds: float = 30.0


@dataclasses.dataclass
class DefragConfig:
    """Active placement repair (grove_tpu/defrag): a background planner
    that migrates placed gangs to consolidate fragmented free capacity
    when an unschedulable gang's explain diagnosis proves defrag would
    seat it. ``enabled`` gates the manager runnable; the GROVE_DEFRAG
    env var (read live, default on) is the incident kill switch for the
    whole subsystem including roll-safe holds."""

    enabled: bool = True
    sync_period_seconds: float = 0.5
    # Disruption budget: at most this many pods evicted for migrations
    # inside any budget window — defrag must repair fragmentation, not
    # become churn itself.
    disruption_budget_pods: int = 8
    budget_window_seconds: float = 30.0
    # Rate limit: minimum gap between migration starts (one migration
    # in flight at a time regardless).
    cooldown_seconds: float = 1.0
    # Hold lifecycle (pre-TIME_SCALE seconds): reservation TTL backstop,
    # bind wait, and reland wait before the executor aborts + releases.
    hold_ttl_seconds: float = 60.0
    hold_timeout_seconds: float = 5.0
    rebind_timeout_seconds: float = 30.0


@dataclasses.dataclass
class DisruptionConfig:
    """The disruption contract + spot-slice reclamation
    (grove_tpu/disruption, docs/design/disruption-contract.md).
    ``enabled`` gates the reclaim controller runnable (which also
    drives checkpoint responders for every barrier); the
    GROVE_DISRUPTION env var (read live, default on) is the incident
    kill switch for the CONTRACT itself — with it off, every planned
    eviction proceeds immediately, exactly the pre-contract shape."""

    enabled: bool = True
    sync_period_seconds: float = 0.25
    # Checkpoint-barrier deadline: a notice expires (and the eviction
    # proceeds, stamped barrier=expired) this long after posting unless
    # the workload acks earlier. Spot reclaim clamps it further to the
    # node's advertised reclaim-at instant.
    default_deadline_seconds: float = 8.0
    # Failed checkpoint acks retry with exponential backoff between
    # these bounds until the deadline (pre-TIME_SCALE seconds).
    ack_retry_base_seconds: float = 0.2
    ack_retry_max_seconds: float = 2.0
    # Evacuation hold lifecycle (pre-TIME_SCALE seconds) — same roles
    # as the defrag knobs: reservation TTL backstop, bind wait, and
    # reland wait before the evacuation degrades gracefully.
    hold_ttl_seconds: float = 60.0
    hold_timeout_seconds: float = 5.0
    # Short enough that a wedged pinned reland degrades (pin released,
    # self-heal lands the gang wherever capacity exists) well inside
    # the chaos harness's recovery budgets.
    rebind_timeout_seconds: float = 20.0
    # Concurrent gang evacuations (a reclaimed slice usually carries
    # several gangs and they are all racing the same deadline; defrag's
    # one-at-a-time pacing would forfeit workloads).
    max_concurrent_evacuations: int = 4
    # How many times a TTL-expired (or otherwise lost) hold is re-taken
    # mid-evacuation before the evacuation proceeds unpinned.
    rehold_attempts: int = 3


@dataclasses.dataclass
class HAConfig:
    """HA control plane (grove_tpu/ha, proposal 0002): ``enabled``
    wires a LeaderElector runnable so the manager campaigns (epoch
    bump + writer fencing) at start — required for multi-replica
    deployments, inert single-replica overhead otherwise (exactly one
    extra WAL record per boot). ``replica`` names this process in
    leadership gauges and /debug/leadership (defaults to
    $GROVE_REPLICA, then "r0"). The GROVE_HA env var (read live,
    default on) is the incident kill switch for the whole subsystem —
    fence checks, campaigns, standby machinery."""

    enabled: bool = False
    replica: str = ""


@dataclasses.dataclass
class OperatorConfiguration:
    concurrency: ControllerConcurrency = dataclasses.field(
        default_factory=ControllerConcurrency)
    scheduler_profiles: list[SchedulerProfile] = dataclasses.field(
        default_factory=lambda: [
            SchedulerProfile(name="default", backend=constants.DEFAULT_SCHEDULER),
            SchedulerProfile(name="simple", backend="simple"),
        ])
    default_scheduler_profile: str = "default"
    topology_aware_scheduling: TopologyAwareSchedulingConfig = dataclasses.field(
        default_factory=TopologyAwareSchedulingConfig)
    authorizer: AuthorizerConfig = dataclasses.field(
        default_factory=AuthorizerConfig)
    server_auth: ServerAuthConfig = dataclasses.field(
        default_factory=ServerAuthConfig)
    server_tls: ServerTlsConfig = dataclasses.field(
        default_factory=ServerTlsConfig)
    autoscaler: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig)
    defrag: DefragConfig = dataclasses.field(default_factory=DefragConfig)
    disruption: DisruptionConfig = dataclasses.field(
        default_factory=DisruptionConfig)
    ha: HAConfig = dataclasses.field(default_factory=HAConfig)
    node_lifecycle: NodeLifecycleConfig = dataclasses.field(
        default_factory=NodeLifecycleConfig)
    profiling: ProfilingConfig = dataclasses.field(
        default_factory=ProfilingConfig)
    log: LogConfig = dataclasses.field(default_factory=LogConfig)
    # reconcile loop tuning
    requeue_base_seconds: float = 0.05
    requeue_max_seconds: float = 5.0


def load_config(path: str) -> OperatorConfiguration:
    """Load + validate an OperatorConfiguration from a YAML file
    (component-config style; reference decode.go + validation.go)."""
    import yaml

    from grove_tpu.api.serde import from_dict, unknown_keys
    from grove_tpu.runtime.errors import ValidationError

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    # Strict decode: a typo'd key silently becoming a default is the worst
    # failure mode a config system can have.
    unknown = unknown_keys(OperatorConfiguration, data)
    if unknown:
        raise ValidationError(
            f"operator configuration {path!r}: unknown keys {unknown}")
    cfg = from_dict(OperatorConfiguration, data)
    problems = validate_config(cfg)
    if problems:
        raise ValidationError(
            f"operator configuration {path!r} invalid: " + "; ".join(problems))
    return cfg


def load_token_file(path: str) -> dict[str, str]:
    """Parse a ``token,actor`` lines file (kube-apiserver
    --token-auth-file shape; rendered into the deploy bundle's Secret /
    tokens file by grove_tpu/deploy.py) into a token→actor map."""
    from grove_tpu.runtime.errors import ValidationError

    tokens: dict[str, str] = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            token, sep, actor = line.partition(",")
            if not sep or not token.strip() or not actor.strip():
                raise ValidationError(
                    f"token file {path!r} line {lineno}: expected "
                    "'token,actor'")
            tokens[token.strip()] = actor.strip()
    return tokens


def validate_config(cfg: OperatorConfiguration) -> list[str]:
    """Return a list of problems (empty == valid)."""
    errs: list[str] = []
    for field, v in dataclasses.asdict(cfg.concurrency).items():
        if v < 1:
            errs.append(f"concurrency.{field} must be >= 1, got {v}")
    names = [p.name for p in cfg.scheduler_profiles]
    if len(set(names)) != len(names):
        errs.append(f"duplicate scheduler profile names: {names}")
    if cfg.default_scheduler_profile not in names:
        errs.append(
            f"default_scheduler_profile {cfg.default_scheduler_profile!r} "
            f"not among profiles {names}")
    tls = cfg.server_tls
    if tls.mode not in ("self-managed", "byo"):
        errs.append(f"server_tls.mode must be self-managed|byo, "
                    f"got {tls.mode!r}")
    if tls.validity_days <= 0:
        errs.append(f"server_tls.validity_days must be > 0, "
                    f"got {tls.validity_days}")
    if not 0 < tls.rotation_fraction < 1:
        errs.append(f"server_tls.rotation_fraction must be in (0, 1), "
                    f"got {tls.rotation_fraction}")
    if tls.enabled and tls.mode == "byo" \
            and not (tls.cert_file and tls.key_file):
        errs.append("server_tls mode 'byo' requires cert_file and key_file")
    if tls.enabled and tls.mode == "self-managed" and not tls.sans:
        errs.append("server_tls.sans must not be empty")
    if cfg.defrag.sync_period_seconds <= 0:
        errs.append("defrag.sync_period_seconds must be > 0, got "
                    f"{cfg.defrag.sync_period_seconds}")
    if cfg.defrag.disruption_budget_pods < 1:
        errs.append("defrag.disruption_budget_pods must be >= 1, got "
                    f"{cfg.defrag.disruption_budget_pods}")
    if cfg.defrag.budget_window_seconds <= 0:
        errs.append("defrag.budget_window_seconds must be > 0, got "
                    f"{cfg.defrag.budget_window_seconds}")
    if cfg.defrag.cooldown_seconds < 0:
        errs.append("defrag.cooldown_seconds must be >= 0, got "
                    f"{cfg.defrag.cooldown_seconds}")
    for knob in ("hold_ttl_seconds", "hold_timeout_seconds",
                 "rebind_timeout_seconds"):
        if getattr(cfg.defrag, knob) <= 0:
            errs.append(f"defrag.{knob} must be > 0, got "
                        f"{getattr(cfg.defrag, knob)}")
    for knob in ("sync_period_seconds", "default_deadline_seconds",
                 "ack_retry_base_seconds", "ack_retry_max_seconds",
                 "hold_ttl_seconds", "hold_timeout_seconds",
                 "rebind_timeout_seconds"):
        if getattr(cfg.disruption, knob) <= 0:
            errs.append(f"disruption.{knob} must be > 0, got "
                        f"{getattr(cfg.disruption, knob)}")
    if cfg.disruption.max_concurrent_evacuations < 1:
        errs.append("disruption.max_concurrent_evacuations must be >= 1, "
                    f"got {cfg.disruption.max_concurrent_evacuations}")
    if cfg.disruption.rehold_attempts < 0:
        errs.append("disruption.rehold_attempts must be >= 0, got "
                    f"{cfg.disruption.rehold_attempts}")
    if cfg.node_lifecycle.grace_seconds <= 0:
        errs.append("node_lifecycle.grace_seconds must be > 0, got "
                    f"{cfg.node_lifecycle.grace_seconds}")
    if cfg.node_lifecycle.sync_period_seconds <= 0:
        errs.append("node_lifecycle.sync_period_seconds must be > 0, got "
                    f"{cfg.node_lifecycle.sync_period_seconds}")
    if cfg.profiling.sample_interval_ms <= 0:
        errs.append("profiling.sample_interval_ms must be > 0, got "
                    f"{cfg.profiling.sample_interval_ms}")
    if cfg.profiling.max_window_seconds <= 0:
        errs.append("profiling.max_window_seconds must be > 0, got "
                    f"{cfg.profiling.max_window_seconds}")
    if cfg.log.level not in ("debug", "info", "warning", "error"):
        errs.append(f"unknown log level {cfg.log.level!r}")
    if cfg.log.format not in ("text", "json"):
        errs.append(f"unknown log format {cfg.log.format!r}")
    return errs
