"""ClusterTopology — the ordered hierarchy of placement domains.

Parity with reference operator/api/core/v1alpha1/clustertopologybinding.go:
32-155, with TPU-native levels. Default hierarchy (outer → inner):

  pool        — node pool / datacenter block (DCN between pools)
  superblock  — optically-switched group of slices (v4/v5p) or pool subnet
  slice       — one ICI mesh (the gang-atomic domain)
  host        — one TPU VM (4 or 8 chips)

Each level names the node label that carries its domain value.
"""

from __future__ import annotations

import dataclasses

from grove_tpu.api import constants
from grove_tpu.api.meta import Condition, ObjectMeta


@dataclasses.dataclass
class TopologyLevel:
    domain: str = ""      # level name, e.g. "slice"
    node_label: str = ""  # node label key carrying the domain value


DEFAULT_TPU_LEVELS = [
    TopologyLevel("pool", constants.NODE_LABEL_POOL),
    TopologyLevel("superblock", constants.NODE_LABEL_SUPERBLOCK),
    TopologyLevel("slice", constants.NODE_LABEL_SLICE),
    TopologyLevel("host", constants.NODE_LABEL_HOST),
]


@dataclasses.dataclass
class ClusterTopologySpec:
    levels: list[TopologyLevel] = dataclasses.field(
        default_factory=lambda: list(DEFAULT_TPU_LEVELS))
    # Scheduler backends that auto-manage their own topology view get it
    # synced from this resource; externally-managed ones are drift-checked.
    externally_managed: bool = False


@dataclasses.dataclass
class ClusterTopologyStatus:
    synced_backends: list[str] = dataclasses.field(default_factory=list)
    drift_detected: bool = False
    conditions: list[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClusterTopology:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ClusterTopologySpec = dataclasses.field(
        default_factory=ClusterTopologySpec)
    status: ClusterTopologyStatus = dataclasses.field(
        default_factory=ClusterTopologyStatus)

    KIND = "ClusterTopology"
