"""Core data-plane types: Pod and Node.

The reference delegates these to Kubernetes; this framework is its own
control plane, so it defines them natively — shaped for TPU workloads:
a Node is one TPU host (VM) belonging to an ICI slice, a Pod is one
workload process (typically one JAX multi-host worker) with chip
requests, scheduling gates, and a startup barrier.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from grove_tpu.api.meta import Condition, ObjectMeta


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class ContainerSpec:
    """The workload process. ``argv`` is executed by the node agent; fake
    nodes (KWOK analog) skip execution and synthesise readiness."""

    name: str = "main"
    argv: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    workdir: str = ""
    # Readiness-probe analog (k8s readinessProbe): when set, the node
    # agent marks the pod Ready only once this file exists (absolute, or
    # relative to the pod workdir) — e.g. written by a serving engine
    # after weights load. Unset → Ready at process start.
    readiness_file: str = ""
    # Probe timing (k8s initialDelaySeconds / periodSeconds /
    # failureThreshold×period analog; bounds enforced by admission,
    # honored by the node agent): no probe before initial_delay after
    # process start; checks at most every period; timeout > 0 fails the
    # pod (→ MinAvailableBreached → gang handling) if the file never
    # appears within initial_delay + timeout.
    readiness_initial_delay_s: float = 0.0
    readiness_period_s: float = 0.5
    readiness_timeout_s: float = 0.0


@dataclasses.dataclass
class StartupBarrier:
    """In-pod startup ordering (the grove-initc analog, SURVEY.md §2.6 I1):
    the node agent blocks the main process until every listed parent
    PodClique has >= min_available Ready pods."""

    parent_cliques: list[str] = dataclasses.field(default_factory=list)  # fqn
    min_available: dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodSpec:
    container: ContainerSpec = dataclasses.field(default_factory=ContainerSpec)
    tpu_chips: int = 0                  # chips requested on the host
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    scheduler_name: str = ""
    scheduling_gates: list[str] = dataclasses.field(default_factory=list)
    hostname: str = ""
    subdomain: str = ""                 # headless-service DNS wiring
    startup_barrier: Optional[StartupBarrier] = None
    priority_class: str = ""
    termination_grace_seconds: float = 5.0


@dataclasses.dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    conditions: list[Condition] = dataclasses.field(default_factory=list)
    node_name: str = ""
    pod_ip: str = ""
    start_time: float = 0.0
    restart_count: int = 0
    message: str = ""


@dataclasses.dataclass
class Pod:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodSpec = dataclasses.field(default_factory=PodSpec)
    status: PodStatus = dataclasses.field(default_factory=PodStatus)

    KIND = "Pod"


@dataclasses.dataclass
class NodeStatus:
    ready: bool = True
    allocatable_chips: int = 0
    heartbeat_time: float = 0.0
    message: str = ""


@dataclasses.dataclass
class NodeSpec:
    tpu_chips: int = 4                  # chips on this host (v5e host = 4)
    fake: bool = True                   # KWOK-analog synthetic node
    unschedulable: bool = False


@dataclasses.dataclass
class Node:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: NodeSpec = dataclasses.field(default_factory=NodeSpec)
    status: NodeStatus = dataclasses.field(default_factory=NodeStatus)

    KIND = "Node"


@dataclasses.dataclass
class Secret:
    """Opaque key/value material the control plane mints for workloads —
    the reference's per-PCS service-account token Secret
    (podcliqueset/components/satokensecret/). Today's single use: the
    workload identity token (`<pcs>-workload-token`, data keys
    ``token``) that in-pod engines present for authenticated,
    PCS-scoped metric pushes. Wire reads are restricted to system
    actors (server.py); the identity an accepted token maps to is
    derived from the secret's OWN labels, never from its data — a
    user-minted secret can therefore never escalate."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    data: dict[str, str] = dataclasses.field(default_factory=dict)

    KIND = "Secret"


@dataclasses.dataclass
class Service:
    """Headless service: DNS-style discovery record for a PCS replica's
    pods (reference: podcliqueset/components/service/). In this control
    plane it materialises as an endpoints map the agent env-injects."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    selector: dict[str, str] = dataclasses.field(default_factory=dict)
    publish_not_ready: bool = True
    endpoints: list[str] = dataclasses.field(default_factory=list)

    KIND = "Service"
