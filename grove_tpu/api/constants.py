"""Shared label keys, env-var names, scheduling gates, finalizers.

Parity with the reference's api/common/constants/constants.go:56-71 label
and env contract, re-targeted at TPU: workload pods receive both the
framework rank identity (GROVE_*) and the JAX/TPU bootstrap contract
(TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / slice metadata) so a multi-host
JAX process group initialises with zero extra wiring.
"""

DOMAIN = "grove.tpu"

# ---- labels ----
LABEL_MANAGED_BY = f"{DOMAIN}/managed-by"
LABEL_MANAGED_BY_VALUE = "grove-tpu-operator"
LABEL_PCS_NAME = f"{DOMAIN}/podcliqueset"
LABEL_PCS_REPLICA = f"{DOMAIN}/podcliqueset-replica-index"
LABEL_PCLQ_NAME = f"{DOMAIN}/podclique"
LABEL_PCLQ_ROLE = f"{DOMAIN}/podclique-role"
LABEL_PCSG_NAME = f"{DOMAIN}/podcliquescalinggroup"
LABEL_PCSG_REPLICA = f"{DOMAIN}/podcliquescalinggroup-replica-index"
LABEL_PODGANG_NAME = f"{DOMAIN}/podgang"
LABEL_POD_INDEX = f"{DOMAIN}/pod-index"
LABEL_POD_TEMPLATE_HASH = f"{DOMAIN}/pod-template-hash"
LABEL_SCHEDULER_NAME = f"{DOMAIN}/scheduler-name"
LABEL_COMPONENT = f"{DOMAIN}/component"
# Marks control-plane-minted token secrets; the server maps bearer
# tokens found in such secrets to the workload actor derived from the
# secret's PCS label (server.py _workload_actor).
LABEL_TOKEN_KIND = f"{DOMAIN}/token-kind"
TOKEN_KIND_WORKLOAD = "workload"
WORKLOAD_ACTOR_PREFIX = "system:workload:"

# ---- node labels (TPU topology; GKE-compatible names kept alongside) ----
NODE_LABEL_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_LABEL_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
NODE_LABEL_SLICE = f"{DOMAIN}/tpu-slice"
NODE_LABEL_SLICE_WORKER = f"{DOMAIN}/tpu-slice-worker"
NODE_LABEL_POOL = f"{DOMAIN}/node-pool"
NODE_LABEL_SUPERBLOCK = f"{DOMAIN}/superblock"
NODE_LABEL_HOST = "kubernetes.io/hostname"
# Reservation mark, taint-like: set on every node of a bound slice by the
# reservation controller; pods carrying the matching node_selector are the
# ONLY pods placement admits onto such nodes (placement._selector_matches).
LABEL_RESERVATION = f"{DOMAIN}/reservation"
# Capacity-hold back-pointer: a SliceReservation created as a defrag
# migration hold or a roll-safe hold names the PodGang it protects here;
# the reservation controller GCs holds whose gang is gone and the chaos
# defrag-holds invariant checks the pointer stays live both ways.
LABEL_HOLD_FOR_GANG = f"{DOMAIN}/hold-for-gang"

# ---- annotations ----
# The ReuseReservationRef wiring (reference podgang.go:65-71 made live):
# names the SliceReservation a gang currently holds — set by the defrag
# executor (migration target hold) or the rolling-update path (roll-safe
# slot hold). The gang scheduler resolves it to a bound slice, constrains
# the gang's pending pods to the reserved hosts, and mirrors the value
# into PodGang.status.reuse_reservation_ref for the read surfaces.
ANNOTATION_RESERVATION_REF = f"{DOMAIN}/reuse-reservation-ref"
# The disruption contract (grove_tpu/disruption, docs/design/
# disruption-contract.md): a JSON-encoded DisruptionNotice on a PodGang
# — every PLANNED eviction (defrag migration, rolling update, spot
# reclaim) posts one and waits for the workload's checkpoint ack (or
# the deadline) before deleting bound pods. Written only through the
# CAS helpers in disruption/contract.py; the gang scheduler mirrors it
# into PodGang.status.disruption + the DisruptionTarget condition.
ANNOTATION_DISRUPTION_NOTICE = f"{DOMAIN}/disruption-notice"
# Opt-out of the barrier's auto-ack for OUT-OF-PROCESS workloads: a
# PodGang carrying this annotation (any non-empty value) declares that
# something remote checkpoints on its behalf, so a missing in-process
# responder must NOT auto-ack the notice — the remote workload watches
# status.disruption / the notice annotation and acks over the wire
# (disruption.ack_notice works against HttpClient), or the deadline
# expires and the eviction proceeds stamped barrier=expired.
ANNOTATION_CHECKPOINT_REQUIRED = f"{DOMAIN}/checkpoint-required"
# Spot-slice reclamation notice on a Node: absolute unix timestamp
# after which the host (and its whole slice — GKE spot reclaims slices
# wholesale) will be withdrawn. Set by the cloud integration or the
# chaos spot-reclaim injector; surfaced by controllers/nodelifecycle.py
# (cordon + Warning event) and consumed by the reclaim controller
# (grove_tpu/disruption/reclaim.py) as the evacuation trigger.
ANNOTATION_RECLAIM_AT = f"{DOMAIN}/reclaim-at"

# ---- env vars injected into workload pods ----
ENV_PCS_NAME = "GROVE_PCS_NAME"
ENV_PCS_INDEX = "GROVE_PCS_INDEX"
ENV_PCLQ_NAME = "GROVE_PCLQ_NAME"
ENV_PCLQ_POD_INDEX = "GROVE_PCLQ_POD_INDEX"
ENV_PCSG_NAME = "GROVE_PCSG_NAME"
ENV_PCSG_INDEX = "GROVE_PCSG_INDEX"
ENV_PCSG_TEMPLATE_NUM_PODS = "GROVE_PCSG_TEMPLATE_NUM_PODS"
ENV_HEADLESS_SERVICE = "GROVE_HEADLESS_SERVICE"
# TPU/JAX bootstrap contract (multi-host process group on a slice)
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_RESERVATION = "GROVE_RESERVATION"
ENV_TPU_SLICE_NAME = "GROVE_TPU_SLICE"
ENV_TPU_SLICE_TOPOLOGY = "GROVE_TPU_SLICE_TOPOLOGY"
ENV_MEGASLICE_INDEX = "GROVE_MULTISLICE_INDEX"  # DCN slice index (PCS replica)
ENV_MEGASLICE_COUNT = "GROVE_MULTISLICE_COUNT"

# ---- scheduling gates ----
GATE_PODGANG_PENDING = f"{DOMAIN}/podgang-pending-creation"

# ---- finalizers ----
FINALIZER_PCS = f"{DOMAIN}/podcliqueset"
FINALIZER_PCLQ = f"{DOMAIN}/podclique"
FINALIZER_PCSG = f"{DOMAIN}/podcliquescalinggroup"

# ---- condition types ----
COND_SCHEDULED = "Scheduled"
COND_READY = "Ready"
COND_INITIALIZED = "Initialized"
COND_UNHEALTHY = "Unhealthy"
COND_DISRUPTION_TARGET = "DisruptionTarget"
COND_MIN_AVAILABLE_BREACHED = "MinAvailableBreached"
COND_PCLQ_SCHEDULED = "PodCliqueScheduled"
# Placement explainability: carries the scheduler's diagnosis headline
# (PodGangStatus.last_diagnosis.reason) while a gang cannot be placed.
COND_UNSCHEDULABLE = "Unschedulable"

# ---- defaults ----
DEFAULT_TERMINATION_DELAY_SECONDS = 4 * 3600.0  # reference default 4h
DEFAULT_SCHEDULER = "gang"
