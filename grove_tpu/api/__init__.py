"""Typed resource API — the framework's equivalent of Grove's CRDs.

Mirrors the reference's API surface (SURVEY.md §2.1, A1-A7):
PodCliqueSet / PodClique / PodCliqueScalingGroup (operator API),
PodGang (scheduler API), ClusterTopology, plus — because this framework
is its own control plane, not a Kubernetes add-on — the core data-plane
types Pod and Node.
"""

from grove_tpu.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    new_meta,
)
from grove_tpu.api.core import (
    Node,
    NodeStatus,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    ContainerSpec,
)
from grove_tpu.api.podcliqueset import (
    AutoScalingConfig,
    HeadlessServiceConfig,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetStatus,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
    ScalingGroupConfig,
    StartupType,
    TopologyConstraint,
    UpdateStrategy,
)
from grove_tpu.api.podclique import PodClique, PodCliqueSpec, PodCliqueStatus
from grove_tpu.api.reservation import (
    ReservationScope,
    ReservationTemplate,
    SliceReservation,
    SliceReservationSpec,
    SliceReservationStatus,
)
from grove_tpu.api.scalinggroup import (
    PodCliqueScalingGroup,
    PodCliqueScalingGroupSpec,
    PodCliqueScalingGroupStatus,
)
from grove_tpu.api.podgang import (
    PodGang,
    PodGangPhase,
    PodGangSpec,
    PodGangStatus,
    PodGroup,
)
from grove_tpu.api.clustertopology import (
    ClusterTopology,
    TopologyLevel,
)

__all__ = [n for n in dir() if not n.startswith("_")]
