"""PodCliqueScalingGroup — cliques that scale together as one unit.

Parity with reference operator/api/core/v1alpha1/scalinggroup.go:37-77;
one PCSG replica == one multi-host JAX process group on one TPU slice.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from grove_tpu.api.meta import Condition, ObjectMeta
from grove_tpu.api.podcliqueset import AutoScalingConfig, TopologyConstraint


@dataclasses.dataclass
class PodCliqueScalingGroupSpec:
    clique_names: list[str] = dataclasses.field(default_factory=list)
    replicas: int = 1
    min_available: int = 1
    auto_scaling: Optional[AutoScalingConfig] = None
    topology: Optional[TopologyConstraint] = None
    pcs_name: str = ""
    pcs_replica: int = 0
    pod_template_hash: str = ""


@dataclasses.dataclass
class PodCliqueScalingGroupStatus:
    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    scheduled_replicas: int = 0
    updated_replicas: int = 0
    conditions: list[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodCliqueScalingGroup:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodCliqueScalingGroupSpec = dataclasses.field(
        default_factory=PodCliqueScalingGroupSpec)
    status: PodCliqueScalingGroupStatus = dataclasses.field(
        default_factory=PodCliqueScalingGroupStatus)

    KIND = "PodCliqueScalingGroup"
