"""Deterministic child-resource naming.

Role parity with the reference's api/common/namegen.go:32-112: every child
resource name is a pure function of its parents, so reconcilers can compute
expected state without reads and informer events can be mapped back to
owners by parsing names.

Scheme:
  PCLQ (standalone):      <pcs>-<pcsReplica>-<clique>
  PCSG:                   <pcs>-<pcsReplica>-<pcsg>
  PCLQ (in PCSG):         <pcs>-<pcsReplica>-<pcsg>-<pcsgReplica>-<clique>
  Pod:                    <pclq>-<podIndex>
  Base PodGang:           <pcs>-<pcsReplica>
  Scaled PodGang:         <pcs>-<pcsReplica>-<pcsg>-<pcsgReplica>
  Headless service:       <pcs>-<pcsReplica>-svc
"""

from __future__ import annotations


def pclq_name(pcs: str, pcs_replica: int, clique: str) -> str:
    return f"{pcs}-{pcs_replica}-{clique}"


def pcsg_name(pcs: str, pcs_replica: int, group: str) -> str:
    return f"{pcs}-{pcs_replica}-{group}"


def pcsg_pclq_name(pcs: str, pcs_replica: int, group: str,
                   pcsg_replica: int, clique: str) -> str:
    return f"{pcs}-{pcs_replica}-{group}-{pcsg_replica}-{clique}"


def pod_name(pclq: str, pod_index: int) -> str:
    return f"{pclq}-{pod_index}"


def pod_index_from_name(pod: str) -> int:
    """Extract the stable pod index from a pod name (hostname-derived, the
    index-reuse mechanism of the reference's internal/index/tracker.go:35)."""
    return int(pod.rsplit("-", 1)[1])


def base_podgang_name(pcs: str, pcs_replica: int) -> str:
    return f"{pcs}-{pcs_replica}"


def scaled_podgang_name(pcs: str, pcs_replica: int, group: str,
                        pcsg_replica: int) -> str:
    return f"{pcs}-{pcs_replica}-{group}-{pcsg_replica}"


def headless_service_name(pcs: str, pcs_replica: int) -> str:
    return f"{pcs}-{pcs_replica}-svc"


def reservation_name(pcs: str, template: str,
                     pcs_replica: int | None = None) -> str:
    """AllReplicas scope: <pcs>-<template>-rsv (one shared object);
    PerReplica: <pcs>-<replica>-<template>-rsv (reference ResourceClaim
    naming convention, proposal 390)."""
    if pcs_replica is None:
        return f"{pcs}-{template}-rsv"
    return f"{pcs}-{pcs_replica}-{template}-rsv"


def pcsg_reservation_name(pcs: str, pcs_replica: int, group: str,
                          template: str,
                          pcsg_replica: int | None = None) -> str:
    """PCSG-level sharing: AllReplicas = one pool per PCSG object
    (<pcs>-<r>-<group>-<template>-rsv); PerReplica = one pool per model
    instance (<pcs>-<r>-<group>-<j>-<template>-rsv)."""
    base = f"{pcs}-{pcs_replica}-{group}"
    if pcsg_replica is None:
        return f"{base}-{template}-rsv"
    return f"{base}-{pcsg_replica}-{template}-rsv"


def workload_token_secret_name(pcs: str) -> str:
    """The per-PCS workload identity token secret (reference
    satokensecret component analog)."""
    return f"{pcs}-workload-token"


def hpa_name(target_kind: str, target: str) -> str:
    return f"{target_kind.lower()}-{target}-hpa"
