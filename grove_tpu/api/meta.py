"""Object metadata, conditions, owner references.

The framework's analog of k8s ObjectMeta as used by the reference's CRDs.
Optimistic concurrency (resource_version), finalizers, and owner-based
garbage collection are implemented by grove_tpu.store.
"""

from __future__ import annotations

import dataclasses
import random
import time
import uuid
from typing import Optional


@dataclasses.dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = True


@dataclasses.dataclass
class Condition:
    """Status condition (type/status/reason/message), k8s-convention."""

    type: str = ""
    status: str = "Unknown"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: list[str] = dataclasses.field(default_factory=list)
    owner_references: list[OwnerReference] = dataclasses.field(default_factory=list)


# uids are identity handles (owner refs, expectations), not secrets: a
# private PRNG seeded once from the OS gives the same v4 format at ~5x
# less cost than uuid4's per-call os.urandom — new_meta runs for every
# EXPECTED child object each component sync, not just actual creates.
# Private instance: test code reseeding the global random module must
# not make uid sequences repeat.
_uid_rng = random.Random(uuid.uuid4().int)


def new_meta(name: str, namespace: str = "default",
             labels: dict[str, str] | None = None,
             annotations: dict[str, str] | None = None) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace,
                      uid=str(uuid.UUID(int=_uid_rng.getrandbits(128),
                                        version=4)),
                      labels=dict(labels or {}),
                      annotations=dict(annotations or {}),
                      creation_timestamp=time.time())


def trace_id_of(obj) -> str:
    """The object's lifecycle trace id ('' when untraced). Stamped into
    ``meta.annotations`` by the store at create (runtime/trace.py):
    children copy their parent's id, so one trace follows a
    PodCliqueSet's whole tree from create to Ready."""
    from grove_tpu.runtime.trace import ANNOTATION_TRACE_ID
    return obj.meta.annotations.get(ANNOTATION_TRACE_ID, "")


def set_condition(conditions: list[Condition], cond: Condition) -> list[Condition]:
    """Upsert a condition by type, bumping last_transition_time on change."""
    out = []
    found = False
    for c in conditions:
        if c.type == cond.type:
            found = True
            if c.status != cond.status:
                cond.last_transition_time = time.time()
            else:
                cond.last_transition_time = c.last_transition_time
                cond = dataclasses.replace(
                    cond, last_transition_time=c.last_transition_time)
            out.append(cond)
        else:
            out.append(c)
    if not found:
        cond.last_transition_time = time.time()
        out.append(cond)
    return out


def get_condition(conditions: list[Condition], ctype: str) -> Condition | None:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def is_condition_true(conditions: list[Condition], ctype: str) -> bool:
    c = get_condition(conditions, ctype)
    return c is not None and c.status == "True"
