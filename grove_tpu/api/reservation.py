"""SliceReservation — hierarchical sharing of TPU slice capacity.

The reference shares scarce accelerator resources across its hierarchy
via DRA ResourceClaims with scope control (proposal
390-hierarchical-resource-sharing; types at
operator/api/core/v1alpha1/podcliqueset.go:402-478 and
resourcesharing.go, realized by the resourceclaim components). On TPU
the fabric itself needs no claim — ICI comes free with slice membership
— but the *slices* are the scarce resource. The same sharing semantics
land here as slice reservations:

- A PCS declares ``ReservationTemplate``s; each materializes
  ``SliceReservation`` children (the ResourceClaim analog).
- ``scope: AllReplicas`` → ONE reservation shared by every PCS replica
  (the claim-per-PCS scope); ``scope: PerReplica`` → one reservation per
  PCS replica (disjoint slice pools, the claim-per-replica scope).
- ``clique_names`` filters which cliques consume the reservation
  (the reference's broadcast filters).

A bound reservation labels its slices' Nodes with
``constants.LABEL_RESERVATION``; covered pods carry the matching
node_selector, and placement treats the label as exclusive (taint-like)
— uncovered pods never land on reserved capacity. Binding and healing
live in ``controllers/reservation.py``.
"""

from __future__ import annotations

import dataclasses
import enum

from grove_tpu.api.meta import Condition, ObjectMeta


class ReservationScope(str, enum.Enum):
    ALL_REPLICAS = "AllReplicas"
    PER_REPLICA = "PerReplica"


@dataclasses.dataclass
class ReservationTemplate:
    """PCS-level declaration (reference ResourceClaimTemplate ref +
    scope + filter, podcliqueset.go:402-478)."""

    name: str = ""
    scope: ReservationScope = ReservationScope.ALL_REPLICAS
    # Slice shape this reservation claims ("" = any generation/topology).
    generation: str = ""
    topology: str = ""
    slice_count: int = 1
    # Which cliques consume the reservation ([] = all cliques).
    clique_names: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SliceReservationSpec:
    generation: str = ""
    topology: str = ""
    slice_count: int = 1
    # Explicit pinned slices (defrag migration targets, roll-safe slot
    # holds): when non-empty the controller binds exactly these slices —
    # occupied or not — instead of hunting free shape-matching ones.
    # The fence still applies (only consumers place onto them); existing
    # bound pods are untouched (the fence gates NEW placement only).
    slices: list[str] = dataclasses.field(default_factory=list)
    # Free-chip requirement gating the bind of an explicit slice: the
    # hold is useless if the target's headroom was eaten between plan
    # and hold, so binding waits until the slice has >= this many free
    # chips (0 = no requirement; roll holds guard an occupied slot).
    chips: int = 0
    # Hold lifetime: the controller deletes the reservation this many
    # seconds after creation (0 = never). Mandatory for holds — an
    # aborted migration or crashed holder must not strand a fenced
    # slice (proposal 0001's stranded-capacity mitigation).
    ttl_seconds: float = 0.0


class ReservationPhase(str, enum.Enum):
    PENDING = "Pending"      # waiting for free matching slices
    BOUND = "Bound"


@dataclasses.dataclass
class SliceReservationStatus:
    phase: ReservationPhase = ReservationPhase.PENDING
    bound_slices: list[str] = dataclasses.field(default_factory=list)
    message: str = ""
    conditions: list[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SliceReservation:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: SliceReservationSpec = dataclasses.field(
        default_factory=SliceReservationSpec)
    status: SliceReservationStatus = dataclasses.field(
        default_factory=SliceReservationStatus)

    KIND = "SliceReservation"
