"""Dataclass <-> plain-dict serialisation for API objects.

Equivalent in role to the reference's generated deepcopy/clientset codecs
(operator/api/core/v1alpha1/zz_generated.deepcopy.go and scheduler/client):
every API type round-trips through JSON/YAML-safe dicts so resources can be
stored, diffed, hashed, and written to disk as manifests.
"""

from __future__ import annotations

import dataclasses
import enum
import types
import typing
from typing import Any, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def to_dict(obj: Any) -> Any:
    """Recursively convert dataclasses/enums/containers to plain data.

    None-valued and default-empty fields are kept (cheap, explicit, and
    hashing cares about values anyway).
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_dict(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _strip_optional(tp: Any) -> Any:
    origin = get_origin(tp)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_dict(cls: type[T], data: Any) -> T:
    """Reconstruct ``cls`` from plain data produced by :func:`to_dict`."""
    return _from(cls, data)


def _from(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    tp = _strip_optional(tp)
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        seq = [_from(elem, v) for v in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {k: _from(vt, v) for k, v in data.items()}
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        if tp not in _HINTS_CACHE:
            _HINTS_CACHE[tp] = get_type_hints(tp)
        hints = _HINTS_CACHE[tp]
        kwargs = {}
        for f in dataclasses.fields(tp):
            if f.name in data:
                kwargs[f.name] = _from(hints[f.name], data[f.name])
        return tp(**kwargs)
    return data


def unknown_keys(cls: type, data: Any, prefix: str = "") -> list[str]:
    """Recursively find dict keys that no dataclass field accepts —
    strict-decoding support (a typo'd config key must not silently
    become a default)."""
    problems: list[str] = []
    if not (dataclasses.is_dataclass(cls) and isinstance(data, dict)):
        return problems
    if cls not in _HINTS_CACHE:
        _HINTS_CACHE[cls] = get_type_hints(cls)
    hints = _HINTS_CACHE[cls]
    fields = {f.name for f in dataclasses.fields(cls)}
    for key, value in data.items():
        path = f"{prefix}.{key}" if prefix else key
        if key not in fields:
            problems.append(path)
            continue
        tp = _strip_optional(hints[key])
        origin = get_origin(tp)
        if origin is list and isinstance(value, list):
            (elem,) = get_args(tp) or (Any,)
            for i, item in enumerate(value):
                problems.extend(unknown_keys(elem, item, f"{path}[{i}]"))
        elif dataclasses.is_dataclass(tp):
            problems.extend(unknown_keys(tp, value, path))
    return problems


def type_problems(obj: Any, prefix: str = "") -> list[str]:
    """Recursively check a dataclass instance's field values against its
    type hints; returns problem paths ("spec.replicas: expected int, got
    dict"). ``from_dict`` passes scalars through untouched, so a
    wrong-typed leaf (a dict where an int belongs) survives decoding —
    this is the companion check that catches it before the object enters
    the store."""
    problems: list[str] = []
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cls = type(obj)
        if cls not in _HINTS_CACHE:
            _HINTS_CACHE[cls] = get_type_hints(cls)
        for f in dataclasses.fields(cls):
            path = f"{prefix}.{f.name}" if prefix else f.name
            _check_type(_HINTS_CACHE[cls][f.name], getattr(obj, f.name),
                        path, problems)
    return problems


def _check_type(tp: Any, value: Any, path: str, problems: list[str]) -> None:
    origin = get_origin(tp)
    if tp is Any:
        return
    if origin is typing.Union or origin is types.UnionType:
        if value is None and type(None) in get_args(tp):
            return
        stripped = _strip_optional(tp)
        if stripped is tp:       # true multi-type union: accept
            return
        tp, origin = stripped, get_origin(stripped)
    if value is None:
        problems.append(f"{path}: expected {_tpname(tp)}, got null")
        return
    if origin in (list, tuple):
        if not isinstance(value, (list, tuple)):
            problems.append(f"{path}: expected list, got "
                            f"{type(value).__name__}")
            return
        (elem,) = get_args(tp) or (Any,)
        for i, item in enumerate(value):
            _check_type(elem, item, f"{path}[{i}]", problems)
        return
    if origin is dict:
        if not isinstance(value, dict):
            problems.append(f"{path}: expected dict, got "
                            f"{type(value).__name__}")
            return
        args = get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        for k, v in value.items():
            _check_type(vt, v, f"{path}[{k!r}]", problems)
        return
    if dataclasses.is_dataclass(tp):
        if not isinstance(value, tp):
            problems.append(f"{path}: expected {_tpname(tp)}, got "
                            f"{type(value).__name__}")
        else:
            problems.extend(type_problems(value, path))
        return
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        if not isinstance(value, tp):
            problems.append(f"{path}: expected {_tpname(tp)}, got "
                            f"{value!r}")
        return
    if tp is float:
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            problems.append(f"{path}: expected float, got "
                            f"{type(value).__name__}")
        return
    if tp in (int, str, bool):
        if not isinstance(value, tp) or (tp is int
                                         and isinstance(value, bool)):
            problems.append(f"{path}: expected {_tpname(tp)}, got "
                            f"{type(value).__name__}")
        return
    # unhandled hint shapes (e.g. protocols): accept


def _tpname(tp: Any) -> str:
    return getattr(tp, "__name__", str(tp))


def clone(obj: T) -> T:
    """Deep copy an API object (the zz_generated deepcopy analog).

    pickle round-trips dataclasses ~3x faster than the dict codec and
    ~2x faster than copy.deepcopy — this is the store's hottest path
    (every read/list/watch-event crosses it).
    """
    import pickle
    return pickle.loads(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
