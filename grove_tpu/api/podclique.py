"""PodClique — a group of identical pods fulfilling one role.

Parity with reference operator/api/core/v1alpha1/podclique.go:38-109.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from grove_tpu.api.meta import Condition, ObjectMeta
from grove_tpu.api.podcliqueset import AutoScalingConfig, PodCliqueTemplate


@dataclasses.dataclass
class PodCliqueSpec:
    role_name: str = ""
    replicas: int = 1
    min_available: int = 1
    template: PodCliqueTemplate = dataclasses.field(
        default_factory=PodCliqueTemplate)
    starts_after: list[str] = dataclasses.field(default_factory=list)  # fqns
    auto_scaling: Optional[AutoScalingConfig] = None
    # Owning context (deterministic naming inputs)
    pcs_name: str = ""
    pcs_replica: int = 0
    pcsg_name: str = ""                # "" when standalone
    pcsg_replica: int = 0
    pod_template_hash: str = ""
    scheduler_name: str = ""
    priority_class: str = ""
    subdomain: str = ""
    # Resolved SliceReservation name when a PCS reservation template
    # covers this clique ("" = unreserved). Pods inherit it as an
    # exclusive node_selector (api/reservation.py).
    reservation: str = ""


@dataclasses.dataclass
class PodCliqueStatus:
    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    scheduled_replicas: int = 0
    gated_replicas: int = 0
    updated_replicas: int = 0
    conditions: list[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodClique:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodCliqueSpec = dataclasses.field(default_factory=PodCliqueSpec)
    status: PodCliqueStatus = dataclasses.field(default_factory=PodCliqueStatus)

    KIND = "PodClique"
