"""PodCliqueSet — the top-level user-facing resource.

Capability parity with the reference's operator/api/core/v1alpha1/
podcliqueset.go:41-227 (replicas, update strategy, clique templates,
startup ordering type, headless service, topology constraint, termination
delay, scaling-group configs) re-designed TPU-first:

- ``TopologyConstraint`` speaks TPU levels (superblock / slice / host)
  instead of rack/NVLink; ``pack_level: "slice"`` means slice-atomic
  placement (all gang pods on one ICI mesh).
- A ``ScalingGroupConfig`` replica is one multi-host JAX process group;
  its pods get TPU_WORKER_ID / TPU_WORKER_HOSTNAMES injected.
- PCS replicas are multislice data-parallel copies spread over DCN.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.meta import Condition, ObjectMeta
from grove_tpu.api.reservation import ReservationTemplate


class StartupType(str, enum.Enum):
    ANY_ORDER = "AnyOrder"
    IN_ORDER = "CliqueStartupTypeInOrder"        # DAG from clique order
    EXPLICIT = "CliqueStartupTypeExplicit"       # StartsAfter edges


def effective_startup_type(tmpl: "PodCliqueSetTemplate") -> StartupType:
    """Resolve an unset startup_type (shared by defaulting and
    expected-state so direct-constructed specs behave like admitted ones).

    The reference defaults to InOrder (admission/pcs/defaulting). One
    deliberate divergence: a template that declares ``starts_after``
    edges without naming a startup type gets EXPLICIT — under a silent
    InOrder default those edges would be ignored, which round 1 shipped
    as a live bug (the enum existed but nothing consumed it).
    """
    if tmpl.startup_type is not None:
        return tmpl.startup_type
    if any(t.starts_after for t in tmpl.cliques):
        return StartupType.EXPLICIT
    return StartupType.IN_ORDER


class UpdateStrategyType(str, enum.Enum):
    ROLLING_RECREATE = "RollingRecreate"
    ON_DELETE = "OnDelete"


@dataclasses.dataclass
class UpdateStrategy:
    type: UpdateStrategyType = UpdateStrategyType.ROLLING_RECREATE


@dataclasses.dataclass
class TopologyConstraint:
    """Placement constraint against ClusterTopology levels.

    pack_level: all pods of the scope land within one domain at this level
    (e.g. "slice" → one ICI mesh). required=False means best-effort
    (preferred) packing. spread_level: sibling replicas spread across
    domains at this level (e.g. PCS replicas across slices/pools for DCN
    multislice).
    """

    pack_level: str = ""
    required: bool = True
    spread_level: str = ""


@dataclasses.dataclass
class AutoScalingConfig:
    """HPA-analog config (reference podclique.go:89-109): the autoscaler
    controller scales replicas between bounds on a target metric.

    ``min_replicas`` left unset is inferred by defaulting admission from
    the owning scope's ``replicas`` (reference defaulting
    podcliqueset.go:80,97: ScaleConfig.MinReplicas ← Replicas) — the
    autoscaler then never scales below the declared steady state unless
    the user explicitly allows it."""

    min_replicas: Optional[int] = None
    max_replicas: int = 1
    metric: str = "queue_depth"
    target_value: float = 0.0


@dataclasses.dataclass
class HeadlessServiceConfig:
    publish_not_ready_addresses: bool = True


@dataclasses.dataclass
class PodCliqueTemplate:
    """One role (leader / worker / prefill / decode...) within the set.

    ``tpu_workers`` pods are created per replica of the owning scope; each
    pod asks for ``chips_per_worker`` chips, so one clique replica maps to
    a (tpu_workers × chips_per_worker)-chip process group.
    """

    name: str = ""
    replicas: int = 1                 # pods per clique instance
    min_available: Optional[int] = None
    container: ContainerSpec = dataclasses.field(default_factory=ContainerSpec)
    tpu_chips_per_pod: int = 0
    starts_after: list[str] = dataclasses.field(default_factory=list)
    auto_scaling: Optional[AutoScalingConfig] = None
    topology: Optional[TopologyConstraint] = None
    priority_class: str = ""


@dataclasses.dataclass
class ScalingGroupConfig:
    """Cliques that scale together as one unit — one replica of the group
    is one complete multi-node model instance (reference
    scalinggroup.go:37-77)."""

    name: str = ""
    clique_names: list[str] = dataclasses.field(default_factory=list)
    replicas: int = 1
    min_available: Optional[int] = None
    auto_scaling: Optional[AutoScalingConfig] = None
    topology: Optional[TopologyConstraint] = None
    # PCSG-level slice sharing (reference proposal 390 PCSG scope):
    # AllReplicas = one pool shared by every replica of this group;
    # PerReplica = one pool PER MODEL INSTANCE — the TPU-iconic shape
    # (each multi-host instance pinned to its own slice set). Scales
    # with live (autoscaled) replica counts.
    reservations: list[ReservationTemplate] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class PodCliqueSetTemplate:
    cliques: list[PodCliqueTemplate] = dataclasses.field(default_factory=list)
    scaling_groups: list[ScalingGroupConfig] = dataclasses.field(default_factory=list)
    # Hierarchical slice-capacity sharing (the reference's resourceSharing
    # ResourceClaim templates, proposal 390 / podcliqueset.go:402-478):
    # each template materializes SliceReservation children whose bound
    # slices are the ONLY capacity covered cliques may land on.
    reservations: list[ReservationTemplate] = dataclasses.field(
        default_factory=list)
    # None → resolved by effective_startup_type (IN_ORDER, or EXPLICIT
    # when starts_after edges are declared).
    startup_type: Optional[StartupType] = None
    priority_class: str = ""
    # Scheduling priority: higher-priority gangs are considered first
    # when capacity is contended (reference PriorityClassName; numeric
    # here — this control plane has no PriorityClass registry).
    priority: int = 0
    scheduler_name: str = ""
    termination_delay_seconds: Optional[float] = None
    headless_service: Optional[HeadlessServiceConfig] = None
    topology: Optional[TopologyConstraint] = None


@dataclasses.dataclass
class PodCliqueSetSpec:
    replicas: int = 1
    template: PodCliqueSetTemplate = dataclasses.field(
        default_factory=PodCliqueSetTemplate)
    update_strategy: UpdateStrategy = dataclasses.field(
        default_factory=UpdateStrategy)
    # Third autoscaling level (reference README "Multi-Level Auto-Scaling"):
    # whole-service replicas — each new replica is a multislice DP copy
    # spread over DCN.
    auto_scaling: Optional[AutoScalingConfig] = None


@dataclasses.dataclass
class UpdateProgress:
    updated_replicas: list[int] = dataclasses.field(default_factory=list)
    current_replica: Optional[int] = None
    target_hash: str = ""
    # True → pod-shaping-only change: the selected replica's PodCliques
    # roll their pods in place (gangs survive); False → the selected
    # replica's children are deleted and recreated wholesale.
    pod_level: bool = False


@dataclasses.dataclass
class LastError:
    code: str = ""
    operation: str = ""
    message: str = ""
    observed_at: float = 0.0


@dataclasses.dataclass
class PodCliqueSetStatus:
    observed_generation: int = 0
    replicas: int = 0
    available_replicas: int = 0
    updated_replicas: int = 0
    generation_hash: str = ""
    # Gang-shaping structure only (expected.structure_hash): decides
    # replica-recreation vs in-place pod-level rolling on template change.
    structure_hash: str = ""
    rolling_update: Optional[UpdateProgress] = None
    conditions: list[Condition] = dataclasses.field(default_factory=list)
    last_errors: list[LastError] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodCliqueSet:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodCliqueSetSpec = dataclasses.field(default_factory=PodCliqueSetSpec)
    status: PodCliqueSetStatus = dataclasses.field(
        default_factory=PodCliqueSetStatus)

    KIND = "PodCliqueSet"
