"""PodGang — the gang-scheduling contract between the operator and
pluggable schedulers.

Parity with the reference's scheduler/api/core/v1alpha1/podgang.go:30-190:
a list of PodGroups with min-replica guarantees, gang- and group-level
topology constraints, a placement-reuse hint for updates, and a status
carrying phase + Scheduled/Ready/Initialized/Unhealthy conditions.

TPU-first difference: ``TopologyConstraint.pack_level == "slice"`` is an
*atomicity* constraint (the gang must land inside exactly one ICI slice),
stronger than the reference's NVLink-domain pack preference.
"""

from __future__ import annotations

import dataclasses
import enum

from grove_tpu.api.meta import Condition, ObjectMeta
from grove_tpu.api.podcliqueset import TopologyConstraint


class PodGangPhase(str, enum.Enum):
    PENDING = "Pending"
    STARTING = "Starting"
    RUNNING = "Running"


@dataclasses.dataclass
class PodGroup:
    """A set of same-shaped pods inside the gang."""

    name: str = ""
    pod_names: list[str] = dataclasses.field(default_factory=list)
    min_replicas: int = 1
    topology: TopologyConstraint | None = None


@dataclasses.dataclass
class PodGangSpec:
    groups: list[PodGroup] = dataclasses.field(default_factory=list)
    topology: TopologyConstraint | None = None
    priority_class: str = ""
    priority: int = 0
    scheduler_name: str = ""
    # Placement-reuse hint: on rolling update the replacement gang prefers
    # the slice/hosts of the gang it replaces (reference podgang.go:65-71).
    reuse_reservation_of: str = ""
    # Base gang this scaled gang depends on ("" for base gangs): scaled
    # gangs are only schedulable after their base gang is placed.
    base_gang: str = ""


@dataclasses.dataclass
class PodGangStatus:
    phase: PodGangPhase = PodGangPhase.PENDING
    conditions: list[Condition] = dataclasses.field(default_factory=list)
    placement_score: float = 0.0
    # chosen placement: slice name per group pod, filled by the scheduler
    assigned_slice: str = ""


@dataclasses.dataclass
class PodGang:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodGangSpec = dataclasses.field(default_factory=PodGangSpec)
    status: PodGangStatus = dataclasses.field(default_factory=PodGangStatus)

    KIND = "PodGang"
