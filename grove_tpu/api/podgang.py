"""PodGang — the gang-scheduling contract between the operator and
pluggable schedulers.

Parity with the reference's scheduler/api/core/v1alpha1/podgang.go:30-190:
a list of PodGroups with min-replica guarantees, gang- and group-level
topology constraints, a placement-reuse hint for updates, and a status
carrying phase + Scheduled/Ready/Initialized/Unhealthy conditions.

TPU-first difference: ``TopologyConstraint.pack_level == "slice"`` is an
*atomicity* constraint (the gang must land inside exactly one ICI slice),
stronger than the reference's NVLink-domain pack preference.
"""

from __future__ import annotations

import dataclasses
import enum

from grove_tpu.api.meta import Condition, ObjectMeta
from grove_tpu.api.podcliqueset import TopologyConstraint


class PodGangPhase(str, enum.Enum):
    PENDING = "Pending"
    STARTING = "Starting"
    RUNNING = "Running"


@dataclasses.dataclass
class PodGroup:
    """A set of same-shaped pods inside the gang."""

    name: str = ""
    pod_names: list[str] = dataclasses.field(default_factory=list)
    min_replicas: int = 1
    topology: TopologyConstraint | None = None


@dataclasses.dataclass
class PodGangSpec:
    groups: list[PodGroup] = dataclasses.field(default_factory=list)
    topology: TopologyConstraint | None = None
    priority_class: str = ""
    priority: int = 0
    scheduler_name: str = ""
    # Placement-reuse hint: on rolling update the replacement gang prefers
    # the slice/hosts of the gang it replaces (reference podgang.go:65-71).
    reuse_reservation_of: str = ""
    # Base gang this scaled gang depends on ("" for base gangs): scaled
    # gangs are only schedulable after their base gang is placed.
    base_gang: str = ""


@dataclasses.dataclass
class DomainDiagnosis:
    """One candidate domain's verdict in a failed placement attempt."""

    domain: str = ""
    level: str = ""              # topology level the domain lives at
    free_chips: int = 0
    total_chips: int = 0
    verdict: str = ""            # chip-shortfall | fragmented | selector-mismatch
    detail: str = ""
    spread_penalty: float = 0.0
    closest: bool = False        # the closest-fit candidate (CLI stars it)


@dataclasses.dataclass
class PreemptionDiagnosis:
    """Why preemption did (not) free capacity for the gang."""

    verdict: str = ""            # not-eligible | no-victims | victims-insufficient
    victims_considered: int = 0
    victim_chips: int = 0
    detail: str = ""


@dataclasses.dataclass
class PlacementDiagnosis:
    """Structured "why is this gang pending" record, built by the gang
    scheduler on FAILED placement attempts only (the happy path never
    pays for it; GROVE_EXPLAIN=0 disables it entirely). Bounded: at
    most top-K candidate domains are retained (``domains_total`` keeps
    the full candidate count honest)."""

    reason: str = ""             # ChipShortfall | TopologyPruned | Fragmented |
                                 # SelectorMismatch | PreemptionRejected |
                                 # StragglerUnplaced
    message: str = ""
    attempts: int = 0            # recorded failed attempts (refresh-throttled)
    first_failure_time: float = 0.0
    last_attempt_time: float = 0.0
    pods: int = 0
    requested_chips: int = 0
    pack_level: str = ""
    required: bool = True
    domains: list[DomainDiagnosis] = dataclasses.field(default_factory=list)
    domains_total: int = 0       # candidates before the top-K bound
    preemption: PreemptionDiagnosis | None = None
    # Capacity withheld by NotReady/cordoned nodes at attempt time —
    # the node-loss answer to "this fit yesterday". The name list is
    # bounded (top-K); count and chips cover every lost node.
    lost_nodes: list[str] = dataclasses.field(default_factory=list)
    lost_nodes_total: int = 0
    lost_chips: int = 0


@dataclasses.dataclass
class DisruptionNotice:
    """One planned-eviction barrier on a gang (the disruption contract,
    grove_tpu/disruption): posted by whoever intends to delete the
    gang's bound pods (defrag migration, rolling update, spot-slice
    reclaim), acknowledged by the workload once its checkpoint is
    durable, expiring at ``deadline`` so an unresponsive workload can
    delay — never veto — the eviction. Lives in the gang's
    ``ANNOTATION_DISRUPTION_NOTICE`` annotation (single CAS write path,
    disruption/contract.py); the scheduler mirrors it here and into a
    ``DisruptionTarget`` condition on every status write."""

    id: str = ""
    reason: str = ""           # defrag-migration | rolling-update | spot-reclaim
    requested_at: float = 0.0
    deadline: float = 0.0      # absolute; eviction proceeds past it
    acked_at: float = 0.0      # 0 = not (yet) acknowledged
    ack_source: str = ""       # workload | auto ("" while unacked)
    evicted_at: float = 0.0    # stamped the moment eviction proceeded
    barrier: str = ""          # final state at eviction: acked | expired
    coalesced: int = 0         # later post_notice calls that joined this one


@dataclasses.dataclass
class PodGangStatus:
    phase: PodGangPhase = PodGangPhase.PENDING
    conditions: list[Condition] = dataclasses.field(default_factory=list)
    placement_score: float = 0.0
    # chosen placement: slice name per group pod, filled by the scheduler
    assigned_slice: str = ""
    # The SliceReservation this gang currently holds (defrag migration
    # target or roll-safe slot hold) — the live ReuseReservationRef
    # (reference podgang.go:140-190). Mirrored from the gang's
    # reuse-reservation-ref annotation by the scheduler (single status
    # writer); "" when the gang holds nothing. Surfaced by grovectl get
    # (RESERVATION column) and grovectl explain.
    reuse_reservation_ref: str = ""
    # Placement explainability: present while the gang is unschedulable
    # (scheduler clears it on successful schedule).
    last_diagnosis: PlacementDiagnosis | None = None
    # Disruption contract: the live notice, mirrored from the
    # ANNOTATION_DISRUPTION_NOTICE annotation by the scheduler (single
    # status writer, like reuse_reservation_ref); None when no planned
    # eviction is in flight.
    disruption: DisruptionNotice | None = None


@dataclasses.dataclass
class PodGang:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodGangSpec = dataclasses.field(default_factory=PodGangSpec)
    status: PodGangStatus = dataclasses.field(default_factory=PodGangStatus)

    KIND = "PodGang"
