"""Scale test runner: N-pod PodCliqueSet deploy / steady-state / delete.

Role parity with reference e2e/tests/scale/scale_test.go:166-258
(Test_ScaleTest_1000): deploy a large PCS onto a fake fleet, measure
  deploy:    pcs-created → pods-created → pods-scheduled → pods-ready →
             pcs-available
  steady:    reconcile count over a quiet window (no-op cost)
  delete:    delete request latency + children-gone latency
and export the timeline as JSON.

Run directly:  python -m grove_tpu.scale --pods 1000
"""

from __future__ import annotations

import dataclasses
import time

from grove_tpu.api import (
    Pod,
    PodClique,
    PodCliqueSet,
    constants as c,
    new_meta,
)
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.meta import is_condition_true
from grove_tpu.api.podcliqueset import (
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
    StartupType,
)
from grove_tpu.cluster import new_cluster
from grove_tpu.scale.measurement import TimelineTracker
from grove_tpu.topology.fleet import FleetSpec, SliceSpec


@dataclasses.dataclass
class ScaleConfig:
    pods: int = 1000
    cliques: int = 4              # pods spread over this many cliques
    pcs_name: str = "scale-pcs"
    deploy_timeout: float = 600.0  # reference budget: 10 min
    # Steady-state stimulus: annotation-touch this many cliques and
    # measure the reconcile ripple (count + latency percentiles) —
    # reference scale_test.go:216-240. The p95 budget is asserted.
    steady_touches: int = 50
    # Calibrated at 300 pods: healthy p95 is ~85-130ms; a per-event
    # pathology (the thing this bound exists to catch) lands in whole
    # seconds. 0.5 keeps 4-6x headroom for loaded single-core runners
    # where wall-clock includes scheduler contention, not just work.
    steady_p95_budget_s: float = 0.5
    poll: float = 0.05
    # Per-phase sampling profiles exported here (the reference captures
    # pprof per phase and pushes to Pyroscope, scale_test.go:131).
    profile_dir: str | None = None
    # > 0: drive pod readiness through this many agent PROCESSES over
    # the HTTP wire (watch + status writes + node heartbeats) instead of
    # the in-process fake kubelet — proves the wire path holds at scale
    # (the reference's KWOK nodes still go through the apiserver).
    remote_agents: int = 0
    # > 0: after steady state, scale the whole PCS out (replicas 2) and
    # back in this many times, requiring full convergence each way — the
    # reference soak_test.go cycle, runnable in wire mode.
    soak_cycles: int = 0
    soak_timeout: float = 300.0


def _fleet_for(pods: int) -> FleetSpec:
    # CPU-style pods (chips=0) at scale — capacity is node count, matching
    # the reference's KWOK nginx pods. ~64 pods/host keeps the node list
    # small relative to the pod list.
    hosts = max(4, pods // 64)
    # v5e 4x4 slice = 4 hosts; count = hosts/4
    return FleetSpec(slices=[SliceSpec(generation="v5e", topology="4x4",
                                       count=max(1, hosts // 4))])


def run_scale_test(cfg: ScaleConfig) -> dict:
    from grove_tpu.runtime.profiler import PhaseProfiler

    tracker = TimelineTracker()
    profiler = PhaseProfiler(enabled=cfg.profile_dir is not None)
    cluster = new_cluster(fleet=_fleet_for(cfg.pods),
                          fake_kubelet=cfg.remote_agents == 0)
    per_clique = cfg.pods // cfg.cliques
    assert per_clique * cfg.cliques == cfg.pods, "pods must divide by cliques"
    server = None
    agents: list = []
    # ExitStack so the remote-agent processes are reaped on EVERY exit
    # path (assertion failure, deploy timeout) — atexit alone would leak
    # them for the rest of a pytest session. LIFO order stops agents
    # before cluster teardown; _stop_remote_agents is idempotent, so the
    # explicit stop at the end of the happy path is fine.
    import contextlib
    with contextlib.ExitStack() as stack:
        stack.enter_context(cluster)
        stack.enter_context(profiler)
        client = cluster.client
        if cfg.remote_agents > 0:
            server, agents = _spawn_remote_agents(cluster, cfg.remote_agents)
            stack.callback(lambda: _stop_remote_agents(server, agents))
        profiler.begin_phase("deploy")
        pcs = PodCliqueSet(
            meta=new_meta(cfg.pcs_name),
            spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
                cliques=[PodCliqueTemplate(
                    name=f"role{i}", replicas=per_clique,
                    min_available=per_clique, tpu_chips_per_pod=0,
                    container=ContainerSpec(argv=["sleep", "inf"]))
                    for i in range(cfg.cliques)],
                # Concurrent deploy is the thing being measured (the
                # reference's KWOK benchmark deploys all pods at once);
                # the IN_ORDER default would serialize cliques into waves.
                startup_type=StartupType.ANY_ORDER,
            )))
        client.create(pcs)
        tracker.record("deploy", "pcs-created")

        sel = {c.LABEL_PCS_NAME: cfg.pcs_name}
        deadline = time.time() + cfg.deploy_timeout
        milestones = {"pods-created": False, "pods-scheduled": False,
                      "pods-ready": False, "pcs-available": False}
        while time.time() < deadline and not all(milestones.values()):
            pods = client.list(Pod, selector=sel)
            if not milestones["pods-created"] and len(pods) >= cfg.pods:
                tracker.record("deploy", "pods-created")
                milestones["pods-created"] = True
            if not milestones["pods-scheduled"] and len(pods) >= cfg.pods \
                    and all(p.status.node_name for p in pods):
                tracker.record("deploy", "pods-scheduled")
                milestones["pods-scheduled"] = True
            if not milestones["pods-ready"] and len(pods) >= cfg.pods and all(
                    is_condition_true(p.status.conditions, c.COND_READY)
                    for p in pods):
                tracker.record("deploy", "pods-ready")
                milestones["pods-ready"] = True
            if not milestones["pcs-available"]:
                live = client.get(PodCliqueSet, cfg.pcs_name)
                if live.status.available_replicas >= 1:
                    tracker.record("deploy", "pcs-available")
                    milestones["pcs-available"] = True
            time.sleep(cfg.poll)
        if not all(milestones.values()):
            missing = [k for k, v in milestones.items() if not v]
            raise TimeoutError(f"deploy milestones not reached: {missing}")

        # Steady-state reconcile cost under a STIMULUS (reference
        # scale_test.go:216-240 triggers reconciles by touching object
        # annotations during the profiled window — an event-driven
        # control plane measures 0.0 over a quiet window, which measures
        # nothing; r2's dashboard proved it, every row 0.0). Touch N
        # cliques, then measure how many reconciles the ripple costs and
        # what each one takes (p50/p95 from the controllers' duration
        # rings, with the budget asserted from the exposed
        # reconcile-duration histogram).
        profiler.begin_phase("steady-state")
        cluster.manager.wait_idle(timeout=30.0, settle=0.3)
        before = {name: v["reconciles"] for name, v in
                  cluster.manager.healthz()["controllers"].items()}
        pclq_ctrl = next(ct for ct in cluster.manager.controllers
                         if ct.name == "podclique")
        keys_before = pclq_ctrl.snapshot_key_counts()
        for ctrl in cluster.manager.controllers:
            ctrl.durations.clear()
        # Snapshot the EXPOSED reconcile-duration histogram at window
        # start: the p95 budget below is asserted from the metrics
        # endpoint (bucket delta over the window — what a deployed
        # `histogram_quantile(rate(...))` alert computes), so the test
        # guards the same surface operators alert on.
        from grove_tpu.runtime import metrics as _m
        hist_before = _m.parse_histograms(
            cluster.manager.metrics_text(),
            "grove_reconcile_duration_seconds")
        tracker.record("steady-state", "window-start")
        t_win = time.time()
        # Round-robin the touches over the cliques: a naive list PREFIX
        # touches whichever clique's pods happen to sort first (creation
        # interleaving is nondeterministic under concurrent deploy), so
        # the per-clique floor below would flake. Interleaving makes the
        # stimulus — and the assertion — deterministic.
        by_clique: dict[str, list] = {}
        for pod in client.list(Pod, selector=sel):
            by_clique.setdefault(
                pod.meta.labels.get(c.LABEL_PCLQ_NAME, ""), []).append(pod)
        rr = [p for group in zip(*(v for v in by_clique.values() if v))
              for p in group]
        touched = 0
        touched_cliques: set[str] = set()
        for pod in rr[:cfg.steady_touches]:
            live = client.get(Pod, pod.meta.name)
            live.meta.annotations["grove.io/scale-touch"] = str(time.time())
            client.update(live)
            touched += 1
            touched_cliques.add(pod.meta.labels.get(c.LABEL_PCLQ_NAME, ""))
        # Drain the ripple: idle again means every touched object's
        # reconcile (and any fan-out) has completed.
        cluster.manager.wait_idle(timeout=60.0, settle=0.3)
        steady_window_s = max(time.time() - t_win, 1e-9)
        tracker.record("steady-state", "window-end")
        after = {name: v["reconciles"] for name, v in
                 cluster.manager.healthz()["controllers"].items()}
        steady_reconciles = sum(after[k] - before[k] for k in after)
        keys_after = pclq_ctrl.snapshot_key_counts()
        durations = sorted(
            d for ctrl in cluster.manager.controllers
            for d in list(ctrl.durations))

        def _pct(p: float) -> float:
            if not durations:
                return 0.0
            return durations[min(len(durations) - 1,
                                 int(p * len(durations)))]

        # Windowed histogram from the exposed metric: sum the bucket
        # deltas across controllers, then take the quantile. The budget
        # is asserted against THIS (bucket upper edge — conservative);
        # the ring-based _pct stays as the exact-value companion the
        # dashboard reports.
        hist_after = _m.parse_histograms(
            cluster.manager.metrics_text(),
            "grove_reconcile_duration_seconds")
        window_cum: dict[float, float] = {}
        for lbls, after_b in hist_after.items():
            delta = _m.subtract_buckets(after_b, hist_before.get(lbls, {}))
            for ub, n in delta.items():
                window_cum[ub] = window_cum.get(ub, 0.0) + n

        def _pct_metric(p: float) -> float:
            return _m.quantile_from_buckets(p, window_cum)

        # Budget: the stimulus must actually produce reconciles (≥ one
        # per touch), and a no-op-ish reconcile at scale must stay
        # cheap — p95 over the budget means list/diff work is being
        # redone per event instead of amortized. Remote mode gets 2×:
        # the wire keeps the server's GIL busy serializing lists/watch
        # replays, which inflates in-process reconcile latency (~300ms
        # p95 at 300 pods / 4 agents vs ~20ms in-process) without
        # implying any algorithmic regression — the bound still catches
        # quadratic blowups.
        # Env-tunable for loaded shared CI runners (a hard wall-clock
        # bound on a noisy box is a flake, not a regression catch).
        # The base budget is calibrated at 300 pods; a no-op reconcile's
        # list/diff work grows linearly with clique size, so the bound
        # scales linearly past that — it exists to catch QUADRATIC
        # blowups, which outrun a linear allowance immediately.
        import os as _os
        budget = float(_os.environ.get("GROVE_SCALE_P95_BUDGET_S",
                                       cfg.steady_p95_budget_s)) \
            * (2 if cfg.remote_agents else 1) \
            * max(1.0, cfg.pods / 300.0)
        assert touched > 0, "steady-state stimulus touched nothing"
        # Pod touches map to their owning clique's request and the
        # workqueue dirty-set COALESCES them (30 touches over 3 cliques
        # legitimately cost ~3-6 reconciles — that dedupe is the design,
        # reference expectations/workqueue semantics). The floor is
        # PER-CLIQUE: every clique whose pod was touched must see ≥1
        # podclique reconcile — an aggregate floor met with zero margin
        # can't distinguish "coalescing works" from "fan-out lost".
        # Reconciles ≈ touches would mean coalescing broke and steady
        # state pays per-event.
        per_clique = {}
        ns = pcs.meta.namespace
        for clique in touched_cliques:
            key = f"{ns}/{clique}"
            per_clique[clique] = (keys_after.get(key, 0)
                                  - keys_before.get(key, 0))
        missing = [k for k, v in per_clique.items() if v < 1]
        assert not missing, (
            f"touched cliques saw no reconcile: {missing} "
            f"(per-clique deltas {per_clique}, {touched} touches) — "
            "touches are not reaching controllers")
        assert steady_reconciles >= len(touched_cliques), (
            f"stimulus produced {steady_reconciles} reconciles for "
            f"{touched} touches over {len(touched_cliques)} cliques")
        assert durations, "no reconcile durations captured in the window"
        assert window_cum.get(float("inf"), 0) > 0, (
            "exposed reconcile-duration histogram recorded nothing in "
            "the steady window — the metric a deployed alert would "
            "watch is not being fed")
        assert _pct_metric(0.95) < budget, (
            f"steady-state reconcile p95 bucket "
            f"{_pct_metric(0.95) * 1e3:.1f}ms (exposed histogram) over "
            f"budget {budget * 1e3:.0f}ms; exact-ring p95 "
            f"{_pct(0.95) * 1e3:.1f}ms")

        # Soak: scale-out/in cycles with full convergence each way
        # (reference e2e/tests/scale/soak_test.go; here optionally over
        # the wire — the kubelet fleet driving readiness remotely).
        soak_cycle_s: list[float] = []
        if cfg.soak_cycles:
            profiler.begin_phase("soak")
            for cyc in range(cfg.soak_cycles):
                t_cyc = time.time()
                for want_replicas, want_pods in ((2, 2 * cfg.pods),
                                                 (1, cfg.pods)):
                    # patch, not get+update: the PCS controller's status
                    # writes race this (rv bump between get and update
                    # → ConflictError); Client.patch retries conflicts.
                    client.patch(PodCliqueSet, cfg.pcs_name,
                                 {"spec": {"replicas": want_replicas}})
                    deadline = time.time() + cfg.soak_timeout
                    while time.time() < deadline:
                        pods = client.list(Pod, selector=sel)
                        if len(pods) == want_pods and all(
                                is_condition_true(p.status.conditions,
                                                  c.COND_READY)
                                for p in pods):
                            break
                        time.sleep(cfg.poll)
                    else:
                        raise TimeoutError(
                            f"soak cycle {cyc}: never converged to "
                            f"{want_pods} ready pods")
                soak_cycle_s.append(time.time() - t_cyc)
                tracker.record("soak", f"cycle-{cyc}")

        # Delete: request latency + full cascade
        profiler.begin_phase("delete")
        t_del = time.time()
        client.delete(PodCliqueSet, cfg.pcs_name)
        delete_request_s = time.time() - t_del
        tracker.record("delete", "request-returned")
        while client.list(Pod, selector=sel) or client.list(
                PodClique, selector=sel):
            time.sleep(cfg.poll)
        tracker.record("delete", "children-gone")
        if agents:
            _stop_remote_agents(server, agents)

    result = {
        "pods": cfg.pods,
        "remote_agents": cfg.remote_agents,
        "deploy_pods_created_s": tracker.duration(
            "deploy", "pcs-created", "pods-created"),
        "deploy_pods_scheduled_s": tracker.duration(
            "deploy", "pcs-created", "pods-scheduled"),
        "deploy_pods_ready_s": tracker.duration(
            "deploy", "pcs-created", "pods-ready"),
        "deploy_available_s": tracker.duration(
            "deploy", "pcs-created", "pcs-available"),
        "steady_touches": touched,
        "steady_touched_cliques": len(touched_cliques),
        "steady_per_clique_reconciles": per_clique,
        "steady_reconciles": steady_reconciles,
        "steady_reconciles_per_s": steady_reconciles / steady_window_s,
        "steady_p50_ms": round(_pct(0.50) * 1e3, 3),
        "steady_p95_ms": round(_pct(0.95) * 1e3, 3),
        # Same window, computed from the exposed histogram (what a
        # deployed histogram_quantile alert would report).
        "steady_p95_metric_ms": round(_pct_metric(0.95) * 1e3, 3),
        "delete_request_s": delete_request_s,
        "delete_cascade_s": tracker.duration(
            "delete", "request-returned", "children-gone"),
        "timeline": tracker.export(),
    }
    if cfg.soak_cycles:
        result["soak_cycles"] = cfg.soak_cycles
        result["soak_cycle_s"] = [round(s, 3) for s in soak_cycle_s]
    if cfg.profile_dir is not None:
        result["profiles"] = profiler.export_dir(cfg.profile_dir)
    return result


def _spawn_remote_agents(cluster, n_agents: int):
    """Start the wire (HTTP API server) and N child agent processes,
    each owning a round-robin partition of the fleet's nodes
    (scale/remote.py). Children are cleaned up explicitly at the end of
    the run and by atexit on error paths."""
    import atexit
    import os
    import secrets
    import subprocess
    import sys

    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    from grove_tpu.api import Node
    from grove_tpu.server import ApiServer

    # Wire mutations require a bearer token (anonymous mutation = 401,
    # W4); mint an ephemeral operator credential for the agents — the
    # same identity `grovectl serve` bootstraps for its first client.
    token = secrets.token_urlsafe(24)
    cluster.manager.config.server_auth.tokens[token] = OPERATOR_ACTOR
    server = ApiServer(cluster, port=0)
    server.start()
    nodes = [n.meta.name for n in cluster.client.list(Node)]
    assert nodes, "fleet has no nodes to partition across agents"
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["GROVE_API_TOKEN"] = token
    agents = []
    for i in range(n_agents):
        part = nodes[i::n_agents]
        if not part:
            continue
        agents.append(subprocess.Popen(
            [sys.executable, "-m", "grove_tpu.scale.remote",
             "--server", f"http://127.0.0.1:{server.port}",
             # The watch feed wakes the kubelet pass on pod events; the
             # tick is only the polling FALLBACK — keep it slow so idle
             # agents don't keep the store busy re-listing the world
             # (at 300 pods, 4 agents list-polling at 0.5s drove the
             # steady-state reconcile p95 from ~20ms to ~350ms).
             "--nodes", ",".join(part), "--tick", "3.0"],
            env=env))
    atexit.register(_stop_remote_agents, server, agents)
    return server, agents


def _stop_remote_agents(server, agents) -> None:
    for p in agents:
        if p.poll() is None:
            p.terminate()
    for p in agents:
        try:
            p.wait(timeout=5)
        except Exception:  # noqa: BLE001 — escalate, never hang the run
            p.kill()
    agents.clear()
    if server is not None:
        server.stop()   # idempotent: _httpd is cleared on first stop


def main(argv=None) -> int:
    import argparse
    import json as _json
    import sys
    parser = argparse.ArgumentParser(prog="grove-scale")
    parser.add_argument("--pods", type=int, default=1000)
    parser.add_argument("--cliques", type=int, default=4)
    parser.add_argument("--remote-agents", type=int, default=0,
                        help="drive pod readiness through this many agent "
                             "processes over the HTTP wire (watch + status "
                             "writes + heartbeats) instead of in-process")
    parser.add_argument("--soak-cycles", type=int, default=0,
                        help="scale the PCS out (replicas 2) and back in "
                             "this many times after steady state, requiring "
                             "full convergence each way (soak_test analog)")
    parser.add_argument("--soak-timeout", type=float, default=300.0,
                        help="per-direction convergence deadline for each "
                             "soak cycle (seconds)")
    parser.add_argument("--json", help="write full timeline JSON here")
    parser.add_argument("--history",
                        help="append a summary line to this JSONL file and "
                             "report regressions vs the best prior run "
                             "(the scale-history analog of the reference's "
                             "hack/scale-history.py)")
    parser.add_argument("--label", default="",
                        help="tag for the history entry (e.g. round/commit)")
    parser.add_argument("--profile-dir",
                        help="capture per-phase sampling profiles "
                             "(collapsed-stack files + summary) here — "
                             "the Pyroscope-push analog")
    args = parser.parse_args(argv)
    result = run_scale_test(ScaleConfig(pods=args.pods, cliques=args.cliques,
                                        profile_dir=args.profile_dir,
                                        remote_agents=args.remote_agents,
                                        soak_cycles=args.soak_cycles,
                                        soak_timeout=args.soak_timeout))
    result.pop("profiles", None)  # summarized in the dir, not the stdout line
    timeline = result.pop("timeline")
    if args.json:
        with open(args.json, "w") as f:
            _json.dump({**result, "timeline": timeline}, f, indent=2)
    if args.history:
        _append_history(args.history, args.label, result)
    print(_json.dumps(result, indent=2))
    return 0


def _append_history(path: str, label: str, result: dict) -> None:
    """Run-over-run tracking: append this run, then compare the headline
    metric (pods-ready latency) against prior runs at the same pod count
    and flag regressions > 20% on stderr."""
    import json as _json
    import os
    import sys

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    prior = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        prior.append(_json.loads(line))
                    except ValueError:
                        pass
    entry = {"label": label, "ts": time.time(), **result}
    with open(path, "a") as f:
        f.write(_json.dumps(entry) + "\n")
    same_scale = [p for p in prior if p.get("pods") == result["pods"]
                  and (p.get("remote_agents", 0) or 0)
                  == (result.get("remote_agents", 0) or 0)
                  and "deploy_pods_ready_s" in p]
    if same_scale:
        best = min(p["deploy_pods_ready_s"] for p in same_scale)
        now = result["deploy_pods_ready_s"]
        if best > 0 and now > best * 1.2:
            print(f"REGRESSION: pods-ready {now:.1f}s vs best "
                  f"{best:.1f}s over {len(same_scale)} prior runs",
                  file=sys.stderr)
        else:
            print(f"history: pods-ready {now:.1f}s (best prior "
                  f"{best:.1f}s, {len(same_scale)} runs)", file=sys.stderr)


if __name__ == "__main__":
    raise SystemExit(main())
