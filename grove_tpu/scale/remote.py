"""Remote-agent scale mode: drive the fleet's pods through M agent
PROCESSES over the HTTP wire.

The in-process scale runner exercises controllers against an in-memory
store; the reference's scale harness additionally keeps its real
apiserver wire in the loop (KWOK nodes still go through the apiserver,
operator/hack/infra_manager/). This module is that analog: each child
process owns a partition of the fleet's nodes and, over an
``HttpClient``,

1. consumes the server's resumable watch feed (``GET /watch`` long-poll
   with 410/relist semantics) to react to pod binds,
2. transitions its nodes' Pending pods Running+Ready via wire status
   writes (the KWOK-style synthetic kubelet, FakeKubeletPool's pass,
   but over HTTP), and
3. heartbeats its nodes at the agent cadence (node-lease analog) so the
   node-lifecycle controller sees live hosts.

So a ``--remote-agents M`` scale run proves the watch ring, the
status-write path, and the heartbeat path hold at N pods — not just at
the 2-host e2e size.

Run as a child:  python -m grove_tpu.scale.remote --server URL \
                   --nodes host-a,host-b
"""

from __future__ import annotations

import threading
import time

from grove_tpu.agent.barrier import barrier_satisfied
from grove_tpu.api import Node, Pod
from grove_tpu.api import constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.httpclient import HttpClient, WatchGoneError


class WireNodeDriver:
    """Synthetic kubelet for a SET of nodes, entirely over the wire."""

    def __init__(self, client: HttpClient, node_names: list[str],
                 namespace: str = "default", tick: float = 1.0,
                 heartbeat_seconds: float = 5.0):
        self.client = client
        self.nodes = set(node_names)
        self.namespace = namespace
        self.tick = tick
        self.heartbeat_seconds = heartbeat_seconds
        self.log = get_logger("scale.remote")
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for target, name in ((self._watch_loop, "wire-watch"),
                             (self._heartbeat_loop, "wire-heartbeat"),
                             (self._kubelet_loop, "wire-kubelet")):
            t = threading.Thread(target=target, name=name, daemon=True)  # grovelint: disable=thread-join-in-stop -- the watch loop blocks in an HTTP long-poll up to 10s; stop() sets the flag and the daemon threads drain on their next wake (joining would stall driver shutdown the poll timeout)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def run_forever(self) -> None:
        self.start()
        while not self._stop.is_set():
            time.sleep(0.2)

    # -- watch: wake the kubelet pass on pod events ------------------------

    def _watch_loop(self) -> None:
        since = None
        while not self._stop.is_set():
            try:
                for _seq, _type, obj in self.client.watch_events(
                        kinds=["Pod"], namespace=self.namespace,
                        since=since, poll_timeout=10.0):
                    since = _seq
                    if self._stop.is_set():
                        return
                    if getattr(obj.status, "node_name", None) in self.nodes:
                        self._wake.set()
            except WatchGoneError:
                since = None        # fell off the history ring: relist
                self._wake.set()
            except GroveError as e:
                self.log.debug("watch reconnect: %s", e)
                time.sleep(0.5)

    # -- kubelet: Pending -> Running+Ready over the wire -------------------

    def _kubelet_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.tick)
            self._wake.clear()
            try:
                self._pass()
            except GroveError as e:
                self.log.debug("kubelet pass error (retried): %s", e)

    def _pass(self) -> None:
        pending = []
        # Server-side field filtering (fieldSelector analog): ask only
        # for MY nodes' Pending pods — at fleet scale the server must
        # not serialize every pod for every agent poll.
        for pod in self.client.list(
                Pod, self.namespace,
                fields={"node_name": ",".join(self.nodes),
                        "phase": PodPhase.PENDING.value}):
            if (pod.status.node_name in self.nodes
                    and pod.status.phase == PodPhase.PENDING
                    and pod.meta.deletion_timestamp is None):
                if not barrier_satisfied(self.client,
                                         pod.spec.startup_barrier,
                                         pod.meta.namespace):
                    continue
                pending.append(pod)
        if not pending:
            return
        # One batched status merge-patch for the whole pass: one round
        # trip, no rv preconditions (the server merges under its lock),
        # and controllers coalesce the burst into one reconcile instead
        # of N wake-ups — the wire stays off the deploy critical path.
        now = time.time()
        items = [(pod.meta.name, {
            "phase": PodPhase.RUNNING.value,
            "start_time": now,
            "pod_ip": (f"10.1.{hash(pod.meta.name) % 250}."
                       f"{hash(pod.meta.uid) % 250}"),
            "conditions": [{"type": c.COND_READY, "status": "True",
                            "reason": "WireNodeReady"}],
        }) for pod in pending]
        self.client.patch_status_many(Pod, items, namespace=self.namespace)

    # -- heartbeats --------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            for name in self.nodes:
                try:
                    self.client.patch_status(Node, name, {
                        "ready": True,
                        "heartbeat_time": time.time(),
                    }, namespace=self.namespace)
                except GroveError:
                    pass            # next beat retries
            self._stop.wait(self.heartbeat_seconds)


def main(argv=None) -> int:
    import argparse
    import os

    parser = argparse.ArgumentParser(prog="grove-scale-remote-agent")
    parser.add_argument("--server", required=True)
    parser.add_argument("--nodes", required=True,
                        help="comma-separated node names this agent owns")
    parser.add_argument("--tick", type=float, default=1.0)
    parser.add_argument("--heartbeat", type=float, default=5.0)
    args = parser.parse_args(argv)
    # Status writes are mutations: authenticate with the injected
    # credential (the $GROVE_API_TOKEN convention every client uses).
    driver = WireNodeDriver(
        HttpClient(args.server,
                   token=os.environ.get("GROVE_API_TOKEN", "")),
        args.nodes.split(","), tick=args.tick,
        heartbeat_seconds=args.heartbeat)
    try:
        driver.run_forever()
    except KeyboardInterrupt:
        driver.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
