"""Timeline measurement for scale tests.

Role parity with reference operator/e2e/measurement/measurement.go:167-320
(TimelineTracker): phases with named milestones, durations derived from
first/last event, JSON export for dashboards / the driver's bench record.
"""

from __future__ import annotations

import json
import time


class TimelineTracker:
    def __init__(self) -> None:
        self._events: list[tuple[str, str, float]] = []  # (phase, name, ts)
        self.t0 = time.time()

    def record(self, phase: str, name: str) -> float:
        ts = time.time()
        self._events.append((phase, name, ts))
        return ts - self.t0

    def duration(self, phase: str, start: str, end: str) -> float | None:
        ts = {name: t for p, name, t in self._events if p == phase}
        if start in ts and end in ts:
            return ts[end] - ts[start]
        return None

    def phase_events(self, phase: str) -> list[tuple[str, float]]:
        return [(name, t - self.t0) for p, name, t in self._events
                if p == phase]

    def export(self) -> dict:
        return {
            "t0": self.t0,
            "events": [{"phase": p, "name": n, "offset_s": round(t - self.t0, 4)}
                       for p, n, t in self._events],
        }

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.export(), f, indent=2)

    def summary(self) -> str:
        lines = []
        for p, n, t in self._events:
            lines.append(f"{t - self.t0:9.3f}s  {p:24s} {n}")
        return "\n".join(lines)
