from grove_tpu.scale.measurement import TimelineTracker
from grove_tpu.scale.runner import ScaleConfig, run_scale_test

__all__ = ["TimelineTracker", "ScaleConfig", "run_scale_test"]
