from grove_tpu.scale.runner import main

raise SystemExit(main())
