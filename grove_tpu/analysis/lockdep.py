"""Lock-order witness — runtime lockdep for the control plane's locks.

The Go reference leans on the race detector to keep 90k LoC of
concurrent controller code honest; this Python port has 22+
lock-holding modules and nothing but discipline. This module is the
dynamic half of that gap (grovelint is the static half): with
``GROVE_LOCKDEP=1`` the store / metrics-hub / deploy-observer /
serving-observer / defrag / standby locks are wrapped at construction,
every cross-lock acquisition records a *class-level* edge (lock
"classes" aggregate instances, the Linux lockdep model — two Stores'
locks are one "store" class), and two things become violations:

- an **acquisition-graph cycle**: thread 1 takes store→hub while
  thread 2 takes hub→store — a deadlock that hasn't fired yet, caught
  the first time both orders are *observed*, no actual interleaving
  required;
- a **blocking call under a witnessed lock**: ``time.sleep`` while
  holding the store lock stalls every writer behind a wait that has
  nothing to do with them (the PR 6 buffer-then-flush discipline,
  enforced at runtime).

Off by default and zero-cost when off: ``maybe_wrap`` returns the raw
lock unless the env flag is set at construction time, so the hot write
path never sees the proxy. Consumers: ``tools/lockdep_smoke.py``, the
chaos harness's lock-order invariant, and tests/test_lockdep.py.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import traceback

ENV = "GROVE_LOCKDEP"


def enabled() -> bool:
    return os.environ.get(ENV, "0") == "1"


@dataclasses.dataclass
class LockViolation:
    kind: str       # "cycle" | "blocking-under-lock"
    detail: str
    stack: str = ""

    def __str__(self) -> str:
        return f"[lockdep:{self.kind}] {self.detail}"


class _Held:
    __slots__ = ("name", "lock_id")

    def __init__(self, name: str, lock_id: int) -> None:
        self.name = name
        self.lock_id = lock_id


class LockWitness:
    """The process-global acquisition-graph recorder.

    Guarded by a plain (unwitnessed) lock; the held-stack is
    thread-local so the common path — no other witnessed lock held —
    costs one TLS read and no graph lock at all."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        self._tls = threading.local()
        # (from, to) -> first-observation stack (class-level edges).
        self.edges: dict[tuple[str, str], str] = {}
        self.edge_counts: dict[tuple[str, str], int] = {}
        self.violations: list[LockViolation] = []
        self._flagged_cycles: set[tuple[str, str]] = set()
        # Per-class acquire tallies — the positive control: a consumer
        # asserting "no violations" must also be able to assert the
        # locks it cares about were actually witnessed (a de-wired
        # witness reports a perfect empty graph forever). Tallies are
        # PER-THREAD dicts (no graph lock on the acquire fast path —
        # serializing every witnessed acquire through one mutex would
        # suppress the very interleavings chaos exists to provoke),
        # registered once per thread and merged at report time.
        self._tallies: list[dict[str, int]] = []

    # -- held stack --------------------------------------------------------

    def _held(self) -> list[_Held]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def held_names(self) -> list[str]:
        return [h.name for h in self._held()]

    # -- events from witnessed locks ---------------------------------------

    def note_acquire(self, name: str, lock_id: int) -> None:
        """Record edges held→name, then push. Called BEFORE the inner
        acquire: the deadlock potential exists at attempt time."""
        held = self._held()
        tally = getattr(self._tls, "tally", None)
        if tally is None:
            tally = self._tls.tally = {}
            with self._graph_lock:    # once per thread, not per acquire
                self._tallies.append(tally)
        tally[name] = tally.get(name, 0) + 1
        reentrant = any(h.lock_id == lock_id for h in held)
        if not reentrant:
            for h in held:
                # Same-class different-instance nesting is not an
                # inter-class order (and a class-level self-edge would
                # flag every such pair as a cycle).
                if h.name != name:
                    self._add_edge(h.name, name)
        held.append(_Held(name, lock_id))

    def note_acquire_failed(self, lock_id: int) -> None:
        """Undo the push for a failed non-blocking acquire."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock_id == lock_id:
                del held[i]
                return

    def note_release(self, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock_id == lock_id:
                del held[i]
                return

    def note_release_all(self, lock_id: int) -> int:
        """Condition-wait support (RLock._release_save): pop every
        nested hold of this lock, return how many."""
        held = self._held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock_id == lock_id:
                del held[i]
                n += 1
        return n

    def note_reacquire(self, name: str, lock_id: int, n: int) -> None:
        """Condition-wake support (RLock._acquire_restore): the lock is
        back; no edges — the order was already recorded at first
        acquire, and edges from a wakeup would invert causality."""
        held = self._held()
        for _ in range(max(1, n)):
            held.append(_Held(name, lock_id))

    def note_blocking(self, what: str) -> None:
        """A known-blocking call is happening on this thread; if any
        witnessed lock is held, that's a violation."""
        held = self.held_names()
        if not held:
            return
        stack = "".join(traceback.format_stack(limit=12)[:-2])
        with self._graph_lock:
            self.violations.append(LockViolation(
                "blocking-under-lock",
                f"{what} while holding {held} — every other thread "
                "queued on those locks waits it out",
                stack))

    # -- graph -------------------------------------------------------------

    def _add_edge(self, a: str, b: str) -> None:
        stack = None
        with self._graph_lock:
            key = (a, b)
            self.edge_counts[key] = self.edge_counts.get(key, 0) + 1
            if key not in self.edges:
                stack = "".join(traceback.format_stack(limit=12)[:-3])
                self.edges[key] = stack
            # Immediate lockdep-style detection: does b already reach a?
            if key not in self._flagged_cycles and self._reaches(b, a):
                self._flagged_cycles.add(key)
                self.violations.append(LockViolation(
                    "cycle",
                    f"acquisition order {a} -> {b} closes a cycle "
                    f"(some thread has taken {b} .. -> {a}); ABBA "
                    "deadlock armed",
                    self.edges.get(key, "") or (stack or "")))

    def _reaches(self, src: str, dst: str) -> bool:
        """DFS over recorded edges; caller holds _graph_lock."""
        seen = set()
        frontier = [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            frontier.extend(b for (a, b) in self.edges if a == node)
        return False

    # -- reporting ---------------------------------------------------------

    def check(self) -> list[LockViolation]:
        """All violations observed so far (cycles are recorded at edge
        insertion; this is a stable read, not a recompute)."""
        with self._graph_lock:
            return list(self.violations)

    def report(self) -> dict:
        with self._graph_lock:
            return {
                "enabled": enabled(),
                "acquires": self._merged_acquires(),
                "edges": [{"from": a, "to": b,
                           "count": self.edge_counts.get((a, b), 0)}
                          for (a, b) in sorted(self.edges)],
                "violations": [dataclasses.asdict(v)
                               for v in self.violations],
            }

    def _merged_acquires(self) -> dict[str, int]:
        """Sum the per-thread tallies (caller holds _graph_lock, which
        guards the registry list; the dicts themselves mutate lock-free
        on their owner threads, so snapshot each with a retry — a
        live thread inserting a NEW class mid-copy is the only race,
        and class keys stabilize after its first few acquires)."""
        out: dict[str, int] = {}
        for tally in self._tallies:
            for _ in range(3):
                try:
                    snap = dict(tally)
                    break
                except RuntimeError:
                    continue
            else:
                snap = {}
            for name, n in snap.items():
                out[name] = out.get(name, 0) + n
        return out

    def reset(self) -> None:
        with self._graph_lock:
            self.edges.clear()
            self.edge_counts.clear()
            self.violations.clear()
            self._flagged_cycles.clear()
            for tally in self._tallies:
                tally.clear()


_WITNESS = LockWitness()


def witness() -> LockWitness:
    return _WITNESS


class _WitnessedLock:
    """Proxy for a plain ``threading.Lock``: acquire/release feed the
    witness; everything else delegates. Deliberately does NOT define
    ``_release_save``/``_acquire_restore`` — a plain Lock has neither,
    and a Condition built on one must see the same surface."""

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _WITNESS.note_acquire(self._name, id(self))
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            _WITNESS.note_acquire_failed(id(self))
        return ok

    def release(self) -> None:
        self._inner.release()
        _WITNESS.note_release(id(self))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<witnessed {self._name} {self._inner!r}>"


class _WitnessedRLock(_WitnessedLock):
    """RLock proxy: additionally speaks the Condition protocol
    (``_is_owned``/``_release_save``/``_acquire_restore``) so
    ``threading.Condition(store._lock)`` keeps working — and keeps the
    witness's held-stack truthful across a wait()."""

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        n = _WITNESS.note_release_all(id(self))
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        self._inner._acquire_restore(state)
        _WITNESS.note_reacquire(self._name, id(self), n)

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        locked = getattr(self._inner, "locked", None)
        return locked() if callable(locked) else self._inner._is_owned()


_real_sleep = time.sleep
_probes_installed = False


def _checking_sleep(seconds: float) -> None:
    # Sub-millisecond sleeps are scheduler yields (spin-wait etiquette),
    # not blocking waits; flagging them would drown the signal.
    if seconds >= 0.001:
        _WITNESS.note_blocking(f"time.sleep({seconds:g})")
    _real_sleep(seconds)


def install_blocking_probes() -> None:
    """Patch the known-blocking calls (``time.sleep``) with a
    held-lock check. Opt-in diagnostics only — never on a default
    path; idempotent."""
    global _probes_installed
    if _probes_installed:
        return
    time.sleep = _checking_sleep
    _probes_installed = True


def uninstall_blocking_probes() -> None:
    global _probes_installed
    if _probes_installed:
        time.sleep = _real_sleep
        _probes_installed = False


def maybe_wrap(lock, name: str):
    """The one call sites use: returns ``lock`` untouched unless
    GROVE_LOCKDEP=1 was set when the owning object was constructed
    (zero overhead when off — the hot path never sees the proxy)."""
    if not enabled():
        return lock
    install_blocking_probes()
    if hasattr(lock, "_release_save"):      # RLock
        return _WitnessedRLock(lock, name)
    return _WitnessedLock(lock, name)
