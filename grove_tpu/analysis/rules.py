"""The rule catalog — one class per invariant this codebase paid to learn.

Every rule header names the incident it encodes; the long-form history
is docs/design/static-analysis.md. Rules are scoped (``applies``) to
the modules whose contract they enforce — a rule about the store lock
does not parse the model code, so false-positive surface stays small
enough that a finding means something.
"""

from __future__ import annotations

import ast

from grove_tpu.analysis.grovelint import Finding, ModuleFile, Rule

# The store/client write verbs — one list shared by the leader-client
# rule and anyone gating on "is this a mutation".
WRITE_VERBS = frozenset({
    "create", "update", "update_status", "update_status_many",
    "patch_status", "patch_status_many", "patch", "delete",
})

JAX_MODULES = ("jax", "jaxlib")


def _is_jax_import(node: ast.stmt) -> bool:
    if isinstance(node, ast.Import):
        return any(a.name.split(".")[0] in JAX_MODULES for a in node.names)
    if isinstance(node, ast.ImportFrom):
        return bool(node.module) and node.module.split(".")[0] in JAX_MODULES
    return False


def _const_number(node: ast.AST) -> float | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return float(node.value)
    return None


class HubUnderStoreLock(Rule):
    """PR 6's overhead discipline: the MetricsHub's lock is held across
    every /metrics render, so a hub call made while holding the store
    lock stalls ALL writers behind each scrape. Store code buffers
    telemetry in per-thread records under the lock and flushes in one
    hub acquisition after release (store/writeobs.py); this rule keeps
    it that way. Scope: grove_tpu/store/. Under-lock regions are
    ``with self._locked_write(..)`` / ``with self._lock`` bodies plus
    functions named ``*_locked`` (the store's under-lock idiom)."""

    name = "hub-under-store-lock"
    description = ("no MetricsHub/GLOBAL_METRICS call reachable while "
                   "the store lock is held (buffer + flush after "
                   "release instead)")

    HUB_NAMES = {"GLOBAL_METRICS"}
    HUB_METHODS = {"inc", "observe", "set", "bulk", "render",
                   "set_gauge_family", "observe_many"}

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel.startswith("grove_tpu/store/")

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        hub_touching = self._hub_touching_functions(mod)

        for region, owner in self._locked_regions(mod):
            for node in ast.walk(region):
                out.extend(self._judge(mod, node, owner, hub_touching))
        return out

    # A function "touches the hub" when it references GLOBAL_METRICS or
    # calls writeobs.flush; calls to such functions from under-lock
    # regions are one-hop violations.
    def _hub_touching_functions(self, mod: ModuleFile) -> set[str]:
        touching: set[str] = set()
        for qual, fn in self._functions(mod):
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id in self.HUB_NAMES:
                    touching.add(qual)
                    break
                chain = self.attr_chain(node) if isinstance(
                    node, ast.Attribute) else []
                if chain and (set(chain) & self.HUB_NAMES
                              or chain[-2:] == ["writeobs", "flush"]):
                    touching.add(qual)
                    break
        return touching

    @staticmethod
    def _functions(mod: ModuleFile):
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node.name, node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield f"{node.name}.{sub.name}", sub

    def _locked_regions(self, mod: ModuleFile):
        """Yield (ast-node, owner-class-name) pairs whose whole subtree
        runs with the store lock held."""
        for qual, fn in self._functions(mod):
            owner = qual.split(".")[0] if "." in qual else ""
            if fn.name.endswith("_locked"):
                yield fn, owner
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    expr = item.context_expr
                    chain = []
                    if isinstance(expr, ast.Call):
                        chain = self.attr_chain(expr.func)
                    elif isinstance(expr, ast.Attribute):
                        chain = self.attr_chain(expr)
                    if chain and (chain[-1] == "_locked_write"
                                  or chain[-1].endswith("_lock")
                                  or chain[-1] == "_event_cond"):
                        for stmt in node.body:
                            yield stmt, owner

    def _judge(self, mod: ModuleFile, node: ast.AST, owner: str,
               hub_touching: set[str]) -> list[Finding]:
        out = []
        if isinstance(node, ast.Name) and node.id in self.HUB_NAMES:
            out.append(self.finding(
                mod, node,
                "GLOBAL_METRICS touched under the store lock — buffer "
                "in the thread's WriteRecord and flush after release "
                "(store/writeobs.py)"))
        elif isinstance(node, ast.Call):
            chain = self.attr_chain(node.func)
            if chain[-2:] == ["writeobs", "flush"]:
                out.append(self.finding(
                    mod, node,
                    "writeobs.flush under the store lock — the flush "
                    "IS the post-release hub batch; call it after the "
                    "guard exits"))
            elif len(chain) == 2 and chain[0] == "self":
                qual = f"{owner}.{chain[1]}" if owner else chain[1]
                if qual in hub_touching:
                    out.append(self.finding(
                        mod, node,
                        f"call to hub-touching {qual}() under the "
                        "store lock"))
            elif len(chain) == 1 and chain[0] in hub_touching:
                out.append(self.finding(
                    mod, node,
                    f"call to hub-touching {chain[0]}() under the "
                    "store lock"))
        return out


class LeaderClientWrite(Rule):
    """PR 10's zombie-leader guard: control-plane writers (controllers,
    schedulers, autoscaler, defrag) must write through the manager's
    epoch-stamped ``leader_client``/``cached_client`` so a deposed
    replica's in-flight write is FENCED, not committed. A write through
    ``mgr.client`` (the unfenced data-plane identity) or a locally
    minted ``Client(...)`` silently reopens the split-brain race the
    fencing epoch closed."""

    name = "leader-client-write"
    description = ("control-plane writes go through the epoch-fenced "
                   "leader client, never mgr.client / a fresh Client()")

    SCOPES = ("grove_tpu/controllers/", "grove_tpu/scheduler/",
              "grove_tpu/defrag/", "grove_tpu/disruption/",
              "grove_tpu/autoscale.py")
    MANAGER_NAMES = {"mgr", "manager"}

    def applies(self, mod: ModuleFile) -> bool:
        return any(mod.rel.startswith(s) for s in self.SCOPES)

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # Minting an unfenced writer locally.
            if isinstance(node.func, ast.Name) and node.func.id == "Client":
                out.append(self.finding(
                    mod, node,
                    "direct Client(...) construction in a control-plane "
                    "writer — accept the manager's epoch-fenced "
                    "leader_client/cached_client by injection instead"))
                continue
            chain = self.attr_chain(node.func)
            if len(chain) < 3 or chain[-1] not in WRITE_VERBS:
                continue
            # <mgr|manager|self.mgr|self.manager>.client.<write-verb>()
            base, attr = chain[:-2], chain[-2]
            if attr != "client":
                continue
            root = base[-1] if base else ""
            if root in self.MANAGER_NAMES or (
                    len(base) >= 2 and base[-2] == "self"
                    and base[-1] in self.MANAGER_NAMES):
                out.append(self.finding(
                    mod, node,
                    f"write verb .{chain[-1]}() on {'.'.join(chain[:-1])} "
                    "— the manager's plain client is the UNFENCED "
                    "data-plane identity; control-plane writes use "
                    "mgr.leader_client (epoch-stamped)"))
        return out


class JaxInTelemetry(Rule):
    """PR 7/11's "nothing on the JIT path": host-side telemetry modules
    must stay importable and callable without touching JAX — a jax
    import at module scope drags XLA init into the control plane, and
    an unbracketed jax call in a telemetry hot path can trigger a
    device sync inside the serving loop. The sanctioned dispatch
    bracket is a *function-local* ``import jax`` (the xprof idiom:
    paid only inside the documented roofline/compile-tracker calls,
    never at import or on the steady telemetry path)."""

    name = "jax-in-telemetry"
    description = ("no module-level jax/jnp in host-side telemetry; "
                   "jax use only inside a function-local import bracket")

    TELEMETRY_MODULES = {
        "grove_tpu/serving/slo.py",
        "grove_tpu/serving/xprof.py",
        "grove_tpu/serving/reqtrace.py",
        "grove_tpu/serving/metrics_push.py",
        "grove_tpu/runtime/metrics.py",
        "grove_tpu/runtime/servingwatch.py",
        "grove_tpu/store/writeobs.py",
    }
    JAX_NAMES = {"jax", "jnp", "jaxlib"}

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel in self.TELEMETRY_MODULES

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        funcs: list[ast.AST] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append(node)
        in_func: set[int] = {id(n) for f in funcs for n in ast.walk(f)}

        # Module-level jax imports: always a finding.
        for node in ast.walk(mod.tree):
            if _is_jax_import(node) and id(node) not in in_func:
                out.append(self.finding(
                    mod, node,
                    "module-level jax import in a host-side telemetry "
                    "module — move it inside the dispatch-bracket "
                    "function that needs it"))

        # jax/jnp name use inside a function without its own bracket
        # import (i.e. leaning on some module-level import).
        for fn in funcs:
            bracket = any(_is_jax_import(n) for n in ast.walk(fn))
            if bracket:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id in self.JAX_NAMES \
                        and isinstance(node.ctx, ast.Load):
                    out.append(self.finding(
                        mod, node,
                        f"'{node.id}' used in telemetry function "
                        f"{fn.name}() without a function-local import "
                        "bracket"))
        # Module-level (non-function) jax name use.
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Name) and node.id in self.JAX_NAMES \
                    and isinstance(node.ctx, ast.Load) \
                    and id(node) not in in_func:
                out.append(self.finding(
                    mod, node,
                    f"module-level '{node.id}' use in a host-side "
                    "telemetry module"))
        return out


class RawTestSleep(Rule):
    """PR 7's one-flake-per-slow-run lesson: the container's CPU shares
    throttle unpredictably (identical code swung the suite 155s→259s),
    so every wall-clock wait in tests scales through TIME_SCALE
    (runtime/timescale.py) at one chokepoint. A raw ``time.sleep(0.6)``
    settle or a hand-rolled ``time.time() + 20`` deadline is right on a
    fast box and a flake on a throttled one. Poll intervals (< 0.25s,
    inside a scaled-deadline loop) are fine — they never sleep a
    deadline out."""

    name = "raw-test-sleep"
    description = ("test waits must scale through runtime/timescale.py "
                   "(settle()/scaled()), not raw sleeps or deadlines")

    # Below this a literal sleep is a poll interval, not a deadline.
    DEADLINE_FLOOR = 0.25

    def applies(self, mod: ModuleFile) -> bool:
        return mod.is_test

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                chain = self.attr_chain(node.func)
                if chain in (["time", "sleep"], ["sleep"]) and node.args:
                    v = _const_number(node.args[0])
                    if v is not None and v >= self.DEADLINE_FLOOR:
                        out.append(self.finding(
                            mod, node,
                            f"raw time.sleep({v:g}) — a fixed settle "
                            "this long is a deadline; use "
                            f"timing.settle({v:g}) so a throttled "
                            "runner gets proportionally more"))
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
                left = node.left
                if isinstance(left, ast.Call):
                    chain = self.attr_chain(left.func)
                    if chain in (["time", "time"], ["time", "monotonic"]):
                        v = _const_number(node.right)
                        if v is not None:
                            out.append(self.finding(
                                mod, node,
                                f"unscaled deadline time.{chain[-1]}() + "
                                f"{v:g} — wrap the budget in scaled() "
                                "(tests/timing.py)"))
        return out


class ThreadJoinInStop(Rule):
    """The runnable contract (runtime/manager.py): the manager calls
    ``stop()`` on every runnable at shutdown, and a started thread that
    stop() doesn't join keeps mutating the store/hub while teardown
    (or the next test) runs — the chaos harness's original flake
    factory. Any class with start()/stop() that creates a
    threading.Thread must join it in stop() (directly or via a helper
    stop() calls)."""

    name = "thread-join-in-stop"
    description = ("a runnable that starts a threading.Thread must "
                   "join it in its stop()")

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel.startswith("grove_tpu/")

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(mod, node))
        return out

    def _check_class(self, mod: ModuleFile,
                     cls: ast.ClassDef) -> list[Finding]:
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if "start" not in methods or "stop" not in methods:
            return []
        thread_calls = []
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call):
                    chain = self.attr_chain(node.func)
                    if chain in (["threading", "Thread"], ["Thread"]):
                        thread_calls.append(node)
        if not thread_calls:
            return []
        if self._joins(methods["stop"], methods, depth=2):
            return []
        return [self.finding(
            mod, node,
            f"{cls.name} starts a threading.Thread but its stop() "
            "never joins one — an unjoined runnable thread outlives "
            "shutdown and races teardown")
            for node in thread_calls]

    def _joins(self, fn: ast.AST, methods: dict, depth: int) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = self.attr_chain(node.func)
                if chain and chain[-1] == "join" \
                        and self._is_thread_join(node, chain):
                    return True
                # One/two-hop: stop() delegating to a helper that joins.
                if depth > 0 and len(chain) == 2 and chain[0] == "self" \
                        and chain[1] in methods:
                    if self._joins(methods[chain[1]], methods, depth - 1):
                        return True
        return False

    @staticmethod
    def _is_thread_join(node: ast.Call, chain: list[str]) -> bool:
        """A bare ``.join(`` also matches os.path.join and
        str.join — both common in teardown, and either would
        permanently blind this rule for the class. A THREAD join is
        one whose receiver names a thread (``self._thread.join()``,
        ``t.join()`` over a threads list) or that passes the
        ``timeout=`` kwarg only thread/process joins accept."""
        if any(k.arg == "timeout" for k in node.keywords):
            return True
        return any("thread" in part.lower() or part in ("t", "th")
                   for part in chain[:-1])


class CloneBeforeMutate(Rule):
    """The informer-cache contract (runtime/informer.py): list-shaped
    reads through the cached client / listers return SHARED objects —
    one mutation in place corrupts every other reader's view of the
    cache (and the store's per-version snapshot clones). Reconcilers
    that edit a listed object ``clone()`` first. This rule tracks, per
    function, names bound from ``.list(...)``/``.list_snapshot(...)``
    (and loop vars over them) and flags attribute/subscript stores on
    them without an intervening clone."""

    name = "clone-before-mutate"
    description = ("objects from informer-cache lists are shared: "
                   "clone() before mutating")

    SCOPES = ("grove_tpu/controllers/", "grove_tpu/scheduler/",
              "grove_tpu/defrag/", "grove_tpu/disruption/",
              "grove_tpu/autoscale.py")
    LIST_VERBS = {"list", "list_snapshot"}
    CLONERS = {"clone", "serde_clone", "deepcopy", "replace"}

    def applies(self, mod: ModuleFile) -> bool:
        return any(mod.rel.startswith(s) for s in self.SCOPES)

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(mod, node))
        return out

    def _is_list_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = self.attr_chain(node.func)
        return bool(chain) and chain[-1] in self.LIST_VERBS

    def _check_function(self, mod: ModuleFile, fn: ast.AST) -> list[Finding]:
        out: list[Finding] = []
        # env: name -> "collection" (a shared list) | "object" (a shared
        # element). A forward pass in statement order; assignment from
        # anything else kills the taint.
        env: dict[str, str] = {}

        def root_name(node: ast.AST) -> str | None:
            while isinstance(node, (ast.Attribute, ast.Subscript)):
                node = node.value
            return node.id if isinstance(node, ast.Name) else None

        def visit(stmts: list[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    val = stmt.value
                    if self._is_list_call(val):
                        env[name] = "collection"
                    elif isinstance(val, ast.Call) and isinstance(
                            val.func, ast.Name) \
                            and val.func.id in self.CLONERS:
                        env.pop(name, None)
                    elif isinstance(val, ast.Subscript) \
                            and env.get(root_name(val) or "") == "collection":
                        env[name] = "object"
                    elif isinstance(val, ast.Name) and val.id in env:
                        env[name] = env[val.id]
                    else:
                        env.pop(name, None)
                elif isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    targets = (stmt.targets if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for tgt in targets:
                        if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                            root = root_name(tgt)
                            if root and env.get(root) == "object":
                                out.append(self.finding(
                                    mod, stmt,
                                    f"mutating '{root}', an object from "
                                    "a shared list read — clone() it "
                                    "first (informer-cache contract, "
                                    "runtime/informer.py)"))
                if isinstance(stmt, ast.For):
                    tainted = False
                    if self._is_list_call(stmt.iter):
                        tainted = True
                    elif isinstance(stmt.iter, ast.Name) \
                            and env.get(stmt.iter.id) == "collection":
                        tainted = True
                    if tainted and isinstance(stmt.target, ast.Name):
                        env[stmt.target.id] = "object"
                    visit(stmt.body)
                    visit(stmt.orelse)
                elif isinstance(stmt, (ast.If, ast.While)):
                    visit(stmt.body)
                    visit(stmt.orelse)
                elif isinstance(stmt, ast.With):
                    visit(stmt.body)
                elif isinstance(stmt, ast.Try):
                    visit(stmt.body)
                    for h in stmt.handlers:
                        visit(h.body)
                    visit(stmt.orelse)
                    visit(stmt.finalbody)

        visit(fn.body)
        return out


class HostSyncInStepLoop(Rule):
    """The paged-engine rebuild's hot-path discipline (PR 15): the
    decode step loop dispatches asynchronously, and a host sync —
    ``jax.block_until_ready``, ``.item()``, ``np.asarray`` on a device
    value — on the dispatch path stalls the chain for a device round
    trip PER STEP: the difference between dispatch-bound and HBM-bound
    decode on high-latency transports (the tunnelled PJRT relay most
    of all). Scope is the WHOLE dispatch path: ``step()``/``run()``
    and the per-tick internals ``_decode_tick()``/``_prefill_tick()``
    they delegate to. The one sanctioned sync there is the xprof
    sampling gate (``if sampled: block_until_ready`` — paid on
    1/N dispatches by design). Window drains and prefill-completion
    fetches live in named helpers (``_drain``/``_fetch_windows``/
    ``_finish_prefill``) outside this rule's scope: once per window or
    per request, never per step — moving a sync there is the fix, not
    an evasion."""

    name = "host-sync-in-step-loop"
    description = ("no block_until_ready/.item()/np.asarray on the "
                   "engine dispatch path (step/run/_decode_tick/"
                   "_prefill_tick) except under the sampling gate")

    # The per-step dispatch path: the public tick entrypoints AND the
    # per-tick internals they delegate to — scoping only to step/run
    # would leave the paged engine's actual dispatch bodies unchecked.
    STEP_FUNCS = {"step", "run", "_decode_tick", "_prefill_tick",
                  "_spec_tick"}
    # What marks an If-test as THE sampling gate: the bound gate flag
    # (``sampled = x is not None and x.should_sample()``) or the gate
    # method itself. Deliberately NOT substrings like "sample" or
    # "xprof" — ``if self._sampling:`` / ``if self.xprof is not
    # None:`` are mode branches taken EVERY dispatch, and a sync
    # hidden under either is exactly the per-step stall this rule
    # exists to catch.
    GATE_NAMES = {"sampled", "should_sample"}
    NP_ROOTS = {"np", "numpy"}

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel == "grove_tpu/serving/engine.py"

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name in self.STEP_FUNCS:
                    self._visit(mod, fn.body, gated=False, out=out)
        return out

    def _is_gate(self, test: ast.AST) -> bool:
        for node in ast.walk(test):
            name = ""
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if name.lower() in self.GATE_NAMES:
                return True
        return False

    def _visit(self, mod: ModuleFile, stmts: list[ast.stmt], gated: bool,
               out: list[Finding]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                # The TEST itself runs every step — a sync there (e.g.
                # `if self._flag.item():`) is flagged under the
                # current gating, while the gate's own body is exempt.
                self._scan_expr(mod, stmt.test, gated, out)
                self._visit(mod, stmt.body,
                            gated or self._is_gate(stmt.test), out)
                self._visit(mod, stmt.orelse, gated, out)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                header = stmt.iter if isinstance(stmt, ast.For) \
                    else stmt.test
                self._scan_expr(mod, header, gated, out)
                self._visit(mod, stmt.body, gated, out)
                self._visit(mod, stmt.orelse, gated, out)
                continue
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._scan_expr(mod, item.context_expr, gated, out)
                self._visit(mod, stmt.body, gated, out)
                continue
            if isinstance(stmt, ast.Try):
                self._visit(mod, stmt.body, gated, out)
                for h in stmt.handlers:
                    self._visit(mod, h.body, gated, out)
                self._visit(mod, stmt.orelse, gated, out)
                self._visit(mod, stmt.finalbody, gated, out)
                continue
            self._scan_expr(mod, stmt, gated, out)

    def _scan_expr(self, mod: ModuleFile, node: ast.AST, gated: bool,
                   out: list[Finding]) -> None:
        if gated or node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                msg = self._sync_call(sub)
                if msg:
                    out.append(self.finding(mod, sub, msg))

    def _sync_call(self, node: ast.Call) -> str | None:
        chain = self.attr_chain(node.func)
        if not chain:
            return None
        if chain[-1] == "block_until_ready":
            return ("jax.block_until_ready on the step path outside "
                    "the sampling gate — the dispatch chain stalls one "
                    "round trip per step; sync in a once-per-window "
                    "helper instead")
        if chain[-1] == "item" and not node.args and not node.keywords:
            return (".item() on the step path — a device→host sync "
                    "per step; accumulate on device and drain per "
                    "window")
        if chain[-1] == "asarray" and len(chain) >= 2 \
                and chain[-2] in self.NP_ROOTS:
            return ("np.asarray on the step path fetches a device "
                    "value synchronously — move the fetch into the "
                    "window drain helper")
        return None


class ReqtraceInStepLoop(HostSyncInStepLoop):
    """The request observatory's hot-path discipline (PR 19,
    docs/design/request-tracing.md): per-request seam stamps
    (enqueue/admit/handoff/done) are unconditional but fire once per
    REQUEST from named helpers; anything recorded per TICK from the
    dispatch path — a prefill chunk span, a spec-window note — takes
    the recorder's lock every engine tick and must sit behind the
    sampling gate (``traced = rt is not None and rt.should_sample()``),
    exactly like xprof's flight recorder. An ungated note call in
    ``_decode_tick``/``_prefill_tick`` turns "sampled decoration" into
    a per-tick lock acquisition — the overhead pin this rule keeps
    honest. Reuses the host-sync rule's walk: same step-path scope,
    same gate detection (plus the reqtrace gate's ``traced`` flag)."""

    name = "reqtrace-gate"
    description = ("reqtrace span recording on the engine dispatch "
                   "path (step/run/_decode_tick/_prefill_tick) must "
                   "sit behind the sampling gate (traced/sampled/"
                   "should_sample)")

    GATE_NAMES = {"sampled", "should_sample", "traced"}
    NOTE_METHODS = {
        "note_enqueue", "note_admit", "note_prefix", "note_chunk",
        "note_prefill_done", "note_handoff", "note_decode_start",
        "note_preempt", "note_resume", "note_spec_window",
        "note_done", "adopt_trace",
    }

    def _sync_call(self, node: ast.Call) -> str | None:
        chain = self.attr_chain(node.func)
        if chain and chain[-1] in self.NOTE_METHODS:
            return (f".{chain[-1]}() on the step path outside the "
                    "sampling gate — per-tick span recording takes "
                    "the recorder lock every dispatch; gate it with "
                    "``traced = rt is not None and rt.should_sample()``"
                    " or stamp once per request from a named helper")
        return None


class WriteToSharedBlock(Rule):
    """The prefix cache's write-safety contract (PR 16,
    docs/design/prefix-cache.md): with refcounted block sharing, a KV
    scatter into a block another sequence also references silently
    corrupts THAT sequence's attention — the worst failure mode in the
    serving stack because nothing raises; tokens just go subtly wrong
    for an unrelated user. The engine's discipline is that every
    function that fetches a scatter-bearing executable
    (``self._get_prefill`` / ``self._get_step``) must first route
    through a copy-on-write helper: ``_resolve_cow`` (copies a pending
    shared source into the sequence's private block BEFORE its next
    chunk lands) or ``_cow_guard`` (raises if any imminent decode write
    targets a refcount>1 block — defense-in-depth; decode writes are
    provably past the shared region). Fetch-before-guard is flagged at
    the fetch site: ordering is the contract, not mere presence."""

    name = "write-to-shared-block"
    description = ("KV scatter dispatch (_get_prefill/_get_step) without "
                   "a prior _resolve_cow/_cow_guard call in the same "
                   "function — writes into refcount>1 blocks must "
                   "copy-on-write first")

    SCATTER_GETTERS = {"_get_prefill", "_get_step", "_get_spec",
                       "_get_draft_prefill"}
    COW_HELPERS = {"_resolve_cow", "_cow_guard"}

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel == "grove_tpu/serving/engine.py"

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(self._check_fn(mod, fn))
        return out

    def _check_fn(self, mod: ModuleFile, fn: ast.AST) -> list[Finding]:
        getters: list[ast.Call] = []
        first_cow: int | None = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = self.attr_chain(node.func)
            if not chain:
                continue
            if chain[-1] in self.SCATTER_GETTERS:
                getters.append(node)
            elif chain[-1] in self.COW_HELPERS:
                if first_cow is None or node.lineno < first_cow:
                    first_cow = node.lineno
        return [
            self.finding(
                mod, g,
                f"{self.attr_chain(g.func)[-1]} fetched without a prior "
                "copy-on-write gate — call self._resolve_cow(seq) or "
                "self._cow_guard(...) earlier in this function so no "
                "scatter can land in a refcount>1 shared block")
            for g in getters
            if first_cow is None or g.lineno < first_cow
        ]


class UnattributedControllerWrite(Rule):
    """PR 20's sweep-attribution contract (docs/design/
    controlplane-observatory.md): writeobs names every store write
    after the reconcile that issued it via a contextvar that
    ``Controller._process`` sets — and that ``run_concurrently``
    copies onto its pool threads. A RAW ``threading.Thread``/``Timer``
    a controller spawns gets a fresh context, so every write from it
    files as ``writer="direct"`` and the observatory's per-controller
    ledger silently under-counts. The discipline: a thread entrypoint
    in controller code that (transitively, via self-calls) issues
    write verbs must stamp itself with ``writeobs.set_writer(...)``
    first. Scope: grove_tpu/controllers/."""

    name = "unattributed-controller-write"
    description = ("store write reachable from a raw controller thread "
                   "without writeobs.set_writer — it files as "
                   "writer=\"direct\" and escapes the sweep ledger")

    THREAD_CTORS = {"Thread", "Timer"}

    def applies(self, mod: ModuleFile) -> bool:
        return mod.rel.startswith("grove_tpu/controllers/")

    def check(self, mod: ModuleFile) -> list[Finding]:
        out: list[Finding] = []
        for cls in mod.tree.body:
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(mod, cls))
        return out

    def _check_class(self, mod: ModuleFile,
                     cls: ast.ClassDef) -> list[Finding]:
        methods = {fn.name: fn for fn in cls.body
                   if isinstance(fn, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        out: list[Finding] = []
        for entry in self._thread_targets(cls, methods):
            if self._sets_writer(methods[entry]):
                continue
            # Closure over self-calls: the thread's whole call tree
            # runs in the unattributed context.
            seen, frontier = {entry}, [entry]
            while frontier:
                fn = methods[frontier.pop()]
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = self.attr_chain(node.func)
                    if chain[:1] == ["self"] and len(chain) == 2 \
                            and chain[1] in methods \
                            and chain[1] not in seen:
                        seen.add(chain[1])
                        frontier.append(chain[1])
                    elif len(chain) >= 2 and chain[-2] == "client" \
                            and chain[-1] in WRITE_VERBS:
                        out.append(self.finding(
                            mod, node,
                            f".{chain[-1]}() on a raw controller thread "
                            f"(entrypoint {cls.name}.{entry}) without "
                            "writeobs.set_writer — the write files as "
                            "writer=\"direct\"; stamp the thread "
                            "entrypoint with writeobs.set_writer(name)"))
        return out

    def _thread_targets(self, cls: ast.ClassDef,
                        methods: dict) -> list[str]:
        """Method names handed to threading.Thread(target=self.X) /
        threading.Timer(delay, self.X) anywhere in the class."""
        targets: list[str] = []
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            chain = self.attr_chain(node.func)
            if not chain or chain[-1] not in self.THREAD_CTORS:
                continue
            cands = [kw.value for kw in node.keywords
                     if kw.arg in ("target", "function")]
            if chain[-1] == "Timer" and len(node.args) >= 2:
                cands.append(node.args[1])
            for cand in cands:
                cc = self.attr_chain(cand)
                if cc[:1] == ["self"] and len(cc) == 2 \
                        and cc[1] in methods:
                    targets.append(cc[1])
        return targets

    def _sets_writer(self, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = self.attr_chain(node.func)
                if chain[-1:] == ["set_writer"]:
                    return True
        return False


ALL_RULES = [
    HubUnderStoreLock,
    LeaderClientWrite,
    JaxInTelemetry,
    RawTestSleep,
    ThreadJoinInStop,
    CloneBeforeMutate,
    HostSyncInStepLoop,
    ReqtraceInStepLoop,
    WriteToSharedBlock,
    UnattributedControllerWrite,
]
