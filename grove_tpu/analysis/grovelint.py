"""grovelint — AST enforcement of the project's earned invariants.

Each rule is one class encoding one incident this codebase already
paid for (the catalog with its history: docs/design/static-analysis.md).
The framework is deliberately small: parse each file once, hand every
rule the same ``ModuleFile``, collect ``Finding``s, apply pragma
suppression, and render human text or a machine-readable JSON report.

Pragmas (the grandfathering mechanism — every use needs a one-line
justification after ``--``):

    x = risky_thing()  # grovelint: disable=rule-name -- why it's safe
    # grovelint: disable-file=rule-name -- module-wide exemption

Exit codes are diff-friendly for CI gates: 0 = clean (or no NEW
findings vs ``--baseline``), 1 = findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Iterable

# Directories never worth parsing (generated, caches, scm internals).
SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
             "bench-history", "scale-history", "pod-logs"}

PRAGMA_RE = re.compile(
    r"#\s*grovelint:\s*(disable|disable-file)\s*=\s*([a-z0-9,\-]+)"
    r"(?:\s*--\s*(\S.*))?")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers churn with every edit, so a
        finding is 'the same one' when rule+file+message match — good
        enough for a no-NEW-findings CI gate."""
        return (self.rule, self.path, self.message)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class PragmaError(Exception):
    """A pragma that exists but is malformed (no justification)."""


class ModuleFile:
    """One parsed source file plus everything a rule needs to judge it."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # Pragma maps, parsed once from COMMENT tokens (not raw lines:
        # pragma-looking text inside a string literal — a lint-test
        # fixture, a docs snippet — must not create a real exemption).
        self.line_disables: dict[int, set[str]] = {}
        self.file_disables: set[str] = set()
        self.bare_pragmas: list[int] = []   # pragma lines missing -- why
        for i, text in self._comments(source):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            verb, rules, why = m.group(1), m.group(2), m.group(3)
            names = {r.strip() for r in rules.split(",") if r.strip()}
            if not why:
                self.bare_pragmas.append(i)
            if verb == "disable-file":
                self.file_disables |= names
            else:
                self.line_disables.setdefault(i, set()).update(names)

    @staticmethod
    def _comments(source: str) -> list[tuple[int, str]]:
        """(line, text) for every real comment token. The file already
        parsed as AST before this runs, so tokenize errors can't
        happen on content we lint — but stay defensive anyway."""
        out: list[tuple[int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.string))
        except tokenize.TokenError:
            pass
        return out

    @property
    def is_test(self) -> bool:
        return self.rel.startswith("tests/")

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_disables:
            return True
        return finding.rule in self.line_disables.get(finding.line, set())


class Rule:
    """One invariant. Subclasses set ``name``/``description`` and
    implement ``check``; ``applies`` scopes the rule to the modules
    whose contract it encodes (a rule about the store lock has no
    business parsing the model code)."""

    name = "abstract"
    description = ""

    def applies(self, mod: ModuleFile) -> bool:
        return True

    def check(self, mod: ModuleFile) -> list[Finding]:
        raise NotImplementedError

    # -- shared AST helpers ------------------------------------------------

    @staticmethod
    def attr_chain(node: ast.AST) -> list[str]:
        """``a.b.c`` -> ["a","b","c"]; [] when the base isn't a Name."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            parts.reverse()
            return parts
        return []

    def finding(self, mod: ModuleFile, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, mod.rel, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


class LintEngine:
    def __init__(self, rules: Iterable[Rule]) -> None:
        self.rules = list(rules)
        self.files_scanned = 0
        self.parse_errors: list[str] = []

    # -- file discovery ----------------------------------------------------

    def iter_files(self, paths: list[str], root: str) -> Iterable[str]:
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(root, p)
            if not os.path.exists(full):
                # A typo'd / renamed path must fail the gate loudly —
                # "0 files, 0 findings, exit 0" is how a CI lint line
                # silently dies.
                self.parse_errors.append(f"{p}: no such file or directory"
                                         f" (resolved to {full})")
                continue
            if os.path.isfile(full):
                if full.endswith(".py"):
                    yield full
                continue
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)

    # -- linting -----------------------------------------------------------

    def lint_source(self, source: str, rel: str) -> list[Finding]:
        mod = ModuleFile(rel, source)
        out: list[Finding] = []
        for rule in self.rules:
            if not rule.applies(mod):
                continue
            out.extend(f for f in rule.check(mod) if not mod.suppressed(f))
        # A pragma without a justification is itself a finding: the
        # grandfathering policy is "exemption + why", never bare.
        for line in mod.bare_pragmas:
            out.append(Finding("pragma-justification", mod.rel, line, 0,
                               "grovelint pragma without a '-- why' "
                               "justification"))
        return out

    def lint_paths(self, paths: list[str], root: str) -> list[Finding]:
        findings: list[Finding] = []
        for full in self.iter_files(paths, root):
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as fh:
                    source = fh.read()
            except OSError as e:
                self.parse_errors.append(f"{rel}: {e}")
                continue
            try:
                findings.extend(self.lint_source(source, rel))
            except SyntaxError as e:
                self.parse_errors.append(f"{rel}: syntax error: {e}")
            self.files_scanned += 1
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings

    # -- reports -----------------------------------------------------------

    def report(self, findings: list[Finding]) -> dict:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "tool": "grovelint",
            "version": 1,
            "files_scanned": self.files_scanned,
            "rules": [{"name": r.name, "description": r.description}
                      for r in self.rules],
            "counts": counts,
            "parse_errors": self.parse_errors,
            "findings": [f.to_dict() for f in findings],
        }


def default_engine() -> LintEngine:
    from grove_tpu.analysis.rules import ALL_RULES
    return LintEngine(r() for r in ALL_RULES)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


DEFAULT_PATHS = ["grove_tpu", "tests", "tools", "bench.py"]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="grovelint",
        description="AST invariant linter for the grove-tpu control "
                    "plane (docs/design/static-analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable JSON report")
    ap.add_argument("--baseline", metavar="FILE",
                    help="suppress findings present in this prior JSON "
                         "report; exit 0 unless NEW findings appear")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the JSON report to FILE (for future "
                         "--baseline gating) and exit by the usual codes")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: the "
                         "tree this package lives in)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    engine = default_engine()
    try:
        findings = engine.lint_paths(args.paths or DEFAULT_PATHS, root)
    except OSError as e:
        print(f"grovelint: {e}", file=sys.stderr)
        return 2

    new = findings
    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                base = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"grovelint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        known = {(f["rule"], f["path"], f["message"])
                 for f in base.get("findings", [])}
        new = [f for f in findings if f.key() not in known]

    report = engine.report(findings)
    report["new_findings"] = [f.to_dict() for f in new]
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in new:
            print(f)
        label = "new " if args.baseline else ""
        print(f"grovelint: {engine.files_scanned} files, "
              f"{len(new)} {label}finding(s)"
              + (f" ({len(findings)} total incl. baselined)"
                 if args.baseline else ""))
        for err in engine.parse_errors:
            print(f"grovelint: parse error: {err}", file=sys.stderr)

    if engine.parse_errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
