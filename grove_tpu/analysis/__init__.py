"""Static + dynamic enforcement of the control plane's earned invariants.

Two halves (docs/design/static-analysis.md):

- ``grovelint`` — an AST-based checker framework. Five PRs of
  concurrency-heavy machinery each shipped a hard-won rule that lived
  only in docstrings ("never touch the MetricsHub under the store
  lock", "control-plane writes go through ``leader_client``", "nothing
  on the JIT path", "test waits scale through TIME_SCALE"); grovelint
  turns each into a checker class that fails CI instead of a comment
  that rots. ``python -m grove_tpu.analysis`` / ``grovectl lint``.

- ``lockdep`` — a lock-order witness (the Linux lockdep model):
  ``GROVE_LOCKDEP=1`` wraps the store/hub/observer/defrag/standby
  locks, records the cross-thread acquisition graph, and fails on
  cycles or on blocking calls made while a witnessed lock is held.
  Run by ``tools/lockdep_smoke.py`` and as a chaos-harness invariant.
"""

# Lazy exports: the lockdep wrapper is imported by Store.__init__ on
# every construction, and pulling the whole linter in with it would tax
# a path that only wants one env check.
_LINT_EXPORTS = {"Finding", "LintEngine", "Rule", "default_engine"}


def __getattr__(name: str):
    import importlib
    if name in _LINT_EXPORTS:
        mod = importlib.import_module("grove_tpu.analysis.grovelint")
        return getattr(mod, name)
    if name in ("lockdep", "grovelint", "rules"):
        return importlib.import_module(f"grove_tpu.analysis.{name}")
    raise AttributeError(name)
