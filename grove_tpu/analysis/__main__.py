"""``python -m grove_tpu.analysis`` — the grovelint entry point."""

import sys

from grove_tpu.analysis.grovelint import main

sys.exit(main())
