"""grove-tpu: a TPU-native orchestration framework + JAX serving stack.

This package provides the capabilities of ai-dynamo/grove (a Kubernetes
operator for gang-scheduled AI inference: PodCliqueSet / PodClique /
PodCliqueScalingGroup / PodGang / ClusterTopology — see
/root/reference/README.md:9-41) re-designed TPU-first as a standalone
control plane plus the JAX workload stack that runs inside the pods it
orchestrates:

- ``grove_tpu.api``        — the typed resource API (Grove's CRDs, A1-A7)
- ``grove_tpu.store``      — versioned object store with watch semantics
                             (the etcd/apiserver analog)
- ``grove_tpu.runtime``    — controller runtime: workqueues, reconcile flow,
                             expectations, concurrency (R1-R10)
- ``grove_tpu.controllers``— domain controllers (C1-C6)
- ``grove_tpu.scheduler``  — pluggable gang-scheduler backends, slice-atomic
                             TPU placement (S1-S5)
- ``grove_tpu.topology``   — TPU fleet model: slices, hosts, ICI/DCN levels
- ``grove_tpu.admission``  — defaulting / validation / authorization (W1-W6)
- ``grove_tpu.agent``      — node agents (real subprocess pods + fake nodes)
                             and the in-pod startup barrier (I1)
- ``grove_tpu.models``     — flagship JAX models (Llama family)
- ``grove_tpu.ops``        — attention, KV cache, norms, rope
- ``grove_tpu.parallel``   — meshes, sharding rules, collectives
- ``grove_tpu.serving``    — disaggregated prefill/decode engine
"""

from grove_tpu.version import __version__  # noqa: F401
