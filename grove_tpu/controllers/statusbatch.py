"""Per-sweep status-write batching for reconcilers (ROADMAP item 5).

The PCS/PCSG/PodClique reconcilers historically ended every sweep with
a full-object ``update_status`` round trip — one write-verb call, one
store-lock acquisition, and one rv-checked PUT per sweep *even when
nothing changed* (the store suppresses the no-op, but only after the
call paid for the lock). At 4096 pods that is thousands of no-op verb
calls per settle round, and the PCS create path commits its status
twice (generation-hash seed, then aggregation).

This module converts those sweeps to ``patch_status_many`` batching:

- Each reconcile opens a :func:`sweep` (a contextvar, so helpers any
  depth down can queue without threading a parameter).
- ``commit_status`` computes a **field-diff merge patch** of the
  object's status against a pre-mutation :func:`snapshot` — only
  changed fields and changed conditions ride; an empty diff queues
  NOTHING (the no-op call disappears entirely, which the sweep
  observatory's ledger can prove: write calls per sweep drop to zero
  at convergence).
- At sweep close the queued patches flush grouped per (kind,
  namespace) through ONE ``patch_status_many`` call each — same-object
  patches are merged first (the PCS seed + aggregation writes become
  one commit), and per-item errors are swallowed exactly like the
  prior ``except GroveError: pass`` (the next event recomputes).

Merge-patch semantics are the status subresource's (store/patch.py):
no rv precondition, per-field last-write-wins, conditions merged BY
TYPE — a concurrent writer's Scheduled condition survives our
MinAvailableBreached patch, which the full-object PUT could clobber
only by losing a conflict retry.

``GROVE_STATUS_BATCH=0`` restores the exact prior path (every
``commit_status`` falls back to the full ``update_status``); the 4k
bench runs the same seed both ways and pins the batched writes/pod
strictly below the unbatched run from the observatory's own ledger
(tools/bench_reconcile.py, tests/test_sweepobs.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
from typing import Any, Iterator

from grove_tpu.api.serde import to_dict
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.logger import get_logger

STATUS_BATCH_ENV = "GROVE_STATUS_BATCH"

log = get_logger("statusbatch")

# The open sweep rides a contextvar (the writeobs writer idiom): one
# reconcile = one sweep, helpers queue from any depth, and worker
# threads never share a sweep.
_sweep_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "grove_status_sweep", default=None)


def enabled() -> bool:
    """Per-call env read (the GROVE_WRITE_OBS idiom): the bench flips
    this between the batched and the prior path on the same process."""
    return os.environ.get(STATUS_BATCH_ENV, "1") != "0"


class StatusSweep:
    """Queued status merge-patches for one reconcile sweep."""

    def __init__(self, client: Any) -> None:
        self.client = client
        # (kind_cls, namespace) -> {name: merged patch dict}
        self._groups: dict[tuple[type, str], dict[str, dict]] = {}

    def queue(self, obj: Any, patch: dict) -> None:
        group = self._groups.setdefault(
            (type(obj), obj.meta.namespace), {})
        prior = group.get(obj.meta.name)
        group[obj.meta.name] = patch if prior is None \
            else _merge_patches(prior, patch)

    def flush(self) -> None:
        """One ``patch_status_many`` per (kind, namespace) group.
        Per-item errors are logged and dropped — the prior per-write
        ``except GroveError: pass`` contract; the next event
        recomputes from live state."""
        for (kind_cls, namespace), items in self._groups.items():
            try:
                results = self.client.patch_status_many(
                    kind_cls, list(items.items()), namespace)
            except GroveError as e:
                log.debug("status batch for %s/%s dropped: %s",
                          kind_cls.KIND, namespace, e)
                continue
            for (name, _), err in zip(items.items(), results):
                if err is not None:
                    log.debug("status patch %s %s/%s dropped: %s",
                              kind_cls.KIND, namespace, name, err)
        self._groups.clear()


@contextlib.contextmanager
def sweep(client: Any) -> Iterator[StatusSweep | None]:
    """Open a status sweep for one reconcile body. With
    GROVE_STATUS_BATCH=0 this is a bare yield and every commit_status
    inside takes the prior full-object path."""
    if not enabled():
        yield None
        return
    s = StatusSweep(client)
    token = _sweep_ctx.set(s)
    try:
        yield s
    finally:
        _sweep_ctx.reset(token)
        s.flush()


def current_sweep() -> StatusSweep | None:
    return _sweep_ctx.get()


def snapshot(obj: Any) -> dict:
    """Pre-mutation status snapshot for ``commit_status`` to diff
    against (plain data, the same serde the patch machinery uses)."""
    return to_dict(obj.status)


def commit_status(client: Any, obj: Any, before: dict,
                  swallow_errors: bool = False) -> Any:
    """Persist ``obj``'s status mutations since ``before``.

    Batched (an open sweep and GROVE_STATUS_BATCH unset/1): queue a
    field-diff merge patch — nothing at all when the diff is empty.
    Otherwise: the prior full-object ``update_status``, including the
    ``swallow_errors`` contract of the status-aggregation call sites.
    Returns the object (the store's refreshed copy on the direct path,
    the local one when queued — callers keep reading their mutation
    either way)."""
    s = _sweep_ctx.get()
    if s is not None and enabled():
        patch = _status_diff(before, to_dict(obj.status))
        if patch:
            s.queue(obj, patch)
        return obj
    try:
        return client.update_status(obj)
    except GroveError:
        if not swallow_errors:
            raise
        return obj  # next event recomputes


def _status_diff(before: dict, after: dict) -> dict:
    """Merge patch carrying only what changed. Conditions diff BY TYPE
    (the store's merge key); other fields compare wholesale — status
    dataclasses are flat enough that a per-field replace is exactly
    the RFC 7386 merge the store applies."""
    patch: dict = {}
    for key, value in after.items():
        if key == "conditions":
            continue
        if before.get(key) != value:
            patch[key] = value
    before_conds = {e.get("type"): e
                    for e in before.get("conditions") or []}
    changed = [e for e in after.get("conditions") or []
               if before_conds.get(e.get("type")) != e]
    if changed:
        patch["conditions"] = changed
    return patch


def _merge_patches(prior: dict, patch: dict) -> dict:
    """Client-side pre-merge of two patches against the same object
    (the PCS generation-hash seed + aggregation pair): later fields
    win; conditions union by type with the later entry winning."""
    merged = dict(prior)
    for key, value in patch.items():
        if key == "conditions":
            by_type = {e.get("type"): e
                       for e in merged.get("conditions") or []}
            for entry in value:
                by_type[entry.get("type")] = entry
            merged["conditions"] = list(by_type.values())
        elif isinstance(value, dict) and \
                isinstance(merged.get(key), dict):
            merged[key] = {**merged[key], **value}
        else:
            merged[key] = value
    return merged
