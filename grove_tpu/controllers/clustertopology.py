"""ClusterTopology controller (C5).

Parity with reference internal/controller/clustertopology + internal/
clustertopology: for every topology-aware scheduler backend, either sync
the CT's level hierarchy into the backend (auto-managed) or drift-check
an externally-managed view; status records synced backends and drift.
``ensure_default_topology`` is the startup pre-sync
(clustertopology.go:31) — controllers start with a valid hierarchy even
before any CT is applied.
"""

from __future__ import annotations

from grove_tpu.api import ClusterTopology, new_meta
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import AlreadyExistsError, GroveError, NotFoundError
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.scheduler.framework import Registry, TopologyAware
from grove_tpu.store.client import Client

DEFAULT_CT_NAME = "default"


def ensure_default_topology(client: Client) -> ClusterTopology:
    """Create the default TPU topology CT if none exists (startup pre-sync)."""
    try:
        return client.get(ClusterTopology, DEFAULT_CT_NAME)
    except NotFoundError:
        pass
    ct = ClusterTopology(meta=new_meta(DEFAULT_CT_NAME))
    try:
        return client.create(ct)
    except AlreadyExistsError:
        return client.get(ClusterTopology, DEFAULT_CT_NAME)


class ClusterTopologyReconciler:
    def __init__(self, client: Client, scheduler_registry: Registry):
        self.client = client
        self.schedulers = scheduler_registry
        self.log = get_logger("clustertopology")

    def reconcile(self, req: Request) -> StepResult:
        try:
            ct = self.client.get(ClusterTopology, req.name, req.namespace)
        except NotFoundError:
            return StepResult.finished()
        if ct.meta.deletion_timestamp is not None:
            return StepResult.finished()

        synced: list[str] = []
        drift = False
        for backend in self.schedulers.backends():
            if not isinstance(backend, TopologyAware):
                continue
            if ct.spec.externally_managed:
                if backend.check_topology_drift(ct):
                    drift = True
                    self.log.warning(
                        "topology drift: backend %s disagrees with CT %s",
                        backend.name, ct.meta.name)
            else:
                backend.sync_topology(ct)
                synced.append(backend.name)
        ct.status.synced_backends = synced
        ct.status.drift_detected = drift
        try:
            self.client.update_status(ct)
        except GroveError:
            pass
        return StepResult.finished()
