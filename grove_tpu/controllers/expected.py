"""Expected-state computation for a PodCliqueSet.

Pure functions mapping a PCS spec to the full set of child resources
(PCLQs, PCSGs, Services, PodGangs) — the declarative core the reconcilers
diff against live state. Role parity with the reference's per-component
buildResource functions plus computeExpectedPodGangs
(podcliqueset/components/podgang/syncflow.go:147-212), with one TPU-first
simplification: because child naming is fully deterministic (namegen),
expected PodGang pod references are computed directly from the spec
instead of being re-read from live pods.
"""

from __future__ import annotations

from grove_tpu.api import constants as c
from grove_tpu.api import namegen
from grove_tpu.api.core import Service
from grove_tpu.api.meta import ObjectMeta, OwnerReference, new_meta
from grove_tpu.api.podclique import PodClique, PodCliqueSpec
from grove_tpu.api.podcliqueset import (
    PodCliqueSet,
    PodCliqueTemplate,
    ScalingGroupConfig,
    StartupType,
    effective_startup_type,
)
from grove_tpu.api.podgang import PodGang, PodGangSpec, PodGroup
from grove_tpu.api.reservation import (
    ReservationScope,
    SliceReservation,
    SliceReservationSpec,
)
from grove_tpu.api.scalinggroup import (
    PodCliqueScalingGroup,
    PodCliqueScalingGroupSpec,
)
from grove_tpu.runtime.hashutil import compute_hash


def owner_ref(obj) -> OwnerReference:
    return OwnerReference(kind=obj.KIND, name=obj.meta.name, uid=obj.meta.uid)


def _hash_neutral_template(pcs: PodCliqueSet):
    """Template copy with every scaling/behavioral knob zeroed.

    Scaling (replica counts, availability floors, autoscaler bounds) and
    lifecycle tuning (priority, termination delay) are NOT updates — a
    kubectl-scale analog must never restart the workload (k8s excludes
    .spec.replicas from the pod-template hash for the same reason).
    """
    from grove_tpu.api.serde import clone
    tmpl = clone(pcs.spec.template)
    tmpl.priority = 0
    tmpl.termination_delay_seconds = None
    for t in tmpl.cliques:
        t.replicas = 0
        t.min_available = None
        t.auto_scaling = None
    for sg in tmpl.scaling_groups:
        sg.replicas = 0
        sg.auto_scaling = None
        # Immutable at admission today, but neutralized anyway so the
        # "floors are not updates" contract holds even if that rule is
        # ever relaxed.
        sg.min_available = None
    return tmpl


def generation_hash(pcs: PodCliqueSet) -> str:
    """Hash of the pod-shaping template (rolling-update trigger; reference
    reconcilespec.go:110-123). Scaling knobs are excluded (see
    _hash_neutral_template) — only changes that alter what runs in the
    pods (or how gangs are shaped) trigger an update.
    """
    return compute_hash(_hash_neutral_template(pcs))


def structure_hash(pcs: PodCliqueSet) -> str:
    """Hash of the gang-shaping structure only (clique set, chip counts,
    scaling-group membership, topology, ordering). Pod-shaping fields
    (container, priority_class) are excluded: when ONLY those change,
    each PodClique rolls its own pods one at a time in place (reference
    podclique/components/pod/rollingupdate.go:87-227) — tearing down
    whole PCS replicas for an image tweak would destroy healthy gangs.
    Structure changes (e.g. tpu_chips_per_pod, which re-plans gangs)
    keep the replica-recreation path.
    """
    from grove_tpu.api.core import ContainerSpec
    tmpl = _hash_neutral_template(pcs)
    tmpl.priority_class = ""
    for t in tmpl.cliques:
        t.container = ContainerSpec()
        t.priority_class = ""
    return compute_hash(tmpl)


def standalone_cliques(pcs: PodCliqueSet) -> list[PodCliqueTemplate]:
    grouped = {name for sg in pcs.spec.template.scaling_groups
               for name in sg.clique_names}
    return [t for t in pcs.spec.template.cliques if t.name not in grouped]


def grouped_cliques(pcs: PodCliqueSet,
                    sg: ScalingGroupConfig) -> list[PodCliqueTemplate]:
    by_name = {t.name: t for t in pcs.spec.template.cliques}
    return [by_name[n] for n in sg.clique_names]


def min_available(t: PodCliqueTemplate) -> int:
    return t.min_available if t.min_available is not None else t.replicas


def sg_min_available(sg: ScalingGroupConfig) -> int:
    # Default matches admission defaulting: one gang-guaranteed instance,
    # remaining replicas are elastic scaled gangs.
    return sg.min_available if sg.min_available is not None else 1


def effective_starts_after(pcs: PodCliqueSet,
                           t: PodCliqueTemplate) -> list[str]:
    """Parent clique names for ``t`` under the template's startup type.

    IN_ORDER translates clique declaration order into an implicit DAG —
    each clique waits on the immediately preceding one (reference
    podcliqueset/components/podclique/podclique.go:357-364; PCSG members
    resolve against the base gang the same way, matching
    podcliquescalinggroup/components/podclique/podclique.go:415-427).
    EXPLICIT uses the declared ``starts_after`` edges; ANY_ORDER none.
    """
    st = effective_startup_type(pcs.spec.template)
    if st == StartupType.EXPLICIT:
        return list(t.starts_after)
    if st == StartupType.IN_ORDER:
        names = [q.name for q in pcs.spec.template.cliques]
        i = names.index(t.name)
        return [names[i - 1]] if i > 0 else []
    return []


def _starts_after_fqns(pcs: PodCliqueSet, replica: int,
                       parents: list[str], child: str = "",
                       pcsg_replica: int = 0) -> list[str]:
    """Map parent clique names to PCLQ FQNs within the same PCS replica.

    A parent in the SAME scaling group as the ``child`` clique resolves
    instance-locally — replica j's worker waits on replica j's leader,
    not instance 0's (each PCSG replica is one independent model
    instance; cross-instance ordering would serialize scale-out and
    wait on the wrong pods). A parent in a DIFFERENT group (or a
    standalone child's grouped parent) resolves to the parent group's
    gang-guaranteed instances [0, minAvailable) — the ones the base
    PodGang promises exist."""
    sg_of = {name: sg for sg in pcs.spec.template.scaling_groups
             for name in sg.clique_names}
    child_sg = sg_of.get(child)
    fqns: list[str] = []
    for parent in parents:
        sg = sg_of.get(parent)
        if sg is None:
            fqns.append(namegen.pclq_name(pcs.meta.name, replica, parent))
        elif child_sg is not None and sg.name == child_sg.name:
            fqns.append(namegen.pcsg_pclq_name(
                pcs.meta.name, replica, sg.name, pcsg_replica, parent))
        else:
            for j in range(sg_min_available(sg)):
                fqns.append(namegen.pcsg_pclq_name(
                    pcs.meta.name, replica, sg.name, j, parent))
    return fqns


def reservation_for(pcs: PodCliqueSet, replica: int, clique_name: str,
                    pcsg_replica: int = 0) -> str:
    """The SliceReservation name covering ``clique_name`` in PCS replica
    ``replica``, or "". PCSG-level templates take precedence for their
    members (the nearest-scope rule); first matching template wins at
    each level (validation rejects overlapping filters)."""
    sg = _sg_of_clique(pcs).get(clique_name)
    if sg is not None:
        for rt in sg.reservations:
            if rt.clique_names and clique_name not in rt.clique_names:
                continue
            if rt.scope == ReservationScope.PER_REPLICA:
                return namegen.pcsg_reservation_name(
                    pcs.meta.name, replica, sg.name, rt.name, pcsg_replica)
            return namegen.pcsg_reservation_name(
                pcs.meta.name, replica, sg.name, rt.name)
    for rt in pcs.spec.template.reservations:
        if rt.clique_names and clique_name not in rt.clique_names:
            continue
        if rt.scope == ReservationScope.PER_REPLICA:
            return namegen.reservation_name(pcs.meta.name, rt.name, replica)
        return namegen.reservation_name(pcs.meta.name, rt.name)
    return ""


def _sg_of_clique(pcs: PodCliqueSet) -> dict[str, ScalingGroupConfig]:
    return {cn: sg for sg in pcs.spec.template.scaling_groups
            for cn in sg.clique_names}


def _rt_spec(rt) -> SliceReservationSpec:
    return SliceReservationSpec(generation=rt.generation,
                                topology=rt.topology,
                                slice_count=rt.slice_count)


def expected_reservations(pcs: PodCliqueSet,
                          live_replicas: dict[str, int] | None = None
                          ) -> list[SliceReservation]:
    """SliceReservation children for PCS-level templates (AllReplicas =
    one shared object, PerReplica = one per PCS replica) and PCSG-level
    templates (AllReplicas = one per PCSG object, PerReplica = one per
    model instance, following live autoscaled replica counts — scale-in
    prunes the instance's reservation and frees its slices)."""
    live_replicas = live_replicas or {}
    out = []
    for rt in pcs.spec.template.reservations:
        if rt.scope == ReservationScope.PER_REPLICA:
            for r in range(pcs.spec.replicas):
                name = namegen.reservation_name(pcs.meta.name, rt.name, r)
                out.append(SliceReservation(
                    meta=_meta(pcs, name, _labels(pcs, r, {})),
                    spec=_rt_spec(rt)))
        else:
            name = namegen.reservation_name(pcs.meta.name, rt.name)
            out.append(SliceReservation(
                meta=_meta(pcs, name, {
                    c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                    c.LABEL_PCS_NAME: pcs.meta.name,
                }),
                spec=_rt_spec(rt)))
    for r in range(pcs.spec.replicas):
        for sg in pcs.spec.template.scaling_groups:
            if not sg.reservations:
                continue
            pcsg_name = namegen.pcsg_name(pcs.meta.name, r, sg.name)
            replicas = live_replicas.get(pcsg_name, sg.replicas)
            for rt in sg.reservations:
                extra = {c.LABEL_PCSG_NAME: pcsg_name}
                if rt.scope == ReservationScope.PER_REPLICA:
                    for j in range(replicas):
                        name = namegen.pcsg_reservation_name(
                            pcs.meta.name, r, sg.name, rt.name, j)
                        out.append(SliceReservation(
                            meta=_meta(pcs, name, _labels(pcs, r, extra)),
                            spec=_rt_spec(rt)))
                else:
                    name = namegen.pcsg_reservation_name(
                        pcs.meta.name, r, sg.name, rt.name)
                    out.append(SliceReservation(
                        meta=_meta(pcs, name, _labels(pcs, r, extra)),
                        spec=_rt_spec(rt)))
    return out


def _clique_to_spec(pcs: PodCliqueSet, replica: int, t: PodCliqueTemplate,
                    name: str, pcsg: str = "", pcsg_replica: int = 0,
                    template_hash: str = "") -> PodCliqueSpec:
    return PodCliqueSpec(
        reservation=reservation_for(pcs, replica, t.name,
                                    pcsg_replica=pcsg_replica),
        role_name=t.name,
        replicas=t.replicas,
        min_available=min_available(t),
        template=t,
        starts_after=_starts_after_fqns(pcs, replica,
                                        effective_starts_after(pcs, t),
                                        child=t.name,
                                        pcsg_replica=pcsg_replica),
        auto_scaling=t.auto_scaling,
        pcs_name=pcs.meta.name,
        pcs_replica=replica,
        pcsg_name=pcsg,
        pcsg_replica=pcsg_replica,
        pod_template_hash=template_hash,
        scheduler_name=pcs.spec.template.scheduler_name,
        priority_class=t.priority_class or pcs.spec.template.priority_class,
        subdomain=namegen.headless_service_name(pcs.meta.name, replica),
    )


def _labels(pcs: PodCliqueSet, replica: int, extra: dict[str, str]
            ) -> dict[str, str]:
    labels = {
        c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
        c.LABEL_PCS_NAME: pcs.meta.name,
        c.LABEL_PCS_REPLICA: str(replica),
    }
    labels.update(extra)
    return labels


# Component ownership labels: the PCS controller prunes only children it
# created itself; PCSG-member PCLQs belong to the PCSG controller (without
# this partition the two reconcilers would fight over membership).
COMPONENT_STANDALONE_PCLQ = "pclq"
COMPONENT_PCSG_PCLQ = "pcsg-pclq"


def expected_services(pcs: PodCliqueSet) -> list[Service]:
    if pcs.spec.template.headless_service is None:
        return []
    out = []
    for r in range(pcs.spec.replicas):
        name = namegen.headless_service_name(pcs.meta.name, r)
        out.append(Service(
            meta=_meta(pcs, name, _labels(pcs, r, {})),
            selector={c.LABEL_PCS_NAME: pcs.meta.name,
                      c.LABEL_PCS_REPLICA: str(r)},
            publish_not_ready=pcs.spec.template.headless_service
            .publish_not_ready_addresses,
        ))
    return out


def _meta(pcs: PodCliqueSet, name: str, labels: dict[str, str]) -> ObjectMeta:
    meta = new_meta(name, namespace=pcs.meta.namespace, labels=labels)
    meta.owner_references = [owner_ref(pcs)]
    # Lifecycle trace: children carry their PCS's trace id so one trace
    # follows the whole tree (runtime/trace.py). Deterministic (not
    # context-dependent): child creates may run on pool threads where
    # the reconcile span's context is not ambient.
    from grove_tpu.runtime.trace import ANNOTATION_TRACE_ID
    tid = pcs.meta.annotations.get(ANNOTATION_TRACE_ID, "")
    if tid:
        meta.annotations[ANNOTATION_TRACE_ID] = tid
    return meta


def expected_standalone_pclqs(pcs: PodCliqueSet,
                              template_hash: str) -> list[PodClique]:
    out = []
    for r in range(pcs.spec.replicas):
        for t in standalone_cliques(pcs):
            name = namegen.pclq_name(pcs.meta.name, r, t.name)
            out.append(PodClique(
                meta=_meta(pcs, name, _labels(pcs, r, {
                    c.LABEL_PCLQ_ROLE: t.name,
                    c.LABEL_COMPONENT: COMPONENT_STANDALONE_PCLQ})),
                spec=_clique_to_spec(pcs, r, t, name,
                                     template_hash=template_hash),
            ))
    return out


def expected_pcsgs(pcs: PodCliqueSet,
                   template_hash: str) -> list[PodCliqueScalingGroup]:
    out = []
    for r in range(pcs.spec.replicas):
        for sg in pcs.spec.template.scaling_groups:
            name = namegen.pcsg_name(pcs.meta.name, r, sg.name)
            out.append(PodCliqueScalingGroup(
                meta=_meta(pcs, name, _labels(pcs, r, {
                    c.LABEL_PCSG_NAME: name})),
                spec=PodCliqueScalingGroupSpec(
                    clique_names=list(sg.clique_names),
                    replicas=sg.replicas,
                    min_available=sg_min_available(sg),
                    auto_scaling=sg.auto_scaling,
                    topology=sg.topology,
                    pcs_name=pcs.meta.name,
                    pcs_replica=r,
                    pod_template_hash=template_hash,
                ),
            ))
    return out


def _pod_group(pclq_fqn: str, replicas: int, min_avail: int,
               topology=None) -> PodGroup:
    return PodGroup(
        name=pclq_fqn,
        pod_names=[namegen.pod_name(pclq_fqn, i) for i in range(replicas)],
        min_replicas=min_avail,
        topology=topology,
    )


def expected_podgangs(pcs: PodCliqueSet,
                      live_replicas: dict[str, int] | None = None
                      ) -> list[PodGang]:
    """Base gang per PCS replica + scaled gang per PCSG replica beyond
    min_available (reference syncflow.go:147-212).

    ``live_replicas`` maps child names (PCLQ FQN or PCSG name) to their
    live replica counts — auto-scaled children own their replica field, so
    gang pod references must follow the live value, not the template.
    """
    live_replicas = live_replicas or {}
    out = []
    tmpl = pcs.spec.template

    def pclq_replicas(fqn: str, t: PodCliqueTemplate) -> int:
        return live_replicas.get(fqn, t.replicas)

    for r in range(pcs.spec.replicas):
        base_name = namegen.base_podgang_name(pcs.meta.name, r)
        groups: list[PodGroup] = []
        for t in standalone_cliques(pcs):
            fqn = namegen.pclq_name(pcs.meta.name, r, t.name)
            groups.append(_pod_group(fqn, pclq_replicas(fqn, t),
                                     min_available(t), t.topology))
        for sg in tmpl.scaling_groups:
            for j in range(sg_min_available(sg)):
                for t in grouped_cliques(pcs, sg):
                    fqn = namegen.pcsg_pclq_name(
                        pcs.meta.name, r, sg.name, j, t.name)
                    groups.append(_pod_group(fqn, pclq_replicas(fqn, t),
                                             min_available(t), t.topology))
        out.append(PodGang(
            meta=_meta(pcs, base_name, _labels(pcs, r, {})),
            spec=PodGangSpec(
                groups=groups,
                topology=tmpl.topology,
                priority_class=tmpl.priority_class,
                priority=tmpl.priority,
                scheduler_name=tmpl.scheduler_name,
            ),
        ))
        # Scaled gangs: one per live PCSG replica >= minAvailable.
        for sg in tmpl.scaling_groups:
            sg_live = live_replicas.get(
                namegen.pcsg_name(pcs.meta.name, r, sg.name), sg.replicas)
            for j in range(sg_min_available(sg), sg_live):
                name = namegen.scaled_podgang_name(pcs.meta.name, r,
                                                   sg.name, j)
                groups = []
                for t in grouped_cliques(pcs, sg):
                    fqn = namegen.pcsg_pclq_name(pcs.meta.name, r, sg.name,
                                                 j, t.name)
                    groups.append(_pod_group(fqn, pclq_replicas(fqn, t),
                                             min_available(t), t.topology))
                out.append(PodGang(
                    meta=_meta(pcs, name, _labels(pcs, r, {
                        c.LABEL_PCSG_NAME: namegen.pcsg_name(
                            pcs.meta.name, r, sg.name)})),
                    spec=PodGangSpec(
                        groups=groups,
                        topology=sg.topology or tmpl.topology,
                        priority_class=tmpl.priority_class,
                        priority=tmpl.priority,
                        scheduler_name=tmpl.scheduler_name,
                        base_gang=base_name,
                    ),
                ))
    return out


def podgang_name_for_pclq(spec: PodCliqueSpec,
                          pcsg_min_available: int | None = None) -> str:
    """Which gang a PCLQ's pods belong to (deterministic).

    Standalone cliques and PCSG replicas below min_available ride the
    base gang; PCSG replicas at/after min_available get scaled gangs
    (reference syncflow.go:161-212).
    """
    if not spec.pcsg_name:
        return namegen.base_podgang_name(spec.pcs_name, spec.pcs_replica)
    assert pcsg_min_available is not None, "PCSG-owned PCLQ needs min_available"
    if spec.pcsg_replica < pcsg_min_available:
        return namegen.base_podgang_name(spec.pcs_name, spec.pcs_replica)
    sg_short = spec.pcsg_name[len(f"{spec.pcs_name}-{spec.pcs_replica}-"):]
    return namegen.scaled_podgang_name(spec.pcs_name, spec.pcs_replica,
                                       sg_short, spec.pcsg_replica)
