"""Node lifecycle: heartbeat-driven failure detection for agent-managed
hosts.

The reference's failure story starts at pod conditions (kubelet/node
controller mark pods, Grove rolls breaches up to gang termination —
SURVEY.md §5). With remote agents heartbeating over HTTP
(agent/remote.py), this controller is the node-lifecycle-controller
analog that closes the loop for host loss:

- a non-fake node whose ``status.heartbeat_time`` goes stale past
  ``grace_seconds`` is marked NotReady (schedulers already skip
  not-ready nodes, scheduler/backends.py) and a Warning event records
  why;
- its Pending/Running pods are marked Failed ("node lost"), which flips
  PodClique readiness, breaches MinAvailable, and hands recovery to the
  standard machinery: pod self-heal onto live nodes, then gang
  termination + recreate if the breach persists past TerminationDelay.

Nodes that have never heartbeated (``heartbeat_time == 0``) are exempt:
in-process fleets publish status at creation and have no agent to beat.
Recovery is owned by the agent — its next heartbeat sets ready=True.

This controller also SURFACES spot-slice reclamation notices
(``ANNOTATION_RECLAIM_AT`` — the GKE spot termination-notice analog,
stamped by the cloud integration or the chaos injector): a noticed node
is cordoned (``spec.unschedulable``) the moment the notice appears so
nothing new lands on dying capacity, with a Warning event naming the
withdrawal instant. The coordinated response — checkpoint barrier,
pinned reland on surviving capacity — is the reclaim controller's job
(grove_tpu/disruption/reclaim.py, docs/design/disruption-contract.md).
"""

from __future__ import annotations

import threading
import time

from grove_tpu.api import Node, Pod, constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.api.meta import Condition, set_condition
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.events import EventRecorder
from grove_tpu.runtime.logger import get_logger


class NodeLifecycleController:
    def __init__(self, client, grace_seconds: float = 15.0,
                 sync_period: float = 1.0, namespace: str | None = None):
        self.client = client
        self.grace_seconds = grace_seconds
        self.sync_period = sync_period
        self.namespace = namespace
        self.log = get_logger("node-lifecycle")
        self.recorder = EventRecorder(client, "node-lifecycle")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._known_nodes: set[tuple[str, str]] | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="node-lifecycle", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)

    def pause(self) -> None:
        """Leadership parking (grove_tpu/ha): a demoted replica must
        not fail nodes or evict pods — its writes would be fenced, and
        the noise would race the real leader's lifecycle decisions."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _run(self) -> None:
        # Raw thread = fresh contextvar context: without this stamp
        # every write below files as writer="direct" in writeobs and
        # escapes the sweep ledger (lint: unattributed-controller-write).
        from grove_tpu.store import writeobs
        token = writeobs.set_writer("node-lifecycle")
        try:
            while not self._stop.is_set():
                if getattr(self, "_paused", False):
                    self._stop.wait(self.sync_period)
                    continue
                try:
                    self._pass()
                except Exception:  # noqa: BLE001 - controller survival
                    self.log.exception("node lifecycle pass panicked")
                self._stop.wait(self.sync_period)
        finally:
            writeobs.reset_writer(token)

    def _pass(self) -> None:
        now = time.time()
        nodes = self.client.list(Node, self.namespace)
        for node in nodes:
            if node.meta.annotations.get(c.ANNOTATION_RECLAIM_AT) \
                    and not node.spec.unschedulable:
                self._cordon_reclaimed(node)
        for node in nodes:
            if node.spec.fake or node.status.heartbeat_time <= 0:
                continue
            stale = now - node.status.heartbeat_time > self.grace_seconds
            if stale and node.status.ready:
                self._mark_lost(node, now)
        known = {(n.meta.namespace, n.meta.name) for n in nodes}
        # Sweep for orphans only when the node set SHRANK (or on the
        # first pass after start — deletions may predate us): a steady
        # fleet must not pay an O(pods) list every second.
        if self._known_nodes is None or not known >= self._known_nodes:
            self._fail_orphans_of_deleted_nodes(known)
        self._known_nodes = known

    def _fail_orphans_of_deleted_nodes(
            self, known: set[tuple[str, str]]) -> None:
        """A pod whose node OBJECT is gone (fleet shrink, operator
        delete) can never run or report again — fail it so self-heal
        reschedules (kube's node controller evicts pods of deleted
        nodes the same way). Applies to fake nodes too: node-object
        deletion is unambiguous, unlike a missed heartbeat."""
        for pod in self.client.list(Pod, self.namespace):
            if not pod.status.node_name \
                    or pod.status.phase not in (PodPhase.PENDING,
                                                PodPhase.RUNNING):
                continue
            if (pod.meta.namespace, pod.status.node_name) in known:
                continue
            try:
                # Node re-check closes the register-then-bind race: a
                # node created after our node list (and a pod bound to
                # it) is alive, not orphaned.
                try:
                    self.client.get(Node, pod.status.node_name,
                                    pod.meta.namespace)
                    continue
                except NotFoundError:
                    pass
                live = self.client.get(Pod, pod.meta.name,
                                       pod.meta.namespace)
                if live.meta.uid != pod.meta.uid \
                        or live.status.node_name != pod.status.node_name:
                    continue
                live.status.phase = PodPhase.FAILED
                live.status.message = \
                    f"node {pod.status.node_name} deleted"
                live.status.conditions = set_condition(
                    live.status.conditions,
                    Condition(type=c.COND_READY, status="False",
                              reason="NodeDeleted"))
                self.client.update_status(live)
                self.log.warning("pod %s/%s: node %s deleted; failing "
                                 "for self-heal", pod.meta.namespace,
                                 pod.meta.name, pod.status.node_name)
            except (NotFoundError, GroveError):
                continue

    def _cordon_reclaimed(self, node: Node) -> None:
        """Spot-reclamation notice surfaced: cordon the node so no new
        placement lands on capacity that is about to vanish (listed
        objects are shared — re-get before mutating)."""
        try:
            live = self.client.get(Node, node.meta.name,
                                   node.meta.namespace)
            stamp = live.meta.annotations.get(c.ANNOTATION_RECLAIM_AT)
            if not stamp or live.spec.unschedulable:
                return  # raced the injector's heal or another pass
            live.spec.unschedulable = True
            self.client.update(live)
        except (NotFoundError, GroveError):
            return  # next pass re-evaluates
        try:
            left = float(stamp) - time.time()
            when = f"in {left:.1f}s" if left > 0 else "imminently"
        except ValueError:
            when = f"at {stamp!r}"
        self.log.warning("node %s: spot reclamation noticed (withdraws "
                         "%s); cordoned", node.meta.name, when)
        self.recorder.event(node, "Warning", "SpotReclaimNoticed",
                            f"spot reclamation notice: capacity "
                            f"withdraws {when}; cordoned — the reclaim "
                            "controller evacuates its gangs")

    def _mark_lost(self, node: Node, now: float) -> None:
        age = now - node.status.heartbeat_time
        try:
            live = self.client.get(Node, node.meta.name, node.meta.namespace)
            if not live.status.ready or \
                    live.status.heartbeat_time != node.status.heartbeat_time:
                return  # raced a heartbeat or another pass
            live.status.ready = False
            live.status.message = (f"heartbeat stale for {age:.1f}s "
                                   f"(grace {self.grace_seconds:.0f}s)")
            self.client.update_status(live)
        except (NotFoundError, GroveError):
            return  # next pass re-evaluates
        self.log.warning("node %s lost: heartbeat stale %.1fs",
                         node.meta.name, age)
        self.recorder.event(node, "Warning", "NodeLost",
                            f"heartbeat stale for {age:.1f}s; failing its "
                            "pods")
        self._fail_pods(node)

    def _fail_pods(self, node: Node) -> None:
        for pod in self.client.list(Pod, None):
            if pod.status.node_name != node.meta.name:
                continue
            if pod.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
                continue
            try:
                live = self.client.get(Pod, pod.meta.name,
                                       pod.meta.namespace)
                if live.meta.uid != pod.meta.uid:
                    continue
                live.status.phase = PodPhase.FAILED
                live.status.message = f"node {node.meta.name} lost"
                live.status.conditions = set_condition(
                    live.status.conditions,
                    Condition(type=c.COND_READY, status="False",
                              reason="NodeLost"))
                self.client.update_status(live)
            except (NotFoundError, GroveError):
                continue  # pod vanished or raced; self-heal handles it
