"""Controller registration — wire reconcilers, watches, and workers.

Parity with reference internal/controller/register.go:34-67 (five
controllers) + cmd/main.go bootstrap order: scheduler registry first,
then controllers with their watch mappings, then backend placement loops
and agents as manager runnables.
"""

from __future__ import annotations

from grove_tpu.api import SliceReservation, constants as c
from grove_tpu.controllers.podclique import PodCliqueReconciler
from grove_tpu.controllers.podcliqueset import PodCliqueSetReconciler
from grove_tpu.controllers.podgang import PodGangReconciler
from grove_tpu.controllers.scalinggroup import ScalingGroupReconciler
from grove_tpu.runtime.controller import (
    Controller,
    Request,
    owner_requests,
    self_requests,
)
from grove_tpu.runtime.manager import Manager
from grove_tpu.scheduler.framework import Registry
from grove_tpu.scheduler.registry import build_registry
from grove_tpu.store.store import Event


def _label_requests(label: str):
    """Map an event to the object named by one of its labels."""
    def mapper(event: Event) -> list[Request]:
        name = event.obj.meta.labels.get(label)
        return [Request(event.obj.meta.namespace, name)] if name else []
    return mapper


def register_controllers(mgr: Manager) -> Registry:
    cfg = mgr.config
    # Schedulers keep a direct client: their read path is the
    # placement snapshot (PR 1), which shares the same per-version
    # clones the informer caches do. It is the manager's LEADER client
    # (not mgr.client) so promotion stamps the scheduler's binds with
    # the fencing epoch — a deposed replica's in-flight bind must be
    # rejected, while node agents on mgr.client stay unfenced.
    registry = build_registry(cfg, mgr.leader_client)
    # Controllers and their event mappers read through the shared
    # informer caches: list-shaped reads become indexed lookups over
    # shared objects instead of per-call store scans. Writes (and point
    # gets) stay on the direct path. GROVE_INFORMER=0 restores direct
    # lists without rewiring anything.
    client = mgr.cached_client

    pcs = PodCliqueSetReconciler(client)
    pcs_ctrl = Controller("podcliqueset", client, pcs.reconcile,
                          workers=cfg.concurrency.podcliqueset,
                          backoff_base=cfg.requeue_base_seconds,
                          backoff_max=cfg.requeue_max_seconds)
    pcs_ctrl.watches(["PodCliqueSet"], self_requests)
    pcs_ctrl.watches(["PodClique", "PodCliqueScalingGroup", "PodGang",
                      "Service"], _label_requests(c.LABEL_PCS_NAME))
    mgr.add_controller(pcs_ctrl)

    pclq = PodCliqueReconciler(
        client, registry,
        disruption_deadline_s=cfg.disruption.default_deadline_seconds,
        barriers_enabled=cfg.disruption.enabled)
    pclq_ctrl = Controller("podclique", client, pclq.reconcile,
                           workers=cfg.concurrency.podclique,
                           backoff_base=cfg.requeue_base_seconds,
                           backoff_max=cfg.requeue_max_seconds)
    pclq_ctrl.watches(["PodClique"], self_requests)
    pclq_ctrl.watches(["Pod"], _label_requests(c.LABEL_PCLQ_NAME))

    def gang_to_pclqs(event: Event) -> list[Request]:
        """PodGang status flips (Initialized/base Scheduled) unblock gate
        removal in its PCS's cliques."""
        ns = event.obj.meta.namespace
        pcs_name = event.obj.meta.labels.get(c.LABEL_PCS_NAME)
        if not pcs_name:
            return []
        from grove_tpu.api import PodClique
        return [Request(ns, q.meta.name) for q in client.list(
            PodClique, ns, selector={c.LABEL_PCS_NAME: pcs_name})]

    pclq_ctrl.watches(["PodGang"], gang_to_pclqs)
    # Demotion hygiene (grove_tpu/ha): parking the controller clears
    # its ExpectationsStore — expectations are IOUs against THIS
    # replica's watch feed, and stale ones surviving a leadership gap
    # are exactly the SURVEY §7 duplicate-pod hazard.
    pclq_ctrl.on_park = pclq.expectations.clear
    mgr.add_controller(pclq_ctrl)

    pcsg = ScalingGroupReconciler(client)
    pcsg_ctrl = Controller("podcliquescalinggroup", client, pcsg.reconcile,
                           workers=cfg.concurrency.podcliquescalinggroup,
                           backoff_base=cfg.requeue_base_seconds,
                           backoff_max=cfg.requeue_max_seconds)
    pcsg_ctrl.watches(["PodCliqueScalingGroup"], self_requests)
    pcsg_ctrl.watches(["PodClique"], _label_requests(c.LABEL_PCSG_NAME))
    mgr.add_controller(pcsg_ctrl)

    gang = PodGangReconciler(client, registry)
    gang_ctrl = Controller("podgang", client, gang.reconcile,
                           workers=cfg.concurrency.podgang,
                           backoff_base=cfg.requeue_base_seconds,
                           backoff_max=cfg.requeue_max_seconds)
    gang_ctrl.watches(["PodGang"], self_requests)
    mgr.add_controller(gang_ctrl)

    from grove_tpu.controllers.reservation import SliceReservationReconciler
    rsv = SliceReservationReconciler(client)
    rsv_ctrl = Controller("slicereservation", client, rsv.reconcile,
                          workers=1,
                          backoff_base=cfg.requeue_base_seconds,
                          backoff_max=cfg.requeue_max_seconds)
    rsv_ctrl.watches(["SliceReservation"], self_requests)

    # Only structural node changes (join/loss/readiness/labels) concern
    # reservations; heartbeat-only status updates arrive every few
    # seconds per node and would otherwise fan into full-cluster scans.
    node_shape: dict[str, tuple] = {}

    def node_to_reservations(event: Event) -> list[Request]:
        from grove_tpu.controllers.reservation import SWEEP_REQUEST
        node = event.obj
        ns = node.meta.namespace
        shape = (tuple(sorted(node.meta.labels.items())),
                 node.status.ready, node.spec.unschedulable)
        key = f"{ns}/{node.meta.name}"
        if event.type.value == "DELETED":
            node_shape.pop(key, None)
        else:
            if node_shape.get(key) == shape:
                return []                      # heartbeat-only churn
            node_shape[key] = shape
        reqs = [Request(ns, r.meta.name) for r in client.list(
            SliceReservation, ns)]
        if reqs:
            return reqs
        # No live reservations: sweep ONLY if this node carries a
        # reservation label (a crash-lost delete event left an orphan
        # fencing it). An unlabeled node joining a reservation-free
        # namespace needs nothing — at fleet-creation scale (1000
        # nodes) unconditional sweeps were a measurable startup tax.
        if node.meta.labels.get(c.LABEL_RESERVATION):
            return [Request(ns, SWEEP_REQUEST)]
        return []

    rsv_ctrl.watches(["Node"], node_to_reservations)

    def gang_to_holds(event: Event) -> list[Request]:
        """A deleted PodGang's defrag/roll holds must release promptly
        (the reconciler GCs holds whose gang is gone) — waiting out the
        30s resync would leave a fenced slice and trip the chaos
        defrag-holds invariant."""
        if event.type.value != "DELETED":
            return []
        ns = event.obj.meta.namespace
        return [Request(ns, r.meta.name) for r in client.list(
            SliceReservation, ns,
            selector={c.LABEL_HOLD_FOR_GANG: event.obj.meta.name})]

    rsv_ctrl.watches(["PodGang"], gang_to_holds)
    mgr.add_controller(rsv_ctrl)

    if cfg.topology_aware_scheduling.enabled:
        from grove_tpu.controllers.clustertopology import (
            ClusterTopologyReconciler,
            ensure_default_topology,
        )
        ensure_default_topology(mgr.client)  # startup pre-sync
        ct = ClusterTopologyReconciler(client, registry)
        ct_ctrl = Controller("clustertopology", client, ct.reconcile,
                             workers=cfg.concurrency.clustertopology,
                             backoff_base=cfg.requeue_base_seconds,
                             backoff_max=cfg.requeue_max_seconds)
        ct_ctrl.watches(["ClusterTopology"], self_requests)
        mgr.add_controller(ct_ctrl)

    for backend in registry.backends():
        runnable = backend.runnable()
        if runnable is not None:
            mgr.add_runnable(runnable)
    return registry
