"""PodClique controller — reconciles a PCLQ to its Pods (C2).

Parity with reference internal/controller/podclique + components/pod:
expectation-gated diff sync, stable index assignment (hole reuse),
scheduling gates removed only once the pod's PodGang exists (and, for
scaled gangs, the base gang is Scheduled — syncflow.go:254-427), env-var
injection, deletion-sorted scale-in, and status with
MinAvailableBreached / PodCliqueScheduled conditions.

TPU-first: env injection includes the JAX multi-host bootstrap contract —
TPU_WORKER_ID is the stable pod index (survives pod replacement via index
reuse), TPU_WORKER_HOSTNAMES is the deterministic list of clique pod
hostnames.
"""

from __future__ import annotations

from grove_tpu.api import (
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodGang,
    constants as c,
    namegen,
)
from grove_tpu.api.core import PodPhase, PodSpec, StartupBarrier
from grove_tpu.api.meta import (
    Condition,
    OwnerReference,
    is_condition_true,
    new_meta,
    set_condition,
)
from grove_tpu.api.serde import clone
from grove_tpu.controllers import statusbatch
from grove_tpu.controllers.expected import podgang_name_for_pclq
from grove_tpu.runtime.concurrent import run_with_slow_start
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.expectations import ExpectationsStore
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.indextracker import available_indices
from grove_tpu.runtime.logger import get_logger
from grove_tpu.scheduler.framework import Registry
from grove_tpu.store.client import Client


class PodCliqueReconciler:
    CRASH_BACKOFF_BASE = 0.2
    CRASH_BACKOFF_MAX = 30.0
    CRASH_RESET_AFTER = 60.0

    def __init__(self, client: Client, scheduler_registry: Registry,
                 disruption_deadline_s: float | None = None,
                 barriers_enabled: bool = True):
        self.client = client
        self.schedulers = scheduler_registry
        # Checkpoint-barrier wiring for the roll path (the operator's
        # disruption config, threaded by register.py; dataclass default
        # when constructed bare in tests). barriers_enabled mirrors
        # disruption.enabled: with the coordinator runnable off,
        # posting notices would stall responder-registered gangs to
        # expiry on every roll — config-off means contract-off here.
        if disruption_deadline_s is None:
            from grove_tpu.api.config import DisruptionConfig
            disruption_deadline_s = \
                DisruptionConfig().default_deadline_seconds
        self._disruption_deadline_s = disruption_deadline_s
        self._barriers_enabled = barriers_enabled
        # Named store => grove_expectations_pending{controller="podclique"}
        # gauge; TTL expiry (a watch event was lost — the double-create
        # hazard's precursor, SURVEY.md §7) surfaces as a Warning event
        # on the clique instead of staying invisible until the chaos
        # checker trips on its consequences.
        self.expectations = ExpectationsStore(
            controller="podclique", on_expired=self._expectation_expired)
        from grove_tpu.runtime.events import EventRecorder
        self.recorder = EventRecorder(client, "podclique")
        self.log = get_logger("podclique")
        # (namespace, pod name) -> (consecutive failures, not-before
        # timestamp): the CrashLoopBackOff analog — an instantly-failing
        # workload must not respawn at full agent tick rate.
        self._crash_backoff: dict[tuple[str, str], tuple[int, float]] = {}

    def _expectation_expired(self, key: str, creates: int,
                             deletes: int) -> None:
        """TTL-expired expectation: ``creates``/``deletes`` UIDs were
        never observed — a lost watch event or an event lag beyond the
        TTL. Warn on the clique so the leak is attributable before its
        consequences (duplicate/over-deleted pods) surface."""
        ns, _, name = key.partition("/")
        self.log.warning("%s: expectation expired unobserved "
                         "(creates=%d deletes=%d)", key, creates, deletes)
        try:
            pclq = self.client.get(PodClique, name, ns)
        except (NotFoundError, GroveError):
            return  # clique gone: nothing to attach the warning to
        self.recorder.event(
            pclq, "Warning", "ExpectationExpired",
            f"sync expectation expired with {creates} create(s) and "
            f"{deletes} delete(s) unobserved; a watch event was lost or "
            "lagged past the TTL — the next sync recomputes from live "
            "state")

    def reconcile(self, req: Request) -> StepResult:
        # One status sweep per reconcile: both _update_status calls
        # below (expectation-gated refresh and end-of-sync aggregation)
        # queue field-diff patches that flush as one patch_status_many
        # batch (GROVE_STATUS_BATCH=0 restores per-call update_status).
        with statusbatch.sweep(self.client):
            return self._reconcile(req)

    def _reconcile(self, req: Request) -> StepResult:
        try:
            pclq = self.client.get(PodClique, req.name, req.namespace)
        except NotFoundError:
            self.expectations.forget(req.key)
            return StepResult.finished()
        if pclq.meta.deletion_timestamp is not None:
            return StepResult.finished()  # cascade removes pods

        pods = self.client.list(Pod, req.namespace,
                                selector={c.LABEL_PCLQ_NAME: pclq.meta.name})
        pods = [p for p in pods if p.meta.deletion_timestamp is None]

        if not self.expectations.satisfied(req.key):
            # Writes from the previous sync are not all visible yet; only
            # status may be refreshed (reference syncflow.go:170).
            self._update_status(pclq, pods)
            return StepResult.requeue(0.05)

        gang_name = self._gang_name(pclq)
        result = self._sync_pods(pclq, pods, gang_name, req)
        if result is not None:
            return result
        self._remove_gates_if_unblocked(pclq, pods, gang_name)
        self._update_status(pclq, pods)
        # Pod-level rolling AFTER gate removal: replacement pods must be
        # able to schedule (and go Ready) or the roll would deadlock
        # waiting on a pod whose gate nothing lifts.
        result = self._rolling_pods_pass(pclq, pods, req)
        if result is not None:
            return result
        return StepResult.finished()

    # ---- pod diff sync ----

    def _sync_pods(self, pclq: PodClique, pods: list[Pod], gang_name: str,
                   req: Request) -> StepResult | None:
        import time as _time
        # Pod-level self-healing: Failed pods are deleted so their index
        # is recreated (the kubelet-restart analog). Gang termination only
        # fires when this self-heal cannot keep MinAvailable satisfied.
        failed = [p for p in pods if p.status.phase == PodPhase.FAILED]
        if failed:
            now = _time.time()
            for p in failed:
                bk = (p.meta.namespace, p.meta.name)
                n, _ = self._crash_backoff.get(bk, (0, 0.0))
                delay = min(self.CRASH_BACKOFF_BASE * (2 ** n),
                            self.CRASH_BACKOFF_MAX)
                self._crash_backoff[bk] = (n + 1, now + delay)
            err = self._delete_pods_observed(req, failed)
            if err is not None:
                return err
            return StepResult.requeue(0.05)
        want = pclq.spec.replicas
        if len(pods) < want:
            now = _time.time()
            used = []
            for p in pods:
                try:
                    used.append(namegen.pod_index_from_name(p.meta.name))
                except ValueError:
                    pass
            indices = available_indices(used, want - len(pods))
            # CrashLoopBackOff: hold back indices whose pod keeps failing.
            ready_keys = {(p.meta.namespace, p.meta.name) for p in pods
                          if is_condition_true(p.status.conditions,
                                               c.COND_READY)}
            for bk in list(self._crash_backoff):
                n, not_before = self._crash_backoff[bk]
                if bk in ready_keys or now - not_before > self.CRASH_RESET_AFTER:
                    del self._crash_backoff[bk]
            held = []
            allowed = []
            for i in indices:
                bk = (pclq.meta.namespace, namegen.pod_name(pclq.meta.name, i))
                entry = self._crash_backoff.get(bk)
                if entry is not None and entry[1] > now:
                    held.append(entry[1] - now)
                else:
                    allowed.append(i)
            indices = allowed
            if not indices and held:
                return StepResult.requeue(min(held))
            new_pods = [self._build_pod(pclq, i, gang_name) for i in indices]
            self.expectations.expect_creates(
                req.key, [p.meta.uid for p in new_pods])
            created, errors = run_with_slow_start(
                [lambda p=p: self._create_observed(req.key, p)
                 for p in new_pods])
            if errors:
                # Unrealised expectations for failed creates must be
                # forgotten or the next syncs would stall until TTL.
                self.expectations.forget(req.key)
                return StepResult.fail(errors[0])
            if held:
                # Some indices are in crash backoff: revisit when the
                # soonest backoff expires (no store event will fire).
                return StepResult.requeue(min(held))
        elif len(pods) > want:
            doomed = sorted(pods, key=_deletion_order)[:len(pods) - want]
            err = self._delete_pods_observed(req, doomed)
            if err is not None:
                return err
        return None

    def _delete_pods_observed(self, req: Request,
                              doomed: list[Pod]) -> StepResult | None:
        """Expectation-tracked pod deletion (shared by self-heal, scale-in
        and rolling update). Returns a failure StepResult or None."""
        self.expectations.expect_deletes(
            req.key, [p.meta.uid for p in doomed])
        for p in doomed:
            try:
                self.client.delete(Pod, p.meta.name, p.meta.namespace)
                self.expectations.observe_delete(req.key, p.meta.uid)
            except NotFoundError:
                self.expectations.observe_delete(req.key, p.meta.uid)
            except GroveError as e:
                self.expectations.forget(req.key)
                return StepResult.fail(e)
        return None

    # ---- pod-level rolling update (reference rollingupdate.go:87-227) ----

    def _rolling_pods_pass(self, pclq: PodClique, pods: list[Pod],
                           req: Request) -> StepResult | None:
        """Replace pods whose template hash is stale, one ready pod at a
        time (oldest first), holding the min_available floor.

        Non-ready stale pods are deleted immediately (they serve nothing);
        a ready stale pod is only taken down when every new-hash pod is
        Ready again and ready >= min_available — so a template edit rolls
        through the clique without ever collapsing the gang.
        """
        target = pclq.spec.pod_template_hash
        if not target:
            return None
        if len(pods) != pclq.spec.replicas:
            # Mid-scale (e.g. a replacement was just created and is not in
            # this pass's listing): deleting another pod now could pierce
            # the floor. Wait for the counts to settle.
            return None
        stale = [p for p in pods
                 if p.meta.labels.get(c.LABEL_POD_TEMPLATE_HASH) != target]
        if not stale:
            # Roll complete for this clique: release the roll-safe slot
            # hold once the gang is whole again (cache-read cheap; a
            # sibling clique still rolling re-takes its own hold), and
            # clear the gang's rolling-update disruption notice with it.
            self._release_roll_hold(pclq, pods)
            self._clear_roll_notice(pclq)
            return None
        # PCS-sequenced rollout: only the currently selected replica rolls
        # (one replica at a time across the set, like the reference's
        # replica-ordered update; the per-pod floor below handles within-
        # replica safety). Poll while waiting — the turn handoff is a PCS
        # status write, which raises no event for this PCLQ.
        if pclq.spec.pcs_name:
            try:
                from grove_tpu.api import PodCliqueSet
                from grove_tpu.api.podcliqueset import UpdateStrategyType
                pcs = self.client.get(PodCliqueSet, pclq.spec.pcs_name,
                                      pclq.meta.namespace)
                if pcs.spec.update_strategy.type == \
                        UpdateStrategyType.ON_DELETE:
                    return None  # user deletes pods; no orchestration
                ru = pcs.status.rolling_update
                if ru is not None and ru.current_replica != pclq.spec.pcs_replica:
                    return StepResult.requeue(0.2)
            except NotFoundError:
                pass

        # Roll-safe slot hold (grove_tpu/defrag; the PR 8 wedge fix at
        # the root): before a deletion frees any bound pod's chips,
        # fence the gang's slice with a SliceReservation so another
        # gang's pending pods cannot land in the slot mid-roll — the
        # replacement relands in place instead of wedging forever as a
        # StragglerUnplaced whose required pack nothing can satisfy.
        if any(p.status.node_name for p in stale):
            hold_wait = self._ensure_roll_hold(pclq)
            if hold_wait is not None:
                return hold_wait

        def ready(p: Pod) -> bool:
            return is_condition_true(p.status.conditions, c.COND_READY)

        stale_not_ready = [p for p in stale if not ready(p)]
        if stale_not_ready:
            err = self._delete_pods_observed(req, stale_not_ready)
            if err is not None:
                return err
            return StepResult.requeue(0.05)

        # The previous replacement must be fully back (all new-hash pods
        # Ready) before the next ready pod is taken down.
        fresh = [p for p in pods if p not in stale]
        if any(not ready(p) for p in fresh):
            return StepResult.requeue(0.1)
        ready_count = sum(1 for p in pods if ready(p))
        if ready_count < pclq.spec.min_available:
            return StepResult.requeue(0.2)

        # The disruption contract: taking down a READY pod is a planned
        # eviction, so it waits behind the gang's checkpoint barrier
        # (one protocol shared with defrag migrations and spot reclaim,
        # grove_tpu/disruption). GROVE_DISRUPTION=0 restores the
        # pre-contract immediate deletion exactly.
        barrier_wait = self._roll_barrier(pclq)
        if barrier_wait is not None:
            return barrier_wait

        victim = min(stale, key=lambda p: p.meta.creation_timestamp or 0.0)
        self.log.info("%s: rolling pod %s -> hash %s (%d stale left)",
                      pclq.meta.name, victim.meta.name, target, len(stale))
        err = self._delete_pods_observed(req, [victim])
        if err is not None:
            return err
        return StepResult.requeue(0.05)

    # ---- roll-safe slot holds (grove_tpu/defrag; ISSUE 9) ---------------

    ROLL_HOLD_TTL_SECONDS = 120.0   # pre-TIME_SCALE backstop

    def _roll_hold_gang(self, pclq: PodClique):
        """The gang a roll hold would protect, or None when holds don't
        apply: defrag disabled, reservation-fenced cliques (their slices
        are already exclusive), gangs without an effective required pack
        (preferred packs relax instead of wedging), or gangs not yet
        placed (no slot to protect). A required pack at EITHER level
        counts — the scheduler hard-packs group-level constraints too
        (plan_gang_grouped), so those rolls wedge exactly the same way."""
        from grove_tpu.defrag import defrag_enabled
        if not defrag_enabled() or pclq.spec.reservation:
            return None
        gang = self._gang_shared(self._gang_name(pclq), pclq.meta.namespace)
        if gang is None or not gang.status.assigned_slice:
            return None
        topo = gang.spec.topology
        required = (topo.required and bool(topo.pack_level)) \
            if topo is not None else True   # scheduler default: slice
        required = required or any(
            grp.topology is not None and grp.topology.pack_level
            and grp.topology.required for grp in gang.spec.groups)
        return gang if required else None

    def _ensure_roll_hold(self, pclq: PodClique) -> StepResult | None:
        """Take (or wait for) the gang's roll hold. Returns a requeue
        while the fence is not yet up — deleting a bound pod before the
        hold is BOUND reopens the wedge window — or None to proceed."""
        from grove_tpu.api import SliceReservation
        from grove_tpu.api.reservation import (
            ReservationPhase,
            SliceReservationSpec,
        )
        from grove_tpu.defrag import roll_hold_name, set_reservation_ref
        from grove_tpu.runtime.timescale import scaled
        gang = self._roll_hold_gang(pclq)
        if gang is None:
            return None
        name = roll_hold_name(gang.meta.name)
        ns = pclq.meta.namespace
        try:
            rsv = self.client.get(SliceReservation, name, ns)
        except NotFoundError:
            try:
                self.client.create(SliceReservation(
                    meta=new_meta(name, namespace=ns, labels={
                        c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                        c.LABEL_HOLD_FOR_GANG: gang.meta.name,
                    }),
                    spec=SliceReservationSpec(
                        slices=[gang.status.assigned_slice],
                        ttl_seconds=scaled(self.ROLL_HOLD_TTL_SECONDS))))
            except GroveError as e:
                # A racing sibling clique created it, or a transient
                # store error: requeue and re-read either way.
                self.log.debug("roll hold %s create raced: %s", name, e)
            return StepResult.requeue(0.05)
        # CAS from unset (or already ours): the gang pointing at a
        # DIFFERENT reservation means a defrag migration is in flight —
        # never steal its pointer, wait for the executor to resolve
        # (rolling a mid-migration gang would fight its reland anyway).
        if not set_reservation_ref(self.client, gang.meta.name, ns, name,
                                   expect=("", name)):
            return StepResult.requeue(0.2)
        if rsv.status.phase != ReservationPhase.BOUND:
            return StepResult.requeue(0.05)
        return None

    def _roll_barrier(self, pclq: PodClique) -> StepResult | None:
        """Post the gang's rolling-update DisruptionNotice and wait for
        ack/deadline before a ready victim goes down. Returns a requeue
        while the barrier is pending, None to proceed (the verdict —
        acked|expired — is stamped onto the notice at that moment).
        Each ready victim re-arms the barrier (the workload's state
        moved between victims, so it re-checkpoints per eviction) —
        but a PENDING barrier is only READ on re-entry, never
        re-posted: polling through post_notice would CAS a coalesce
        write onto the gang every 0.1s requeue."""
        from grove_tpu.disruption import REASON_ROLLING, barrier_state, \
            disruption_enabled, note_evicted, notice_of, request_barrier
        if not self._barriers_enabled or not disruption_enabled():
            return None     # pre-contract: delete immediately
        gang = self._gang_shared(self._gang_name(pclq),
                                 pclq.meta.namespace)
        if gang is None or not gang.status.assigned_slice:
            return None     # nothing placed: deletion disrupts nothing
        notice = notice_of(gang)
        if notice is not None and not notice.evicted_at:
            state = barrier_state(notice)   # read-only poll path
        else:
            state, notice = request_barrier(
                self.client, gang.meta.name, pclq.meta.namespace,
                REASON_ROLLING, self._disruption_deadline_s)
            if state == "retry":
                # The notice write lost every CAS round: not a license
                # to delete — try again shortly.
                return StepResult.requeue(0.1)
            if state in ("disabled", "gone"):
                return None
        if state == "pending":
            return StepResult.requeue(0.1)
        if notice is not None and not notice.evicted_at:
            # First victim under this notice: freeze the verdict
            # (repeat calls are id-CAS'd no-ops).
            note_evicted(self.client, gang.meta.name,
                         pclq.meta.namespace, notice.id)
        return None

    def _clear_roll_notice(self, pclq: PodClique) -> None:
        """Drop the gang's rolling-update notice once the WHOLE gang is
        back on nodes (the roll hold's wholeness rule: per-gang notice,
        cliques roll one at a time). Only rolling-update notices are
        touched — a defrag or reclaim barrier on the same gang belongs
        to its own executor."""
        from grove_tpu.disruption import REASON_ROLLING, clear_notice
        from grove_tpu.disruption.contract import notice_of
        gang = self._gang_shared(self._gang_name(pclq),
                                 pclq.meta.namespace)
        if gang is None:
            return
        notice = notice_of(gang)
        if notice is None or notice.reason != REASON_ROLLING:
            return
        expected = [pn for grp in gang.spec.groups for pn in grp.pod_names]
        gang_pods = {p.meta.name: p for p in self.client.list(
            Pod, pclq.meta.namespace,
            selector={c.LABEL_PODGANG_NAME: gang.meta.name})
            if p.meta.deletion_timestamp is None}
        if not expected or any(pn not in gang_pods
                               or not gang_pods[pn].status.node_name
                               for pn in expected):
            return                        # a sibling clique still rolls
        clear_notice(self.client, gang.meta.name, pclq.meta.namespace,
                     notice.id)

    def _release_roll_hold(self, pclq: PodClique, pods: list[Pod]) -> None:
        """Drop the gang's roll hold once the WHOLE gang is back on
        nodes — the hold is per-gang while cliques roll one at a time,
        so releasing on this clique's pods alone would unfence a sibling
        clique's still-relanding replacement (the exact wedge window).
        Only roll holds are released here — a defrag migration hold on
        the same gang belongs to its executor."""
        from grove_tpu.api import SliceReservation
        from grove_tpu.defrag import defrag_enabled, roll_hold_name, \
            set_reservation_ref
        if not defrag_enabled():
            return
        gang = self._gang_shared(self._gang_name(pclq), pclq.meta.namespace)
        if gang is None:
            return
        name = roll_hold_name(gang.meta.name)
        if gang.meta.annotations.get(c.ANNOTATION_RESERVATION_REF) != name:
            return
        if any(not p.status.node_name for p in pods):
            return                        # our replacement still relanding
        expected = [pn for grp in gang.spec.groups for pn in grp.pod_names]
        gang_pods = {p.meta.name: p for p in self.client.list(
            Pod, pclq.meta.namespace,
            selector={c.LABEL_PODGANG_NAME: gang.meta.name})
            if p.meta.deletion_timestamp is None}
        if not expected or any(pn not in gang_pods
                               or not gang_pods[pn].status.node_name
                               for pn in expected):
            return                        # a sibling clique still rolls
        if not set_reservation_ref(self.client, gang.meta.name,
                                   pclq.meta.namespace, "",
                                   expect=(name,)):
            return                        # retried on the next reconcile
        try:
            self.client.delete(SliceReservation, name, pclq.meta.namespace)
        except (NotFoundError, GroveError):
            pass

    def _create_observed(self, key: str, pod: Pod) -> None:
        try:
            self.client.create(pod)
        except GroveError:
            self.expectations.observe_create(key, pod.meta.uid)
            raise
        self.expectations.observe_create(key, pod.meta.uid)

    def _gang_name(self, pclq: PodClique) -> str:
        if not pclq.spec.pcsg_name:
            return podgang_name_for_pclq(pclq.spec)
        try:
            pcsg = self.client.get(PodCliqueScalingGroup, pclq.spec.pcsg_name,
                                   pclq.meta.namespace)
            return podgang_name_for_pclq(pclq.spec, pcsg.spec.min_available)
        except NotFoundError:
            # PCSG not visible yet; assume base gang (re-synced on event).
            return namegen.base_podgang_name(pclq.spec.pcs_name,
                                             pclq.spec.pcs_replica)

    # ---- pod construction (reference components/pod/pod.go:138-201) ----

    def _build_pod(self, pclq: PodClique, index: int, gang_name: str) -> Pod:
        spec = pclq.spec
        name = namegen.pod_name(pclq.meta.name, index)
        container = clone(spec.template.container)
        pod = Pod(
            meta=new_meta(name, namespace=pclq.meta.namespace, labels={
                c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                c.LABEL_PCS_NAME: spec.pcs_name,
                c.LABEL_PCS_REPLICA: str(spec.pcs_replica),
                c.LABEL_PCLQ_NAME: pclq.meta.name,
                c.LABEL_PCLQ_ROLE: spec.role_name,
                c.LABEL_POD_INDEX: str(index),
                c.LABEL_POD_TEMPLATE_HASH: spec.pod_template_hash,
                **({c.LABEL_PCSG_NAME: spec.pcsg_name,
                    c.LABEL_PCSG_REPLICA: str(spec.pcsg_replica)}
                   if spec.pcsg_name else {}),
            }),
            spec=PodSpec(
                container=container,
                tpu_chips=spec.template.tpu_chips_per_pod,
                scheduling_gates=[c.GATE_PODGANG_PENDING],
                hostname=name,
                subdomain=spec.subdomain,
                priority_class=spec.priority_class,
                # Reserved cliques may ONLY land on their reservation's
                # slices; placement treats the label as exclusive, so
                # this selector is both grant and fence.
                node_selector=({c.LABEL_RESERVATION: spec.reservation}
                               if spec.reservation else {}),
            ),
        )
        pod.meta.owner_references = [OwnerReference(
            kind=PodClique.KIND, name=pclq.meta.name, uid=pclq.meta.uid)]
        # Trace propagation: the pod joins its PCLQ's lifecycle trace
        # (which carries the root PCS's id) — also correct for
        # self-healed replacements, whose startup belongs to the same
        # story. Explicit because creates fan out through the shared
        # task pool, where the reconcile span's context is not ambient.
        from grove_tpu.runtime.trace import ANNOTATION_TRACE_ID
        tid = pclq.meta.annotations.get(ANNOTATION_TRACE_ID, "")
        if tid:
            pod.meta.annotations[ANNOTATION_TRACE_ID] = tid
        self._add_env(pod, pclq, index)
        if spec.starts_after:
            pod.spec.startup_barrier = StartupBarrier(
                parent_cliques=list(spec.starts_after),
                min_available=self._parent_min_available(pclq),
            )
        backend = self.schedulers.get(spec.scheduler_name or None)
        backend.prepare_pod(pod, gang_name)
        return pod

    def _parent_min_available(self, pclq: PodClique) -> dict[str, int]:
        """Pin thresholds for parents that already exist; parents not yet
        visible are resolved live by the barrier (agent/barrier.py)."""
        out = {}
        for fqn in pclq.spec.starts_after:
            try:
                parent = self.client.get(PodClique, fqn, pclq.meta.namespace)
                out[fqn] = parent.spec.min_available
            except NotFoundError:
                pass
        return out

    def _add_env(self, pod: Pod, pclq: PodClique, index: int) -> None:
        """Reference components/pod/pod.go:330-375 env contract + the TPU
        bootstrap set (the MNNVL/ComputeDomain analog is: nothing — ICI
        comes free with slice membership; SURVEY.md §2.8)."""
        spec = pclq.spec
        env = pod.spec.container.env
        env[c.ENV_PCS_NAME] = spec.pcs_name
        env[c.ENV_PCS_INDEX] = str(spec.pcs_replica)
        env[c.ENV_PCLQ_NAME] = pclq.meta.name
        env[c.ENV_PCLQ_POD_INDEX] = str(index)
        env[c.ENV_HEADLESS_SERVICE] = spec.subdomain
        if spec.pcsg_name:
            env[c.ENV_PCSG_NAME] = spec.pcsg_name
            env[c.ENV_PCSG_INDEX] = str(spec.pcsg_replica)
            env[c.ENV_PCSG_TEMPLATE_NUM_PODS] = str(
                spec.template.replicas)
        # TPU multi-host process-group contract
        hostnames = ",".join(
            namegen.pod_name(pclq.meta.name, i)
            for i in range(spec.replicas))
        env[c.ENV_TPU_WORKER_ID] = str(index)
        env[c.ENV_TPU_WORKER_HOSTNAMES] = hostnames
        env[c.ENV_MEGASLICE_INDEX] = str(spec.pcs_replica)
        if spec.reservation:
            env[c.ENV_RESERVATION] = spec.reservation

    # ---- gate removal (reference syncflow.go:254-427) ----

    def _gang_shared(self, name: str, namespace: str) -> PodGang | None:
        """Read-only gang lookup through the shared informer cache when
        the client carries one (gate checks only inspect conditions —
        no reason to pay a clone per reconcile); direct get otherwise."""
        lister = getattr(self.client, "lister", None)
        if lister is not None:
            lst = lister(PodGang)
            if lst is not None:
                return lst.get(name, namespace)
        try:
            return self.client.get(PodGang, name, namespace)
        except NotFoundError:
            return None

    def _remove_gates_if_unblocked(self, pclq: PodClique, pods: list[Pod],
                                   gang_name: str) -> None:
        gated = [p for p in pods if c.GATE_PODGANG_PENDING in
                 p.spec.scheduling_gates]
        if not gated:
            return
        gang = self._gang_shared(gang_name, pclq.meta.namespace)
        if gang is None:
            return  # gang not created yet: stay gated
        if not is_condition_true(gang.status.conditions, c.COND_INITIALIZED):
            return  # not all gang pods exist yet
        if gang.spec.base_gang:
            # scaled gang: wait for the base gang to be placed first so
            # scaled capacity can never starve the base gang
            base = self._gang_shared(gang.spec.base_gang,
                                     pclq.meta.namespace)
            if base is None:
                return
            if not is_condition_true(base.status.conditions, c.COND_SCHEDULED):
                return
        for pod in gated:
            # Listed objects are shared informer-cache state: clone
            # before editing (the list_snapshot contract).
            ungated = clone(pod)
            ungated.spec.scheduling_gates = [
                g for g in ungated.spec.scheduling_gates
                if g != c.GATE_PODGANG_PENDING]
            try:
                self.client.update(ungated)
            except GroveError:
                pass  # retried on next event

    # ---- status (reference reconcilestatus.go:210-282) ----

    def _update_status(self, pclq: PodClique, pods: list[Pod]) -> None:
        before = statusbatch.snapshot(pclq)
        ready = sum(1 for p in pods
                    if is_condition_true(p.status.conditions, c.COND_READY))
        scheduled = sum(1 for p in pods if p.status.node_name)
        gated = sum(1 for p in pods if p.spec.scheduling_gates)
        updated = sum(1 for p in pods
                      if p.meta.labels.get(c.LABEL_POD_TEMPLATE_HASH)
                      == pclq.spec.pod_template_hash)
        pclq.status.replicas = len(pods)
        pclq.status.ready_replicas = ready
        pclq.status.scheduled_replicas = scheduled
        pclq.status.gated_replicas = gated
        pclq.status.updated_replicas = updated
        pclq.status.observed_generation = pclq.meta.generation
        # A breach only counts once the clique has been scheduled: during
        # initial placement "not ready yet" is startup, not failure
        # (reference reconcilestatus.go:210-272 gates on PodCliqueScheduled).
        # PodCliqueScheduled is sticky — losing pods after placement is a
        # breach, not a return to "awaiting placement".
        was_scheduled = scheduled >= pclq.spec.min_available or \
            is_condition_true(pclq.status.conditions, c.COND_PCLQ_SCHEDULED)
        breached = was_scheduled and ready < pclq.spec.min_available
        pclq.status.conditions = set_condition(
            pclq.status.conditions, Condition(
                type=c.COND_MIN_AVAILABLE_BREACHED,
                status="True" if breached else "False",
                reason=f"ready={ready} minAvailable={pclq.spec.min_available}"))
        pclq.status.conditions = set_condition(
            pclq.status.conditions, Condition(
                type=c.COND_PCLQ_SCHEDULED,
                status="True" if was_scheduled else "False",
                reason=f"scheduled={scheduled}"))
        statusbatch.commit_status(self.client, pclq, before,
                                  swallow_errors=True)


def _deletion_order(pod: Pod) -> tuple:
    """Scale-in preference: gated first, then unscheduled, then not-ready,
    then highest index (reference deletion-sort)."""
    ready = is_condition_true(pod.status.conditions, c.COND_READY)
    try:
        idx = namegen.pod_index_from_name(pod.meta.name)
    except ValueError:
        idx = 0
    return (
        0 if pod.spec.scheduling_gates else 1,
        0 if not pod.status.node_name else 1,
        0 if not ready else 1,
        -idx,
    )
