"""PodCliqueScalingGroup controller (C3).

Parity with reference internal/controller/podcliquescalinggroup: fans a
PCSG out to member PCLQs per PCSG replica (names
<pcs>-<i>-<pcsg>-<j>-<clique>), injects GROVE_PCSG_* context, supports
scale-in by pruning replica PCLQs, and rolls member readiness up to
ScheduledReplicas / ReadyReplicas / MinAvailableBreached.
"""

from __future__ import annotations

from grove_tpu.api import (
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    constants as c,
    namegen,
)
from grove_tpu.api.meta import Condition, OwnerReference, set_condition
from grove_tpu.api.serde import clone as serde_clone
from grove_tpu.controllers import expected as exp
from grove_tpu.controllers import statusbatch
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client


class ScalingGroupReconciler:
    def __init__(self, client: Client):
        self.client = client
        self.log = get_logger("podcliquescalinggroup")

    def reconcile(self, req: Request) -> StepResult:
        # One status sweep per reconcile (see statusbatch): the roll-up
        # below queues a field-diff patch, flushed via patch_status_many.
        with statusbatch.sweep(self.client):
            return self._reconcile(req)

    def _reconcile(self, req: Request) -> StepResult:
        try:
            pcsg = self.client.get(PodCliqueScalingGroup, req.name,
                                   req.namespace)
        except NotFoundError:
            return StepResult.finished()
        if pcsg.meta.deletion_timestamp is not None:
            return StepResult.finished()
        try:
            pcs = self.client.get(PodCliqueSet, pcsg.spec.pcs_name,
                                  req.namespace)
        except NotFoundError:
            return StepResult.requeue(0.2)  # parent not visible yet

        errors = self._sync_member_pclqs(pcsg, pcs)
        self._update_status(pcsg)
        if errors:
            return StepResult.fail(errors[0])
        return StepResult.finished()

    def _member_name(self, pcsg: PodCliqueScalingGroup, replica: int,
                     clique: str) -> str:
        sg_short = pcsg.meta.name[
            len(f"{pcsg.spec.pcs_name}-{pcsg.spec.pcs_replica}-"):]
        return namegen.pcsg_pclq_name(pcsg.spec.pcs_name,
                                      pcsg.spec.pcs_replica, sg_short,
                                      replica, clique)

    def _sync_member_pclqs(self, pcsg: PodCliqueScalingGroup,
                           pcs: PodCliqueSet) -> list[Exception]:
        errors: list[Exception] = []
        by_name = {t.name: t for t in pcs.spec.template.cliques}
        live = {q.meta.name: q for q in self.client.list(
            PodClique, pcsg.meta.namespace,
            selector={c.LABEL_PCSG_NAME: pcsg.meta.name})}
        expected_names = set()
        for j in range(pcsg.spec.replicas):
            for clique in pcsg.spec.clique_names:
                t = by_name.get(clique)
                if t is None:
                    errors.append(GroveError(
                        f"clique {clique!r} referenced by {pcsg.meta.name} "
                        "not in PCS template", operation="SyncPCLQ"))
                    continue
                name = self._member_name(pcsg, j, clique)
                expected_names.add(name)
                spec = exp._clique_to_spec(
                    pcs, pcsg.spec.pcs_replica, t, name,
                    pcsg=pcsg.meta.name, pcsg_replica=j,
                    template_hash=pcsg.spec.pod_template_hash)
                cur = live.get(name)
                if cur is not None and spec.auto_scaling is not None:
                    spec.replicas = cur.spec.replicas  # autoscaler-owned
                try:
                    if cur is None:
                        pclq = PodClique(
                            meta=exp._meta(pcs, name, exp._labels(
                                pcs, pcsg.spec.pcs_replica, {
                                    c.LABEL_PCLQ_ROLE: clique,
                                    c.LABEL_PCSG_NAME: pcsg.meta.name,
                                    c.LABEL_PCSG_REPLICA: str(j),
                                    c.LABEL_COMPONENT: exp.COMPONENT_PCSG_PCLQ,
                                })),
                            spec=spec)
                        # owned by the PCSG (cascade + watch mapping)
                        pclq.meta.owner_references = [OwnerReference(
                            kind=PodCliqueScalingGroup.KIND,
                            name=pcsg.meta.name, uid=pcsg.meta.uid)]
                        # _meta stamped the PCS's trace id; a PCSG
                        # created outside a PCS still passes its own
                        # trace down to the member it fans out.
                        from grove_tpu.runtime.trace import \
                            ANNOTATION_TRACE_ID
                        tid = pcsg.meta.annotations.get(
                            ANNOTATION_TRACE_ID, "")
                        if tid:
                            pclq.meta.annotations.setdefault(
                                ANNOTATION_TRACE_ID, tid)
                        self.client.create(pclq)
                    # Dataclass equality: same drift decision as the
                    # to_dict round-trip at a fraction of the per-sync
                    # cost (see podcliqueset._sync_children).
                    elif cur.spec != spec:
                        # cur is shared informer-cache state: clone
                        # before grafting the expected spec onto it.
                        fresh = serde_clone(cur)
                        fresh.spec = spec
                        self.client.update(fresh)
                except GroveError as e:
                    errors.append(e)
        # prune scale-in leftovers
        for name, cur in live.items():
            if name not in expected_names and cur.meta.deletion_timestamp is None:
                try:
                    self.client.delete(PodClique, name, pcsg.meta.namespace)
                except GroveError as e:
                    errors.append(e)
        return errors

    def _update_status(self, pcsg: PodCliqueScalingGroup) -> None:
        before = statusbatch.snapshot(pcsg)
        members = self.client.list(
            PodClique, pcsg.meta.namespace,
            selector={c.LABEL_PCSG_NAME: pcsg.meta.name})
        ready_replicas = 0
        scheduled_replicas = 0
        for j in range(pcsg.spec.replicas):
            mine = [q for q in members
                    if q.meta.labels.get(c.LABEL_PCSG_REPLICA) == str(j)]
            if len(mine) == len(pcsg.spec.clique_names) and all(
                    q.status.ready_replicas >= q.spec.min_available
                    for q in mine):
                ready_replicas += 1
            if len(mine) == len(pcsg.spec.clique_names) and all(
                    q.status.scheduled_replicas >= q.spec.min_available
                    for q in mine):
                scheduled_replicas += 1
        pcsg.status.replicas = pcsg.spec.replicas
        pcsg.status.ready_replicas = ready_replicas
        pcsg.status.scheduled_replicas = scheduled_replicas
        pcsg.status.observed_generation = pcsg.meta.generation
        breached = ready_replicas < pcsg.spec.min_available
        pcsg.status.conditions = set_condition(
            pcsg.status.conditions, Condition(
                type=c.COND_MIN_AVAILABLE_BREACHED,
                status="True" if breached else "False",
                reason=(f"readyReplicas={ready_replicas} "
                        f"minAvailable={pcsg.spec.min_available}")))
        statusbatch.commit_status(self.client, pcsg, before,
                                  swallow_errors=True)
