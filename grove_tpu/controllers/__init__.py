from grove_tpu.controllers.register import register_controllers

__all__ = ["register_controllers"]
