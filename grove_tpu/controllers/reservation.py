"""SliceReservation controller — bind reservations to concrete slices.

The binding half of hierarchical slice sharing (api/reservation.py; the
reference's resourceclaim machinery creates DRA claims and lets the DRA
driver allocate — here the controller IS the allocator):

- **Bind**: pick ``slice_count`` free slices whose nodes match the
  requested generation/topology, label every node in them with
  ``LABEL_RESERVATION = <reservation name>``, and record them in
  ``status.bound_slices``. A slice is free when none of its nodes carry
  a reservation label and no pods are bound to it (reserving under a
  running workload would strand it — placement treats the label as
  exclusive).
- **Heal**: a bound slice whose nodes vanished (host loss, fleet
  shrink) is replaced by a fresh free slice; surviving bindings are
  kept (pods already placed there keep their home).
- **Sweep**: nodes labeled for a reservation that no longer exists (or
  no longer claims their slice) are unlabeled — covers PCS deletion
  pruning the reservation objects and heal-time rebinding alike.

Deleting a reservation therefore returns its slices to the general pool
on the next sweep, the ResourceClaim GC analog (owner refs + deletion in
the reference, proposal 390 "Owner References and Garbage Collection").
"""

from __future__ import annotations

import collections
import time

from grove_tpu.api import Node, Pod, PodGang, SliceReservation, constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.api.reservation import ReservationPhase
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.events import EventRecorder
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client

# Sentinel request name: "sweep labels only" (no such reservation can
# exist — validation requires DNS-label names).
SWEEP_REQUEST = "~sweep"


class SliceReservationReconciler:
    def __init__(self, client: Client):
        self.client = client
        self.log = get_logger("reservation")
        self.recorder = EventRecorder(client, "reservation-controller")
        self._last_sweep = 0.0

    # ---- reconcile one reservation --------------------------------------

    # The per-reservation resync cadence: bindings and orphan labels are
    # re-checked even when no event fires (the sweep's durability story —
    # a crash that loses a delete event must not strand labels forever).
    RESYNC_SECONDS = 30.0

    def reconcile(self, req: Request) -> StepResult:
        if req.name == SWEEP_REQUEST:
            # Label-hygiene sentinel (node events with no live
            # reservations): nothing to bind, just sweep.
            if not self._sweep_orphan_labels(req.namespace):
                return StepResult.requeue(2.0)
            return StepResult.finished()
        try:
            rsv = self.client.get(SliceReservation, req.name, req.namespace)
        except NotFoundError:
            # Deleted: its labels are cleaned by the sweep (watch on the
            # reservation delete event routes here too).
            if not self._sweep_orphan_labels(req.namespace):
                return StepResult.requeue(2.0)
            return StepResult.finished()
        if rsv.meta.deletion_timestamp is not None:
            return StepResult.finished()

        # Hold GC: a defrag/roll hold whose protected gang is gone has
        # nothing left to fence for — delete it so the slice returns to
        # the pool (the TTL is the backstop; this is the prompt path,
        # fed by the PodGang-delete watch mapping in register.py).
        holder_gang = rsv.meta.labels.get(c.LABEL_HOLD_FOR_GANG)
        if holder_gang:
            try:
                self.client.get(PodGang, holder_gang, req.namespace)
            except NotFoundError:
                return self._expire(rsv, "HoldOrphaned",
                                    f"protected gang {holder_gang} is gone")

        # TTL expiry: an abandoned hold must not strand capacity
        # (proposal 0001's mandatory-TTL mitigation). Deleting the
        # object returns its slices via the sweep below / next event.
        ttl_left = None
        if rsv.spec.ttl_seconds > 0:
            ttl_left = (rsv.meta.creation_timestamp + rsv.spec.ttl_seconds
                        - time.time())
            if ttl_left <= 0:
                return self._expire(
                    rsv, "ReservationExpired",
                    f"ttl {rsv.spec.ttl_seconds:.0f}s elapsed unreleased")

        nodes = self.client.list(Node, req.namespace)
        by_slice = _nodes_by_slice(nodes)

        if rsv.spec.slices:
            bound, lost, missing = self._bind_explicit(rsv, by_slice)
        else:
            # Drop bindings whose slice no longer exists (heal path).
            bound = [s for s in rsv.status.bound_slices if s in by_slice]
            lost = [s for s in rsv.status.bound_slices if s not in by_slice]

            missing = rsv.spec.slice_count - len(bound)
            if missing > 0:
                free = self._free_slices(rsv, by_slice, exclude=set(bound))
                take = free[:missing]
                bound.extend(take)
                missing -= len(take)

        try:
            self._apply_labels(rsv, by_slice, set(bound))
        except GroveError as e:
            return StepResult.fail(e)

        phase = (ReservationPhase.BOUND if missing <= 0
                 else ReservationPhase.PENDING)
        if missing <= 0:
            msg = ""
        elif rsv.spec.slices:
            msg = (f"waiting for {missing} pinned slice(s) of "
                   f"{rsv.spec.slices}: fenced by another reservation, "
                   f"nodes missing/not-ready, or fewer than "
                   f"{rsv.spec.chips} chips free")
        else:
            msg = (f"waiting for {missing} free "
                   f"{rsv.spec.generation or 'any'}/"
                   f"{rsv.spec.topology or 'any'} slice(s)")
        changed = (sorted(bound) != sorted(rsv.status.bound_slices)
                   or phase != rsv.status.phase
                   or msg != rsv.status.message)
        if changed:
            if lost:
                self.recorder.event(rsv, "Warning", "SliceLost",
                                    f"bound slice(s) {lost} vanished; "
                                    "rebinding")
            rsv.status.bound_slices = sorted(bound)
            rsv.status.phase = phase
            rsv.status.message = msg
            try:
                self.client.update_status(rsv)
            except GroveError as e:
                return StepResult.fail(e)
            self.log.info("reservation %s: %s (%s)", rsv.meta.name,
                          phase.value, rsv.status.bound_slices)
        # Rate-limited hygiene: at most one full-namespace sweep per
        # resync period across ALL reservations (per-reconcile sweeping
        # would be O(reservations x nodes) for redundant scans).
        # Monotonic: a wall-clock step backwards must not suppress it.
        if time.monotonic() - self._last_sweep > self.RESYNC_SECONDS:
            self._last_sweep = time.monotonic()
            self._sweep_orphan_labels(req.namespace)
        delay = 2.0 if missing > 0 else self.RESYNC_SECONDS
        if ttl_left is not None:
            # Wake at the TTL deadline, not a poll after it: a stranded
            # hold fences real capacity for exactly as long as we sleep.
            delay = max(0.05, min(delay, ttl_left))
        return StepResult.requeue(delay)

    # ---- helpers --------------------------------------------------------

    def _expire(self, rsv: SliceReservation, reason: str,
                detail: str) -> StepResult:
        """Delete a reservation whose hold lapsed (TTL) or whose gang
        vanished; its node labels return via the sweep. A hold's gang
        also loses its reuse-reservation-ref pointer — a dangling ref
        would leave the gang defrag-ineligible forever (the planner
        skips annotated gangs) and lie on every read surface."""
        self.recorder.event(rsv, "Warning", reason,
                            f"releasing {rsv.meta.name}: {detail}")
        holder = rsv.meta.labels.get(c.LABEL_HOLD_FOR_GANG)
        if holder:
            # CAS clear: only while the gang still points at THIS hold
            # (a fresh replacement hold must not lose its pointer).
            from grove_tpu.defrag import set_reservation_ref
            set_reservation_ref(self.client, holder, rsv.meta.namespace,
                                "", expect=(rsv.meta.name,))
        try:
            self.client.delete(SliceReservation, rsv.meta.name,
                               rsv.meta.namespace)
        except (NotFoundError, GroveError):
            pass
        if not self._sweep_orphan_labels(rsv.meta.namespace):
            return StepResult.requeue(2.0)
        return StepResult.finished()

    def _bind_explicit(self, rsv: SliceReservation,
                       by_slice: dict[str, list[Node]]
                       ) -> tuple[list[str], list[str], int]:
        """Bind the explicitly pinned ``spec.slices`` (defrag targets and
        roll-safe holds): occupancy does NOT block — the fence gates new
        placement only, existing pods keep running — but a slice fenced
        by ANOTHER reservation, missing its nodes, or (for defrag
        targets) short of ``spec.chips`` free stays unbound. Already-
        bound slices are never re-gated on chips: the consumer landing
        on its reserved capacity must not unbind its own hold."""
        used: dict[str, int] = collections.defaultdict(int)
        if rsv.spec.chips > 0:
            for p in self.client.list(Pod, rsv.meta.namespace):
                if p.status.node_name and p.status.phase in (
                        PodPhase.PENDING, PodPhase.RUNNING):
                    used[p.status.node_name] += p.spec.tpu_chips
        already = set(rsv.status.bound_slices)
        bound: list[str] = []
        lost: list[str] = []
        for slice_name in rsv.spec.slices:
            nodes = by_slice.get(slice_name)
            if not nodes:
                lost.append(slice_name)
                continue
            if slice_name in already:
                bound.append(slice_name)    # keep: heal semantics
                continue
            if any((n.meta.labels.get(c.LABEL_RESERVATION) or rsv.meta.name)
                   != rsv.meta.name for n in nodes):
                continue                    # fenced by another reservation
            if not all(n.status.ready for n in nodes):
                continue                    # never bind flapping capacity
            if rsv.spec.chips > 0:
                free = sum(n.status.allocatable_chips - used[n.meta.name]
                           for n in nodes)
                if free < rsv.spec.chips:
                    continue                # headroom eaten since the plan
            bound.append(slice_name)
        missing = len(rsv.spec.slices) - len(bound)
        return bound, lost, missing

    def _free_slices(self, rsv: SliceReservation,
                     by_slice: dict[str, list[Node]],
                     exclude: set[str]) -> list[str]:
        """Free, shape-matching slices — no reservation label on any
        node, no pods bound to any node. Sorted for determinism."""
        occupied_hosts = {
            p.status.node_name
            for p in self.client.list(Pod, rsv.meta.namespace)
            if p.status.node_name
            and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)}
        out = []
        for slice_name, nodes in sorted(by_slice.items()):
            if slice_name in exclude:
                continue
            if not all(n.status.ready for n in nodes):
                continue  # never bind onto flapping capacity
            if rsv.spec.generation and any(
                    n.meta.labels.get(c.NODE_LABEL_TPU_ACCELERATOR)
                    != f"tpu-{rsv.spec.generation}" for n in nodes):
                continue
            if rsv.spec.topology and any(
                    n.meta.labels.get(c.NODE_LABEL_TPU_TOPOLOGY)
                    != rsv.spec.topology for n in nodes):
                continue
            if any(n.meta.labels.get(c.LABEL_RESERVATION) for n in nodes):
                continue
            if any(n.meta.name in occupied_hosts for n in nodes):
                continue
            out.append(slice_name)
        return out

    def _apply_labels(self, rsv: SliceReservation,
                      by_slice: dict[str, list[Node]],
                      bound: set[str]) -> None:
        """Converge node labels: bound slices carry this reservation's
        mark; slices this reservation no longer claims lose it."""
        for slice_name, nodes in by_slice.items():
            want = rsv.meta.name if slice_name in bound else None
            for node in nodes:
                have = node.meta.labels.get(c.LABEL_RESERVATION)
                if want is not None and have != want:
                    self.client.patch(Node, node.meta.name, {
                        "metadata": {"labels": {c.LABEL_RESERVATION: want}}},
                        namespace=node.meta.namespace)
                elif want is None and have == rsv.meta.name:
                    self.client.patch(Node, node.meta.name, {
                        "metadata": {"labels": {c.LABEL_RESERVATION: None}}},
                        namespace=node.meta.namespace)

    def _sweep_orphan_labels(self, namespace: str) -> bool:
        """Unlabel nodes whose reservation is gone or disowns their
        slice (deletion GC + heal cleanup). Returns False when any patch
        failed — a label left behind fences the node out of ALL
        placement, so callers must retry."""
        ok = True
        live: dict[str, set[str]] = {}
        for rsv in self.client.list(SliceReservation, namespace):
            live[rsv.meta.name] = set(rsv.status.bound_slices)
        for node in self.client.list(Node, namespace):
            holder = node.meta.labels.get(c.LABEL_RESERVATION)
            if not holder:
                continue
            slice_name = node.meta.labels.get(c.NODE_LABEL_SLICE, "")
            if slice_name not in live.get(holder, set()):
                try:
                    self.client.patch(Node, node.meta.name, {
                        "metadata": {"labels": {c.LABEL_RESERVATION: None}}},
                        namespace=node.meta.namespace)
                except GroveError:
                    ok = False  # caller requeues
        return ok


def _nodes_by_slice(nodes: list[Node]) -> dict[str, list[Node]]:
    """ALL nodes by slice, ready or not: a binding survives a heartbeat
    flap (NotReady nodes still exist — dropping the binding would unlabel
    the slice and let general pods squat it in the recovery window); only
    node DELETION counts as slice loss. Readiness gates NEW bindings
    (_free_slices), not existing ones."""
    out: dict[str, list[Node]] = collections.defaultdict(list)
    for n in nodes:
        slice_name = n.meta.labels.get(c.NODE_LABEL_SLICE)
        if slice_name:
            out[slice_name].append(n)
    return dict(out)
