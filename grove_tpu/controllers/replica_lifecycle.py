"""PCS-replica lifecycle: gang termination and rolling updates (C1d).

Parity with reference podcliqueset/components/podcliquesetreplica:

- Gang termination (gangterminate.go:69-230): a PCS replica whose
  standalone PCLQ or PCSG has MinAvailableBreached persisting beyond
  TerminationDelay is deleted wholesale (all children), then recreated by
  the next component sync — gang restart semantics. The delay gives the
  scheduler/agents time to self-heal before the hammer falls.

- Rolling update (rollingupdate.go:37-296): on template-hash change,
  replicas are recreated one at a time, ordered breached-first → already
  -in-progress → by index; the replacement gang carries a placement-reuse
  hint (the slice of the gang it replaces; reference ReuseReservationRef,
  scheduler api podgang.go:65-71).
"""

from __future__ import annotations

import time

from grove_tpu.api import (
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodGang,
    constants as c,
)
from grove_tpu.api.meta import get_condition
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.events import EventRecorder
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client

ANNOTATION_PREFERRED_SLICE = f"{c.DOMAIN}/preferred-slice"

log = get_logger("replica-lifecycle")


def _replica_children(client: Client, pcs: PodCliqueSet, replica: int):
    sel = {c.LABEL_PCS_NAME: pcs.meta.name,
           c.LABEL_PCS_REPLICA: str(replica)}
    ns = pcs.meta.namespace
    return (client.list(PodClique, ns, sel),
            client.list(PodCliqueScalingGroup, ns, sel),
            client.list(PodGang, ns, sel))


def record_replica_slices(client: Client, pcs: PodCliqueSet,
                          replica: int) -> dict[str, str]:
    """Snapshot gang → slice for a replica about to be recreated."""
    _, _, gangs = _replica_children(client, pcs, replica)
    return {g.meta.name: g.status.assigned_slice
            for g in gangs if g.status.assigned_slice}


def delete_replica_children(client: Client, pcs: PodCliqueSet,
                            replica: int) -> None:
    """Delete every child of one PCS replica (pods go via cascade)."""
    pclqs, pcsgs, gangs = _replica_children(client, pcs, replica)
    ns = pcs.meta.namespace
    for kind_cls, objs in ((PodClique, pclqs),
                           (PodCliqueScalingGroup, pcsgs),
                           (PodGang, gangs)):
        for obj in objs:
            if obj.meta.deletion_timestamp is not None:
                continue
            try:
                client.delete(kind_cls, obj.meta.name, ns)
            except NotFoundError:
                pass


def breach_started_at(client: Client, pcs: PodCliqueSet,
                      replica: int) -> float | None:
    """Earliest MinAvailableBreached=True transition among the replica's
    standalone PCLQs and PCSGs; None when nothing is breached."""
    pclqs, pcsgs, _ = _replica_children(client, pcs, replica)
    starts = []
    for q in pclqs:
        if q.spec.pcsg_name:
            continue  # rolled up through its PCSG
        cond = get_condition(q.status.conditions, c.COND_MIN_AVAILABLE_BREACHED)
        if cond is not None and cond.status == "True":
            starts.append(cond.last_transition_time)
    for g in pcsgs:
        cond = get_condition(g.status.conditions, c.COND_MIN_AVAILABLE_BREACHED)
        if cond is not None and cond.status == "True":
            starts.append(cond.last_transition_time)
    return min(starts) if starts else None


def gang_termination_pass(client: Client, pcs: PodCliqueSet) -> float | None:
    """Terminate replicas whose breach outlived TerminationDelay.

    Returns a requeue delay when a breach clock is running, else None.
    """
    delay = pcs.spec.template.termination_delay_seconds
    if delay is None:
        delay = c.DEFAULT_TERMINATION_DELAY_SECONDS
    soonest: float | None = None
    now = time.time()
    for r in range(pcs.spec.replicas):
        started = breach_started_at(client, pcs, r)
        if started is None:
            continue
        elapsed = now - started
        if elapsed >= delay:
            log.info("gang-terminating %s replica %d (breached %.1fs > %.1fs)",
                     pcs.meta.name, r, elapsed, delay)
            EventRecorder(client, "replica-lifecycle").event(
                pcs, "Warning", "GangTerminated",
                f"replica {r}: MinAvailable breached for {elapsed:.0f}s "
                f"(> {delay:.0f}s); deleting and recreating the gang",
                key=f"replica-{r}")
            delete_replica_children(client, pcs, r)
        else:
            remaining = delay - elapsed
            soonest = remaining if soonest is None else min(soonest, remaining)
    return soonest


# ---- rolling update ----

def replica_pods_at_hash(client: Client, pcs: PodCliqueSet, replica: int,
                         target_hash: str) -> bool:
    from grove_tpu.api import Pod
    pods = client.list(Pod, pcs.meta.namespace,
                       selector={c.LABEL_PCS_NAME: pcs.meta.name,
                                 c.LABEL_PCS_REPLICA: str(replica)})
    return bool(pods) and all(
        p.meta.labels.get(c.LABEL_POD_TEMPLATE_HASH) == target_hash
        for p in pods)


def _replica_available(client: Client, pcs: PodCliqueSet, replica: int) -> bool:
    """Availability from LIVE pod objects, not aggregated PCLQ status —
    the aggregate can lag a just-deleted pod by one sync, and advancing
    the rollout on that stale read takes a second replica down while the
    first is still recovering.

    Standalone and gang-guaranteed PCSG-member cliques (pcsg_replica <
    PCSG min_available) must each hold their per-clique floor; elastic
    scaled replicas being down (e.g. preempted) must NOT stall a rollout.
    """
    from grove_tpu.api import Pod
    from grove_tpu.api.meta import is_condition_true
    pclqs, pcsgs, _ = _replica_children(client, pcs, replica)
    standalone = [q for q in pclqs if not q.spec.pcsg_name]
    if not standalone and not pcsgs:
        return False
    sg_min = {g.meta.name: g.spec.min_available for g in pcsgs}
    # A PCSG whose member PCLQs have not materialised yet has zero ready
    # pods — it must read as unavailable, not vacuously available.
    members_of = {name: 0 for name in sg_min}
    for q in pclqs:
        if q.spec.pcsg_name in members_of:
            members_of[q.spec.pcsg_name] += 1
    if any(n == 0 and sg_min[name] > 0 for name, n in members_of.items()):
        return False
    pods = [p for p in client.list(
        Pod, pcs.meta.namespace,
        selector={c.LABEL_PCS_NAME: pcs.meta.name,
                  c.LABEL_PCS_REPLICA: str(replica)})
        if p.meta.deletion_timestamp is None]
    ready_by_pclq: dict[str, int] = {}
    for p in pods:
        if is_condition_true(p.status.conditions, c.COND_READY):
            name = p.meta.labels.get(c.LABEL_PCLQ_NAME, "")
            ready_by_pclq[name] = ready_by_pclq.get(name, 0) + 1
    for q in pclqs:
        if q.spec.pcsg_name:
            threshold = sg_min.get(q.spec.pcsg_name)
            # Unknown PCSG (orphan member) → treat as guaranteed.
            if threshold is not None and q.spec.pcsg_replica >= threshold:
                continue  # elastic scaled-gang member may be down
        if ready_by_pclq.get(q.meta.name, 0) < q.spec.min_available:
            return False
    return True


def rolling_update_pass(client: Client, pcs: PodCliqueSet) -> float | None:
    """Advance the rolling update by at most one replica recreation.

    Returns a requeue delay while the update is in flight, None when done.
    OnDelete strategy only does bookkeeping (reference podcliqueset.go:
    488-504): PCLQ templates are already updated; the user deletes pods.
    """
    progress = pcs.status.rolling_update
    if progress is None:
        return None
    target = progress.target_hash

    from grove_tpu.api.podcliqueset import UpdateStrategyType
    on_delete = (pcs.spec.update_strategy.type == UpdateStrategyType.ON_DELETE)

    pending = [r for r in range(pcs.spec.replicas)
               if not replica_pods_at_hash(client, pcs, r, target)]
    if not pending:
        pcs.status.rolling_update = None
        pcs.status.updated_replicas = pcs.spec.replicas
        # Drop the per-update placement hints: they describe fleet state at
        # the moment of this update and must not bias future recreations.
        stale = [k for k in pcs.meta.annotations
                 if k.startswith(ANNOTATION_PREFERRED_SLICE)]
        try:
            client.update_status(pcs)
            if stale:
                fresh = client.get(PodCliqueSet, pcs.meta.name,
                                   pcs.meta.namespace)
                for k in stale:
                    fresh.meta.annotations.pop(k, None)
                client.update(fresh)
        except GroveError:
            pass
        return None
    # Persist rollout progress so watchers see per-replica advancement
    # (also the only bookkeeping OnDelete gets).
    updated_count = pcs.spec.replicas - len(pending)
    if pcs.status.updated_replicas != updated_count:
        pcs.status.updated_replicas = updated_count
        try:
            pcs = client.update_status(pcs)
            progress = pcs.status.rolling_update
            if progress is None:
                return 0.2
        except GroveError:
            pass
    if on_delete:
        return None  # user-driven; no orchestration

    # Order: breached first, then the one already being updated, then index
    # (reference rollingupdate.go:182-235).
    def order(r: int):
        breached = breach_started_at(client, pcs, r) is not None
        in_progress = progress.current_replica == r
        return (0 if breached else 1, 0 if in_progress else 1, r)

    pending.sort(key=order)
    victim = pending[0]

    if progress.current_replica == victim:
        # Already selected (pod-level: its PCLQs are rolling pods;
        # replica-level: recreated) — wait for it to reach the hash.
        return 0.2
    # Availability floor: never take a second replica down while the
    # previous one is still recovering (unless it is itself breached).
    if progress.current_replica is not None and \
            progress.current_replica != victim and \
            not _replica_available(client, pcs, progress.current_replica):
        return 0.2

    if progress.pod_level:
        # Hand the turn to the replica's PodClique controllers (they roll
        # pods one at a time, gated on current_replica); nothing is
        # deleted here, so gangs and placements survive.
        log.info("rolling update %s: pod-level update of replica %d -> %s",
                 pcs.meta.name, victim, target)
        EventRecorder(client, "replica-lifecycle").event(
            pcs, "Normal", "RollingUpdateReplica",
            f"replica {victim}: rolling pods in place to hash {target}",
            key=f"replica-{victim}")
        progress.current_replica = victim
        try:
            client.update_status(pcs)
        except GroveError:
            pass
        return 0.2

    slices = record_replica_slices(client, pcs, victim)
    if slices:
        # Full per-gang map: gang names are deterministic across the
        # recreation, so each gang gets exactly its old slice back.
        import json
        pcs.meta.annotations[ANNOTATION_PREFERRED_SLICE + f"-{victim}"] = \
            json.dumps(slices)
        try:
            pcs = client.update(pcs)
            progress = pcs.status.rolling_update
            if progress is None:
                return 0.2
        except GroveError:
            return 0.1
    log.info("rolling update %s: recreating replica %d -> %s",
             pcs.meta.name, victim, target)
    EventRecorder(client, "replica-lifecycle").event(
        pcs, "Normal", "RollingUpdateReplica",
        f"recreating replica {victim} at template hash {target}",
        key=f"replica-{victim}")
    delete_replica_children(client, pcs, victim)
    progress.current_replica = victim
    try:
        client.update_status(pcs)
    except GroveError:
        pass
    return 0.2
