"""PodGang controller (C4) — thin backend delegation.

Parity with reference internal/controller/podgang/reconciler.go:59-86:
resolve the backend from the gang's scheduler name (or default) and hand
the gang to Backend.sync_podgang. Native backends place gangs in their own
loop; this controller is the seam where a translating backend (e.g. one
emitting an external scheduler's CRD) would do its work.
"""

from __future__ import annotations

from grove_tpu.api import PodGang
from grove_tpu.api.meta import trace_id_of
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import NotFoundError
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.scheduler.framework import Registry
from grove_tpu.store.client import Client


class PodGangReconciler:
    def __init__(self, client: Client, scheduler_registry: Registry):
        self.client = client
        self.schedulers = scheduler_registry
        self.log = get_logger("podgang")

    def reconcile(self, req: Request) -> StepResult:
        try:
            gang = self.client.get(PodGang, req.name, req.namespace)
        except NotFoundError:
            return StepResult.finished()
        if gang.meta.deletion_timestamp is not None:
            return StepResult.finished()
        try:
            backend = self.schedulers.get(gang.spec.scheduler_name or None)
        except KeyError as e:
            return StepResult.fail(e)
        # Child span under reconcile.podgang: native backends no-op
        # here, but a translating backend's CRD emission is exactly the
        # kind of cross-system hop a trace must not lose. A pending
        # diagnosis rides along as an attr so a trace of a stuck gang
        # names its reason without a second lookup.
        attrs = {"gang": gang.meta.name, "backend": backend.name}
        if gang.status.last_diagnosis is not None:
            attrs["pending_reason"] = gang.status.last_diagnosis.reason
        with GLOBAL_TRACER.span(
                "podgang.sync",
                trace_id=trace_id_of(gang) or None,
                attrs=attrs):
            backend.sync_podgang(gang)
        return StepResult.finished()
