"""PodCliqueSet controller — the top-level reconciler (C1).

Parity with reference internal/controller/podcliqueset: finalizer flow,
generation-hash change detection, dependency-grouped component sync
(G1 services → G2 podcliques → G3 scalinggroups ∥ podgangs; reference
reconcilespec.go:274-300), and status aggregation (AvailableReplicas =
replicas with no MinAvailableBreached).
"""

from __future__ import annotations

import dataclasses

from grove_tpu.api import (
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodGang,
    SliceReservation,
)
from grove_tpu.api import constants as c
from grove_tpu.api.core import Service
from grove_tpu.api.meta import Condition, is_condition_true, set_condition
from grove_tpu.api.serde import clone as serde_clone
from grove_tpu.controllers import expected as exp
from grove_tpu.controllers import replica_lifecycle as lifecycle
from grove_tpu.controllers import statusbatch
from grove_tpu.runtime.concurrent import run_concurrently
from grove_tpu.runtime.controller import Request
from grove_tpu.runtime.errors import (
    AlreadyExistsError,
    GroveError,
    NotFoundError,
)
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client


class PodCliqueSetReconciler:
    def __init__(self, client: Client):
        self.client = client
        self.log = get_logger("podcliqueset")

    def reconcile(self, req: Request) -> StepResult:
        # One status sweep per reconcile: the generation-hash seed and
        # the aggregation below queue field-diff patches that flush as
        # ONE patch_status_many batch (GROVE_STATUS_BATCH=0 restores
        # the per-call update_status path).
        with statusbatch.sweep(self.client):
            return self._reconcile(req)

    def _reconcile(self, req: Request) -> StepResult:
        try:
            pcs = self.client.get(PodCliqueSet, req.name, req.namespace)
        except NotFoundError:
            return StepResult.finished()

        if pcs.meta.deletion_timestamp is not None:
            return self._reconcile_delete(pcs)

        if c.FINALIZER_PCS not in pcs.meta.finalizers:
            pcs.meta.finalizers.append(c.FINALIZER_PCS)
            pcs = self.client.update(pcs)

        template_hash = exp.generation_hash(pcs)
        if not pcs.status.generation_hash:
            before = statusbatch.snapshot(pcs)
            pcs.status.generation_hash = template_hash
            pcs.status.structure_hash = exp.structure_hash(pcs)
            pcs = statusbatch.commit_status(self.client, pcs, before)
        elif pcs.status.generation_hash != template_hash:
            # Pod-shaping-only change (e.g. an image tweak): each PCLQ of
            # the replica being updated rolls its pods one at a time in
            # place — gangs and placements survive. Structure change:
            # the selected replica is recreated wholesale. Either way the
            # rollout is sequenced one PCS replica at a time. An empty
            # stored structure_hash (status predating the field) means
            # the prior structure is unknown — fall back to the safe
            # replica-level recreation.
            s_hash = exp.structure_hash(pcs)
            pod_level = pcs.status.structure_hash == s_hash
            self.log.info("%s: %s rolling update to %s", pcs.meta.name,
                          "pod-level" if pod_level else "replica-level",
                          template_hash)
            pcs = self._init_rolling_update(pcs, template_hash, s_hash,
                                            pod_level)
        elif not pcs.status.structure_hash:
            # Backfill for statuses written before structure_hash existed.
            before = statusbatch.snapshot(pcs)
            pcs.status.structure_hash = exp.structure_hash(pcs)
            pcs = statusbatch.commit_status(self.client, pcs, before)

        # Availability loops first (reference sync group G1): gang
        # termination and rolling-update orchestration may delete replica
        # children that the component sync below then recreates.
        requeue = lifecycle.gang_termination_pass(self.client, pcs)
        ru_requeue = lifecycle.rolling_update_pass(self.client, pcs)
        if ru_requeue is not None:
            requeue = ru_requeue if requeue is None else min(requeue, ru_requeue)

        errors = self._sync_components(pcs, template_hash)
        self._sync_service_endpoints(pcs)
        self._update_status(pcs)
        if errors:
            return StepResult.fail(errors[0])
        if requeue is not None:
            return StepResult.requeue(requeue)
        return StepResult.finished()

    # ---- deletion (finalizer path) ----

    def _reconcile_delete(self, pcs: PodCliqueSet) -> StepResult:
        # Children are removed by owner-reference cascade on final removal;
        # the finalizer exists so asynchronous cleanup could be ordered
        # here (and so tests can observe the marked state).
        if c.FINALIZER_PCS in pcs.meta.finalizers:
            pcs.meta.finalizers.remove(c.FINALIZER_PCS)
            self.client.update(pcs)
        return StepResult.finished()

    # ---- rolling update bookkeeping (full orchestration in rollout.py) ----

    def _init_rolling_update(self, pcs: PodCliqueSet, target_hash: str,
                             s_hash: str, pod_level: bool) -> PodCliqueSet:
        from grove_tpu.api.podcliqueset import UpdateProgress
        pcs.status.generation_hash = target_hash
        pcs.status.structure_hash = s_hash
        pcs.status.rolling_update = UpdateProgress(target_hash=target_hash,
                                                   pod_level=pod_level)
        # Deliberately NOT batched: rolling_update_pass (direct writer,
        # same sweep) advances this progress object — a queued init
        # patch flushing afterwards would roll it back.
        return self.client.update_status(pcs)

    # ---- component sync ----

    def _sync_components(self, pcs: PodCliqueSet,
                         template_hash: str) -> list[Exception]:
        # Live (autoscaled) replica counts shape both gang pod references
        # and per-instance PCSG reservations.
        live = self._live_replicas(pcs)
        # G1: services + slice reservations (reservations must exist
        # before cliques so the binding controller can work while pods
        # are still being created).
        errors = self._sync_children(Service, exp.expected_services(pcs), pcs)
        errors += self._sync_children(
            SliceReservation, exp.expected_reservations(pcs, live), pcs,
            update_spec=True)
        self._ensure_workload_token(pcs, errors)
        if errors:
            return errors
        # G2: standalone PCLQs (must exist before podgangs reference pods).
        # The component label keeps PCSG-member PCLQs (owned by the PCSG
        # controller) out of this diff's prune set.
        errors = self._sync_children(
            PodClique, exp.expected_standalone_pclqs(pcs, template_hash), pcs,
            update_spec=True,
            extra_selector={c.LABEL_COMPONENT: exp.COMPONENT_STANDALONE_PCLQ})
        if errors:
            return errors
        # G3: scaling groups ∥ podgangs. Gangs reference live (possibly
        # autoscaled) replica counts and carry placement-reuse hints for
        # replicas being recreated by a rolling update.
        gangs = exp.expected_podgangs(pcs, live)
        for gang in gangs:
            r = gang.meta.labels.get(c.LABEL_PCS_REPLICA, "")
            raw = pcs.meta.annotations.get(
                lifecycle.ANNOTATION_PREFERRED_SLICE + f"-{r}")
            if raw:
                import json
                try:
                    hint = json.loads(raw).get(gang.meta.name, "")
                except (ValueError, AttributeError):
                    hint = ""
                if hint:
                    gang.meta.annotations[
                        lifecycle.ANNOTATION_PREFERRED_SLICE] = hint
        errors = run_concurrently([
            lambda: self._raise_all(self._sync_children(
                PodCliqueScalingGroup, exp.expected_pcsgs(pcs, template_hash),
                pcs, update_spec=True)),
            lambda: self._raise_all(self._sync_children(
                PodGang, gangs, pcs, update_spec=True)),
        ])
        return errors

    def _ensure_workload_token(self, pcs: PodCliqueSet,
                               errors: list[Exception]) -> None:
        """Mint the per-PCS workload identity token (reference
        satokensecret component): create-once — a regenerated token
        would invalidate running pods' credentials — removed by the
        owner cascade with the PCS. Kubelets inject it as
        GROVE_API_TOKEN; the server maps it to the PCS-scoped workload
        actor for authenticated metric pushes (api/core.py Secret)."""
        import secrets as pysecrets
        from grove_tpu.api import namegen
        from grove_tpu.api.core import Secret
        from grove_tpu.api.meta import new_meta

        name = namegen.workload_token_secret_name(pcs.meta.name)
        try:
            cur = self.client.get(Secret, name, pcs.meta.namespace)
        except NotFoundError:
            cur = None
        except GroveError as e:
            # Same error contract as the create path: record and let the
            # rest of the PCS sync proceed (a transient read failure
            # must not skip G2+ child syncs for this pass).
            errors.append(e)
            return
        if cur is not None:
            if cur.meta.labels.get(c.LABEL_TOKEN_KIND) != \
                    c.TOKEN_KIND_WORKLOAD:
                # Squatted name (admission now forbids user Secrets, but
                # one may predate that or arrive via a privileged
                # actor): the server will never map it, so say so
                # loudly instead of silently serving no identity.
                from grove_tpu.runtime.events import EventRecorder
                EventRecorder(self.client, "podcliqueset").event(
                    pcs, "Warning", "WorkloadTokenConflict",
                    f"Secret {name!r} exists but is not a control-plane "
                    "workload token; pods of this PodCliqueSet run "
                    "without workload identity until it is removed")
            return
        sec = Secret(
            meta=new_meta(name, namespace=pcs.meta.namespace, labels={
                c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                c.LABEL_PCS_NAME: pcs.meta.name,
                c.LABEL_TOKEN_KIND: c.TOKEN_KIND_WORKLOAD,
            }),
            data={"token": pysecrets.token_urlsafe(24)})
        sec.meta.owner_references = [exp.owner_ref(pcs)]
        from grove_tpu.runtime.trace import ANNOTATION_TRACE_ID
        tid = pcs.meta.annotations.get(ANNOTATION_TRACE_ID, "")
        if tid:
            sec.meta.annotations[ANNOTATION_TRACE_ID] = tid
        try:
            self.client.create(sec)
        except AlreadyExistsError:
            pass                               # concurrent sync won the race
        except GroveError as e:
            errors.append(e)

    def _live_replicas(self, pcs: PodCliqueSet) -> dict[str, int]:
        """Live replica counts for auto-scaled children (they own their
        replicas field; template values are only the initial state)."""
        live: dict[str, int] = {}
        sel = {c.LABEL_PCS_NAME: pcs.meta.name}
        for q in self.client.list(PodClique, pcs.meta.namespace, sel):
            if q.spec.auto_scaling is not None:
                live[q.meta.name] = q.spec.replicas
        for g in self.client.list(PodCliqueScalingGroup, pcs.meta.namespace,
                                  sel):
            if g.spec.auto_scaling is not None:
                live[g.meta.name] = g.spec.replicas
        return live

    @staticmethod
    def _raise_all(errors: list[Exception]) -> None:
        if errors:
            raise errors[0]

    def _sync_children(self, kind_cls, expected_objs, pcs,
                       update_spec: bool = False,
                       extra_selector: dict[str, str] | None = None
                       ) -> list[Exception]:
        """Create missing / update drifted / prune orphaned children."""
        errors: list[Exception] = []
        selector = {c.LABEL_PCS_NAME: pcs.meta.name}
        if extra_selector:
            selector.update(extra_selector)
        live = {o.meta.name: o for o in self.client.list(
            kind_cls, pcs.meta.namespace, selector)}
        expected_names = set()
        for obj in expected_objs:
            expected_names.add(obj.meta.name)
            cur = live.get(obj.meta.name)
            try:
                if cur is None:
                    self.client.create(obj)
                elif update_spec:
                    if getattr(obj.spec, "auto_scaling", None) is not None:
                        # replicas are owned by the autoscaler once the
                        # child exists; never stomp them from the template
                        obj.spec.replicas = cur.spec.replicas
                    # Dataclass equality, not to_dict round-trips: the
                    # same drift decision at a fraction of the cost (the
                    # update_status no-op check's argument) — this
                    # comparison runs for EVERY child on EVERY sync.
                    if cur.spec != obj.spec:
                        # cur is shared informer-cache state: clone
                        # before grafting the expected spec onto it.
                        fresh = serde_clone(cur)
                        fresh.spec = obj.spec
                        self.client.update(fresh)
            except GroveError as e:
                errors.append(e)
        # prune: children no longer in the expected set (scale-in, template
        # restructure) — reference syncflow.go orphan pruning
        for name, cur in live.items():
            if name not in expected_names and cur.meta.deletion_timestamp is None:
                try:
                    self.client.delete(kind_cls, name, pcs.meta.namespace)
                except GroveError as e:
                    errors.append(e)
        return errors

    def _sync_service_endpoints(self, pcs: PodCliqueSet) -> None:
        """Publish pod endpoints into each replica's headless Service —
        the DNS record analog workloads discover peers through (reference
        components/service/; publishNotReadyAddresses defaults true)."""
        from grove_tpu.api import Pod
        from grove_tpu.api.meta import is_condition_true as _ready
        hs = pcs.spec.template.headless_service
        if hs is None:
            return
        for svc in self.client.list(Service, pcs.meta.namespace,
                                    {c.LABEL_PCS_NAME: pcs.meta.name}):
            pods = self.client.list(Pod, pcs.meta.namespace, svc.selector)
            eps = sorted(
                p.spec.hostname for p in pods
                if hs.publish_not_ready_addresses
                or _ready(p.status.conditions, c.COND_READY))
            publish = hs.publish_not_ready_addresses
            if eps != svc.endpoints or svc.publish_not_ready != publish:
                # svc is shared informer-cache state: clone before edit.
                fresh = serde_clone(svc)
                fresh.endpoints = eps
                fresh.publish_not_ready = publish  # follow template edits
                try:
                    self.client.update(fresh)
                except GroveError:
                    pass

    # ---- status ----

    def _update_status(self, pcs: PodCliqueSet) -> None:
        try:
            pcs = self.client.get(PodCliqueSet, pcs.meta.name, pcs.meta.namespace)
        except NotFoundError:
            return
        before = statusbatch.snapshot(pcs)
        selector = {c.LABEL_PCS_NAME: pcs.meta.name}
        pclqs = self.client.list(PodClique, pcs.meta.namespace, selector)
        pcsgs = self.client.list(PodCliqueScalingGroup, pcs.meta.namespace,
                                 selector)
        # Group children by replica once: the per-replica listcomp shape
        # was O(replicas x children) — a measurable quadratic term in
        # every status sync at fleet scale (64 replicas x 64+ cliques).
        pclqs_by_r: dict[str, list] = {}
        for q in pclqs:
            if not q.spec.pcsg_name:
                pclqs_by_r.setdefault(
                    q.meta.labels.get(c.LABEL_PCS_REPLICA, ""), []).append(q)
        pcsgs_by_r: dict[str, list] = {}
        for g in pcsgs:
            pcsgs_by_r.setdefault(
                g.meta.labels.get(c.LABEL_PCS_REPLICA, ""), []).append(g)
        available = 0
        for r in range(pcs.spec.replicas):
            replica_pclqs = pclqs_by_r.get(str(r), [])
            replica_pcsgs = pcsgs_by_r.get(str(r), [])
            breached = any(
                is_condition_true(q.status.conditions,
                                  c.COND_MIN_AVAILABLE_BREACHED)
                for q in replica_pclqs) or any(
                is_condition_true(g.status.conditions,
                                  c.COND_MIN_AVAILABLE_BREACHED)
                for g in replica_pcsgs)
            ready = (replica_pclqs or replica_pcsgs) and all(
                q.status.ready_replicas >= q.spec.min_available
                for q in replica_pclqs) and all(
                g.status.ready_replicas >= g.spec.min_available
                for g in replica_pcsgs)
            if ready and not breached:
                available += 1
        pcs.status.replicas = pcs.spec.replicas
        pcs.status.available_replicas = available
        pcs.status.observed_generation = pcs.meta.generation
        pcs.status.conditions = set_condition(pcs.status.conditions, Condition(
            type="Available",
            status="True" if available >= pcs.spec.replicas else "False",
            reason=f"{available}/{pcs.spec.replicas} replicas available"))
        statusbatch.commit_status(self.client, pcs, before,
                                  swallow_errors=True)
