"""grovectl — run a control plane, apply manifests, watch status.

Usage examples (see samples/):

  # bring up an in-process cluster with a fake v5e fleet, deploy a
  # PodCliqueSet, wait for it to become available, print the timeline:
  python -m grove_tpu.cli run --fleet v5e:4x4:2 --apply samples/simple1.yaml

  # inspect resources after the run (printed automatically):
  python -m grove_tpu.cli run --fleet v5e:4x4:2 --apply f.yaml --show pods

The reference reserves a kubectl-plugin module for this role
(cli-plugin/, empty stub); here the CLI is functional and doubles as the
demo/e2e driver.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from grove_tpu.api import (
    Node,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodGang,
    constants as c,
)
from grove_tpu.api.meta import is_condition_true
from grove_tpu.cluster import new_cluster
from grove_tpu.manifest import load_manifest
from grove_tpu.topology.fleet import FleetSpec, SliceSpec


def parse_fleet(spec: str) -> FleetSpec:
    """'v5e:4x4:2[,v5p:2x2x2:1]' -> FleetSpec."""
    slices = []
    for part in spec.split(","):
        gen, topo, count = part.split(":")
        slices.append(SliceSpec(generation=gen, topology=topo,
                                count=int(count)))
    return FleetSpec(slices=slices)


def print_pods(client, namespace="default") -> None:
    rows = [("POD", "PHASE", "READY", "NODE", "GATES")]
    for p in client.list(Pod, namespace):
        ready = "1/1" if is_condition_true(p.status.conditions,
                                           c.COND_READY) else "0/1"
        rows.append((p.meta.name, p.status.phase.value, ready,
                     p.status.node_name or "<none>",
                     ",".join(p.spec.scheduling_gates) or "-"))
    _table(rows)


def print_gangs(client, namespace="default") -> None:
    rows = [("PODGANG", "PHASE", "SCHEDULED", "SLICE", "SCORE")]
    for g in client.list(PodGang, namespace):
        rows.append((g.meta.name, g.status.phase.value,
                     str(is_condition_true(g.status.conditions,
                                           c.COND_SCHEDULED)),
                     g.status.assigned_slice or "-",
                     f"{g.status.placement_score:.2f}"))
    _table(rows)


def print_sets(client, namespace="default") -> None:
    rows = [("PODCLIQUESET", "REPLICAS", "AVAILABLE", "HASH")]
    for s in client.list(PodCliqueSet, namespace):
        rows.append((s.meta.name, str(s.spec.replicas),
                     str(s.status.available_replicas),
                     s.status.generation_hash))
    _table(rows)


def print_events(client, namespace="default") -> None:
    from grove_tpu.runtime.events import Event
    events = client.list(Event, namespace)
    if not events:
        return
    rows = [("EVENT", "TYPE", "REASON", "COUNT", "MESSAGE")]
    for e in sorted(events, key=lambda e: e.last_seen):
        rows.append((f"{e.involved_kind}/{e.involved_name}", e.type,
                     e.reason, str(e.count), e.message[:60]))
    _table(rows)


def _table(rows) -> None:
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))


def _serve_config(args: argparse.Namespace):
    """Config for a serve-shaped command: --config file plus bearer
    tokens from --token-file/$GROVE_TOKEN_FILE (kube --token-auth-file
    analog; the deploy bundle mounts its Secret here). None when
    neither is given. Shared by the leader path and the standby path —
    a promoted standby must honor the same tokens the dead leader did,
    or failover silently locks every operator out."""
    config = None
    if getattr(args, "config", None):
        from grove_tpu.api.config import load_config
        config = load_config(args.config)
    token_file = (getattr(args, "token_file", None)
                  or os.environ.get("GROVE_TOKEN_FILE"))
    if token_file:
        from grove_tpu.api.config import OperatorConfiguration, \
            load_token_file
        if config is None:
            config = OperatorConfiguration()
        config.server_auth.tokens.update(load_token_file(token_file))
    return config


def _build_cluster(args: argparse.Namespace):
    """Shared bring-up for run/serve: config, fleet, --real agent."""
    config = _serve_config(args)
    state_dir = getattr(args, "state_dir", None)
    takeover = bool(getattr(args, "takeover", False))
    if getattr(args, "replica", None):
        from grove_tpu.api.config import OperatorConfiguration
        if config is None:
            config = OperatorConfiguration()
        config.ha.replica = args.replica
        config.ha.enabled = True    # naming a replica implies HA intent
    if takeover and state_dir:
        print(f"standing by for state-dir lease {state_dir!r} "
              "(takes over when the current holder exits)",
              file=sys.stderr, flush=True)
    fleet = parse_fleet(args.fleet)
    if args.real:
        fleet.fake = False
        cluster = new_cluster(config=config, fleet=fleet, fake_kubelet=False,
                              state_dir=state_dir, state_takeover=takeover)
        from grove_tpu.agent.process import ProcessKubelet
        cluster.manager.add_runnable(ProcessKubelet(cluster.client))
    else:
        cluster = new_cluster(config=config, fleet=fleet,
                              state_dir=state_dir, state_takeover=takeover)
    return cluster


def cmd_run(args: argparse.Namespace) -> int:
    cluster = _build_cluster(args)
    with cluster:
        client = cluster.client
        t0 = time.time()
        objs = []
        if args.apply:
            with open(args.apply) as f:
                objs = load_manifest(f)
            for obj in objs:
                client.create(obj)
                print(f"created {obj.KIND}/{obj.meta.name}")
        sets = [o for o in objs if isinstance(o, PodCliqueSet)]
        deadline = time.time() + args.timeout
        for pcs in sets:
            while time.time() < deadline:
                live = client.get(PodCliqueSet, pcs.meta.name,
                                  pcs.meta.namespace)
                if live.status.available_replicas >= live.spec.replicas:
                    print(f"PodCliqueSet/{pcs.meta.name} available "
                          f"({live.status.available_replicas}/"
                          f"{live.spec.replicas}) after "
                          f"{time.time() - t0:.2f}s")
                    break
                time.sleep(0.05)
            else:
                print(f"TIMEOUT waiting for PodCliqueSet/{pcs.meta.name}",
                      file=sys.stderr)
                print_pods(client)
                print_gangs(client)
                print_events(client)
                return 1
        print()
        print_sets(client)
        print()
        print_gangs(client)
        print()
        print_pods(client)
        print()
        print_events(client)
        if args.hold:
            print(f"\nholding cluster for {args.hold}s (ctrl-c to stop)...")
            time.sleep(args.hold)
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Long-running daemon: control plane + HTTP API."""
    from grove_tpu.server import ApiServer
    if getattr(args, "standby", False):
        if not getattr(args, "peer", None):
            print("error: --standby requires --peer <leader-url>",
                  file=sys.stderr)
            return 1
        if not getattr(args, "state_dir", None):
            # Without the shared state dir a promotion would come up on
            # an EMPTY in-memory store (the mirror is a cache, not the
            # durable state) and without the flock nothing would stop
            # split-brain on a partition false-positive.
            print("error: --standby requires --state-dir (the shared "
                  "durable state the promotion loads and flocks)",
                  file=sys.stderr)
            return 1
        return _serve_standby(args)
    cluster = _build_cluster(args)
    try:
        with cluster:
            # Bootstrap credential (k3s-style): without configured tokens
            # every remote mutation would be rejected, so generate an
            # operator token and print it once.
            auth = cluster.manager.config.server_auth
            bootstrap_token = None
            if not auth.tokens and not auth.allow_anonymous_mutations:
                import secrets
                from grove_tpu.admission.authorization import OPERATOR_ACTOR
                bootstrap_token = secrets.token_urlsafe(24)
                auth.tokens[bootstrap_token] = OPERATOR_ACTOR
            tls_cfg = cluster.manager.config.server_tls
            if args.tls:
                tls_cfg.enabled = True
            if args.tls_cert_dir:
                tls_cfg.enabled = True
                tls_cfg.cert_dir = args.tls_cert_dir
            if getattr(args, "tls_san", None):
                tls_cfg.enabled = True
                tls_cfg.sans.extend(s for s in args.tls_san
                                    if s not in tls_cfg.sans)
            if tls_cfg.enabled:
                # The serving address must be in the leaf's SANs or every
                # off-host client fails hostname verification. Wildcard
                # binds get this host's names; explicit hosts get added.
                import socket as _socket
                extra = ([_socket.gethostname(), _socket.getfqdn()]
                         if args.host in ("0.0.0.0", "::")
                         else [args.host])
                tls_cfg.sans.extend(s for s in extra
                                    if s and s not in tls_cfg.sans)
            server = ApiServer(cluster, host=args.host, port=args.port)
            try:
                server.start()
            except OSError as e:
                print(f"error: cannot bind {args.host}:{args.port}: {e}",
                      file=sys.stderr)
                return 1
            if bootstrap_token is not None:
                print(f"api token (generated): {bootstrap_token}\n"
                      f"  export GROVE_API_TOKEN={bootstrap_token}")
            if server.ca_file:
                print(f"tls ca certificate: {server.ca_file}\n"
                      f"  export GROVE_API_CA={server.ca_file}")
            # Pods learn the control-plane URL so in-pod engines can push
            # autoscaling metrics (serving/metrics_push.py). Wildcard
            # binds map to loopback — pods launched by the in-process
            # kubelet are local, and 0.0.0.0 is not a routable target.
            push_host = "127.0.0.1" if args.host in ("0.0.0.0", "::") \
                else args.host
            url = f"{server.scheme}://{push_host}:{server.port}"
            from grove_tpu.agent.process import ProcessKubelet
            for r in cluster.manager.runnables:
                if isinstance(r, ProcessKubelet):
                    r.extra_env["GROVE_CONTROL_PLANE"] = url
                    if server.ca_file:
                        r.extra_env["GROVE_API_CA"] = server.ca_file
            print(f"grove-tpu control plane serving on "
                  f"{url}  (ctrl-c to stop)")
            try:
                while True:
                    time.sleep(1.0)
            finally:
                server.stop()
    except KeyboardInterrupt:
        pass
    return 0


def _http(server: str, path: str, method: str = "GET",
          body: bytes | None = None,
          content_type: str = "application/yaml",
          token: str | None = None, ca: str | None = None,
          _followed: bool = False):
    """Request against a serve daemon. Returns (status, decoded-body);
    status 0 = could not reach the server. Shared by the client verbs and
    the server tests. ``token`` (default: $GROVE_API_TOKEN) authenticates
    mutating verbs; ``ca`` (default: $GROVE_API_CA) pins the TLS CA for
    https:// servers. A 503 whose body names the leader (a standby
    refusing a write — grove_tpu/ha) retries once against the hint, so
    grovectl pointed at any replica just works."""
    import json as _json
    import os as _os
    import urllib.error
    import urllib.request

    def decode(raw: bytes, ctype: str):
        if "json" in ctype:
            try:
                return _json.loads(raw or b"null")
            except ValueError:
                pass
        return raw.decode(errors="replace")

    headers = {"Content-Type": content_type}
    if token is None:
        token = _os.environ.get("GROVE_API_TOKEN", "")
    if token:
        headers["Authorization"] = f"Bearer {token}"
    ctx = None
    if server.startswith("https"):
        import ssl
        if ca is None:
            ca = _os.environ.get("GROVE_API_CA", "")
        ctx = ssl.create_default_context(cafile=ca or None)
    req = urllib.request.Request(f"{server}{path}", method=method, data=body,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            return resp.status, decode(resp.read(),
                                       resp.headers.get("Content-Type", ""))
    except urllib.error.HTTPError as e:
        # Error bodies may be non-JSON (proxy, wrong service on the port),
        # and a loaded server can reset (ConnectionResetError) or
        # close the socket short of Content-Length (IncompleteRead, an
        # HTTPException) mid-body — the status code is already in hand
        # either way.
        import http.client as _hc
        try:
            raw = e.read()
        except (OSError, _hc.HTTPException):
            raw = b""
        decoded = decode(raw, e.headers.get("Content-Type", "") or "json")
        hint = (decoded.get("leader") or ""
                if isinstance(decoded, dict) else "")
        if e.code == 503 and hint and not _followed \
                and hint.rstrip("/") != server.rstrip("/"):
            return _http(hint.rstrip("/"), path, method, body,
                         content_type, token, ca, _followed=True)
        return e.code, decoded
    except urllib.error.URLError as e:
        return 0, {"error": f"cannot reach {server}: {e.reason}"}


def _err_text(body) -> str:
    return body.get("error", body) if isinstance(body, dict) else str(body)


# kubectl-style printcolumns per kind (the reference declares
# printcolumns on every CRD, podcliqueset.go:28-35): (header, getter).
def _age(ts: float, now: float) -> str:
    d = max(0.0, now - ts)
    if d < 120:
        return f"{d:.0f}s"
    if d < 7200:
        return f"{d / 60:.0f}m"
    return f"{d / 3600:.1f}h"


def _cond(obj: dict, ctype: str) -> str:
    for cd in (obj.get("status", {}) or {}).get("conditions") or []:
        if cd.get("type") == ctype:
            return cd.get("status", "")
    return ""


def _pending_reason(obj: dict) -> str:
    """The Unschedulable condition's reason while it holds (empty once
    scheduled) — the PENDING-REASON printcolumn and the one-word answer
    `grovectl explain` expands on."""
    for cd in (obj.get("status", {}) or {}).get("conditions") or []:
        if cd.get("type") == c.COND_UNSCHEDULABLE \
                and cd.get("status") == "True":
            return cd.get("reason", "")
    return ""


_PRINT_COLUMNS: dict = {
    "PodCliqueSet": [
        ("REPLICAS", lambda o: str(o["spec"].get("replicas", 0))),
        ("AVAILABLE", lambda o: str(
            o["status"].get("available_replicas", 0))),
        ("UPDATED", lambda o: str(
            o["status"].get("updated_replicas", 0))),
    ],
    "PodClique": [
        ("REPLICAS", lambda o: str(o["spec"].get("replicas", 0))),
        ("READY", lambda o: str(o["status"].get("ready_replicas", 0))),
        ("MINAVAIL", lambda o: str(
            o["spec"].get("min_available", 0))),
        ("BREACHED", lambda o: _cond(o, c.COND_MIN_AVAILABLE_BREACHED)),
    ],
    "PodCliqueScalingGroup": [
        ("REPLICAS", lambda o: str(o["spec"].get("replicas", 0))),
        ("READY", lambda o: str(o["status"].get("ready_replicas", 0))),
        ("SCHEDULED", lambda o: str(
            o["status"].get("scheduled_replicas", 0))),
    ],
    "PodGang": [
        ("PHASE", lambda o: str(o["status"].get("phase", ""))),
        ("SCHEDULED", lambda o: _cond(o, c.COND_SCHEDULED)),
        ("READY", lambda o: _cond(o, c.COND_READY)),
        ("PENDING-REASON", _pending_reason),
        # The live ReuseReservationRef: a defrag migration target or
        # roll-safe slot hold the gang is pinned to (grovectl explain
        # expands on it).
        ("RESERVATION", lambda o: str(
            o["status"].get("reuse_reservation_ref", "") or "-")),
    ],
    "Pod": [
        ("PHASE", lambda o: str(o["status"].get("phase", ""))),
        ("READY", lambda o: _cond(o, c.COND_READY)),
        ("NODE", lambda o: o["status"].get("node_name", "")),
    ],
    "Node": [
        ("READY", lambda o: "True" if o["status"].get("ready") else "False"),
        ("CHIPS", lambda o: str(o["spec"].get("tpu_chips", 0))),
        ("CORDONED", lambda o: (
            "True" if o["spec"].get("unschedulable") else "")),
    ],
}


def cmd_get(args: argparse.Namespace) -> int:
    """Read resources from a running serve daemon. ``-o table`` renders
    the kind's printcolumns (the reference declares printcolumns on
    every CRD); default stays JSON for scripting."""
    import json as _json
    from urllib.parse import urlencode
    params = {}
    if getattr(args, "selector", None):
        if args.name:
            # kubectl parity: a name already identifies one object; a
            # selector on top would be silently unenforced server-side.
            print("error: --selector cannot be combined with a resource "
                  "name", file=sys.stderr)
            return 1
        for part in args.selector.split(","):
            k, _, v = part.partition("=")
            if not k or not v:
                print(f"error: bad selector {part!r} (want key=value)",
                      file=sys.stderr)
                return 1
            if f"l.{k}" in params and params[f"l.{k}"] != v:
                # Two values for one key can never both hold (AND
                # semantics) — overwriting would silently broaden.
                print(f"error: conflicting selector values for {k!r}",
                      file=sys.stderr)
                return 1
            params[f"l.{k}"] = v
    path = f"/api/{args.kind}" + (f"/{args.name}" if args.name else "")
    if params:
        path += "?" + urlencode(params)
    status, body = _http(args.server, path, ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(body)}", file=sys.stderr)
        return 1
    if args.output == "table":
        objs = body if isinstance(body, list) else [body]
        now = time.time()
        cols = _PRINT_COLUMNS.get(args.kind, [])
        rows = [("NAME", *(h for h, _ in cols), "AGE")]
        for o in objs:
            rows.append((
                o.get("meta", {}).get("name", ""),
                *(get(o) for _, get in cols),
                _age(o.get("meta", {}).get("creation_timestamp", now),
                     now)))
        _table(rows)
        return 0
    print(_json.dumps(body, indent=2))
    return 0


def cmd_describe(args: argparse.Namespace) -> int:
    """Human-oriented single-object view: identity, spec highlights,
    status, conditions with transition ages, and the object's events —
    the kubectl-describe analog built from the same wire verbs."""
    import json as _json
    status, obj = _http(args.server, f"/api/{args.kind}/{args.name}"
                        f"?namespace={args.namespace}", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(obj)}", file=sys.stderr)
        return 1
    meta = obj.get("meta", {})
    now = time.time()

    def age(ts: float) -> str:
        return _age(ts, now)

    print(f"Name:       {meta.get('name', '')}")
    print(f"Namespace:  {meta.get('namespace', '')}")
    print(f"Kind:       {args.kind}")
    print(f"UID:        {meta.get('uid', '')}")
    print(f"Created:    {age(meta.get('creation_timestamp', now))} ago "
          f"(generation {meta.get('generation', 0)}, "
          f"rv {meta.get('resource_version', 0)})")
    if meta.get("labels"):
        print("Labels:     " + ", ".join(
            f"{k}={v}" for k, v in sorted(meta["labels"].items())))
    owners = meta.get("owner_references") or []
    if owners:
        print("Owner:      " + ", ".join(
            f"{o.get('kind')}/{o.get('name')}" for o in owners))
    if meta.get("deletion_timestamp"):
        print("State:      TERMINATING")
    st = obj.get("status", {}) or {}
    scalars = {k: v for k, v in st.items()
               if isinstance(v, (int, float, str, bool)) and v != ""
               and k != "conditions"}
    if scalars:
        print("Status:")
        for k, v in sorted(scalars.items()):
            print(f"  {k}: {v}")
    conds = st.get("conditions") or []
    if conds:
        print("Conditions:")
        rows = [("  TYPE", "STATUS", "AGE", "REASON", "MESSAGE")]
        for cd in conds:
            rows.append(("  " + cd.get("type", ""), cd.get("status", ""),
                         age(cd.get("last_transition_time", now)),
                         cd.get("reason", ""), cd.get("message", "")))
        _table(rows)
    errs = st.get("last_errors") or []
    if errs:
        print("Last errors:")
        for e in errs:
            print(f"  [{e.get('code', '')}] {e.get('operation', '')}: "
                  f"{e.get('message', '')}")
    ev_status, events = _http(
        args.server, f"/api/Event?namespace={args.namespace}", ca=args.ca)
    if ev_status == 200:
        mine = [e for e in events
                if e.get("involved_name") == args.name
                and e.get("involved_kind") == args.kind]
        if mine:
            print("Events:")
            rows = [("  AGE", "TYPE", "REASON", "COUNT", "MESSAGE")]
            for e in sorted(mine, key=lambda e: e.get("last_seen", 0.0)):
                rows.append(("  " + age(e.get("last_seen", 0.0)),
                             e.get("type", ""), e.get("reason", ""),
                             str(e.get("count", 1)), e.get("message", "")))
            _table(rows)
    if args.json:
        print(_json.dumps(obj, indent=2))
    return 0


def cmd_top(args: argparse.Namespace) -> int:
    """kubectl-top-style capacity view: per-node chip allocation from
    live pod placements, rolled up per slice."""
    status, nodes = _http(args.server, "/api/Node?namespace=*", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(nodes)}", file=sys.stderr)
        return 1
    status, pods = _http(args.server, "/api/Pod?namespace=*", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(pods)}", file=sys.stderr)
        return 1
    used: dict[str, int] = {}
    for p in pods:
        node = p.get("status", {}).get("node_name")
        # Mirror the scheduler's accounting exactly (build_host_views):
        # only live (Pending/Running) pods consume chips — a completed
        # batch pod keeps its node_name but its chips are schedulable.
        if (node and not p.get("meta", {}).get("deletion_timestamp")
                and p.get("status", {}).get("phase") in ("Pending",
                                                         "Running")):
            used[node] = used.get(node, 0) + p.get("spec", {}).get(
                "tpu_chips", 0)
    slice_rollup: dict[str, list[int]] = {}
    rows = [("NODE", "SLICE", "CHIPS", "USED", "FREE", "STATE")]
    for n in sorted(nodes, key=lambda n: n["meta"]["name"]):
        name = n["meta"]["name"]
        # allocatable (status) — what the scheduler can actually place
        # on, not the spec'd hardware count: a registered-but-not-yet-
        # heartbeating remote node allocates 0.
        total = n.get("status", {}).get("allocatable_chips", 0)
        u = used.get(name, 0)
        # A node that drops NotReady (allocatable 0) while its pods are
        # still live would print negative FREE and skew the slice
        # rollup; fall back to the spec'd hardware count so the
        # maintenance view stays readable during node loss.
        if u > total:
            total = max(u, n.get("spec", {}).get("tpu_chips", 0))
        sl = n.get("meta", {}).get("labels", {}).get(
            c.NODE_LABEL_SLICE, "")
        state = []
        if not n.get("status", {}).get("ready"):
            state.append("NotReady")
        if n.get("spec", {}).get("unschedulable"):
            state.append("Cordoned")
        rows.append((name, sl, str(total), str(u), str(total - u),
                     ",".join(state) or "Ready"))
        agg = slice_rollup.setdefault(sl or name, [0, 0])
        agg[0] += total
        agg[1] += u
    _table(rows)
    print()
    srows = [("SLICE", "CHIPS", "USED", "FREE")]
    for sl, (total, u) in sorted(slice_rollup.items()):
        srows.append((sl, str(total), str(u), str(total - u)))
    _table(srows)
    return 0


def cmd_scale(args: argparse.Namespace) -> int:
    """kubectl scale analog: replica count via the same merge-patch
    surface HPA-style controllers use (the scale subresource's job)."""
    import json as _json
    body = _json.dumps({"spec": {"replicas": args.replicas}}).encode()
    status, out = _http(args.server,
                        f"/api/{args.kind}/{args.name}"
                        f"?namespace={args.namespace}",
                        "PATCH", body,
                        content_type="application/merge-patch+json",
                        ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(out)}", file=sys.stderr)
        return 1
    print(f"{args.kind}/{args.name} scaled to {args.replicas}")
    return 0


def cmd_rollout(args: argparse.Namespace) -> int:
    """kubectl rollout status analog: report a PodCliqueSet's rolling
    update progress (exit 0 = up to date, 1 = in progress) or --watch
    until it completes."""
    deadline = time.time() + args.timeout

    def once():
        """True=done, False=in progress, None=transient fetch error.
        Raises SystemExit(1) on a PERMANENT error (404/403/...): only a
        connection failure (status 0, server mid-restart) is worth
        retrying inside the watch deadline."""
        status, obj = _http(args.server,
                            f"/api/PodCliqueSet/{args.name}"
                            f"?namespace={args.namespace}", ca=args.ca)
        if status != 200:
            print(f"error ({status}): {_err_text(obj)}", file=sys.stderr)
            if status != 0:
                raise SystemExit(1)
            return None
        meta = obj.get("meta", {}) or {}
        st = obj.get("status", {}) or {}
        spec = obj.get("spec", {}) or {}
        ru = st.get("rolling_update")
        total = spec.get("replicas", 0)
        updated = st.get("updated_replicas", 0)
        if ru:
            mode = "pod-level" if ru.get("pod_level") else \
                "replica-recreation"
            cur = ru.get("current_replica")
            print(f"rolling update in progress ({mode}, target "
                  f"{ru.get('target_hash', '')[:12]}): "
                  f"{len(ru.get('updated_replicas') or [])}/{total} "
                  f"replicas updated"
                  + (f", updating replica {cur}" if cur is not None
                     else ""))
            return False
        # No in-progress update AND the controller has observed the
        # latest spec generation (kubectl's observedGeneration guard —
        # a watch started right after an apply must not win the race
        # against the controller creating rolling_update).
        if st.get("observed_generation", 0) < meta.get("generation", 0):
            print(f"PodCliqueSet/{args.name}: waiting for the controller "
                  f"to observe generation {meta.get('generation', 0)}")
            return False
        # Print the REAL updated counter: max(updated, total) would
        # fabricate "2/2" when updated_replicas is 0 (a PCS that never
        # rolled) or lags — the observed_generation guard above already
        # makes the up-to-date verdict itself safe.
        print(f"PodCliqueSet/{args.name}: up to date "
              f"({updated}/{total} replicas updated)")
        return True

    while True:
        done = once()
        if done is True:
            return 0
        if not args.watch:
            # Exit code distinguishes in-progress (and fetch errors)
            # from complete for scripts polling without --watch.
            return 1
        if time.time() > deadline:
            print("timed out waiting for rollout", file=sys.stderr)
            return 1
        # Transient fetch errors retry inside the deadline too (a serve
        # daemon mid-restart must not abort a watch with budget left).
        time.sleep(args.poll)


def cmd_deploy_status(args: argparse.Namespace) -> int:
    """Render a PodCliqueSet's deploy-progress record from the serve
    daemon's deploy observatory: pods per lifecycle stage, milestone
    offsets, write amplification (store writes per pod deployed), and
    the control plane's queue-wait vs reconcile-work split — the
    write-path companion to `grovectl rollout status` (which tracks
    spec rollouts; this tracks the deploy's cost). Exit 0 once the PCS
    reached Available, 1 while in progress (scripts poll it like
    rollout status)."""
    from grove_tpu.runtime.deploywatch import render_deploy_status
    status, data = _http(args.server,
                         f"/debug/deploy/{args.namespace}/{args.name}",
                         ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    for line in render_deploy_status(data, time.time()):
        print(line)
    return 0 if data.get("available_at") else 1


def cmd_serving_status(args: argparse.Namespace) -> int:
    """Render a scaling scope's serving SLO state from the serve
    daemon's serving observatory: engine-pushed signals (queue depth,
    KV utilization, TTFT/TPOT percentiles) aggregated per the
    registry's modes, judged against the scope's autoscaling target —
    the serving companion to `grovectl deploy-status`. Exit 0 while no
    watched SLO is breached, 1 on a breach (scripts alert on it)."""
    from grove_tpu.runtime.servingwatch import render_serving_status
    status, data = _http(args.server,
                         f"/debug/serving/{args.namespace}/{args.name}",
                         ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    for line in render_serving_status(data):
        print(line)
    breached = any((s.get("slo") or {}).get("breached")
                   for s in data.get("scopes", []))
    return 1 if breached else 0


def cmd_engine_profile(args: argparse.Namespace) -> int:
    """Render one serving engine's data-plane observatory payload from
    the serve daemon (GET /debug/xprof/<ns>/<name>): device-time phase
    breakdown with the hottest phase starred, the XLA compile table
    (lowerings, recompiles, storm warnings), memory accounting with a
    KV-headroom bar, and roofline estimates (stamped model-derived on
    backends without live stats) — the execution-layer companion to
    `grovectl serving-status` (that judges latency SLOs; this says
    where the device time and HBM go). Exit 0 on a healthy profile,
    1 when recompile storms were recorded (scripts alert on shape
    churn)."""
    from grove_tpu.serving.xprof import render_engine_profile
    status, data = _http(args.server,
                         f"/debug/xprof/{args.namespace}/{args.name}",
                         ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    for line in render_engine_profile(data):
        print(line)
    storms = (data.get("compile") or {}).get("storms", 0)
    return 1 if storms else 0


def cmd_request_trace(args: argparse.Namespace) -> int:
    """Render one request's span timeline from the serve daemon
    (GET /debug/requests/<ns>/<name>): phase attribution with the
    dominant phase starred, then the span-by-span timeline — the
    "why was this request slow" view. Without ``rid``, list the
    retained traces (slowest-K starred) so an exemplar id from
    serving-status can be picked off. Exit 0 when the requested trace
    rendered, 1 when it was never retained (ring churn or never
    traced)."""
    from grove_tpu.serving.reqtrace import render_request_trace
    status, data = _http(
        args.server, f"/debug/requests/{args.namespace}/{args.name}",
        ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    if args.rid is None:
        scope = data.get("scope") or {}
        print(f"engine:    {scope.get('namespace', '?')}/"
              f"{scope.get('name', '?')}")
        ring = data.get("ring") or {}
        print(f"retained:  {ring.get('len', 0)}/"
              f"{ring.get('capacity', 0)} finished "
              f"({ring.get('finished_total', 0)} total, "
              f"{data.get('dropped', 0)} dropped, "
              f"{data.get('live', 0)} live)")
        slowest = {t.get("rid") for t in data.get("slowest") or []}
        rows = {t.get("rid"): t for t in data.get("traces") or []}
        for t in data.get("slowest") or []:
            rows.setdefault(t.get("rid"), t)
        for rid in sorted(rows):
            t = rows[rid]
            star = " *" if rid in slowest else ""
            print(f"  rid {rid:<8} e2e {t.get('e2e_s', 0.0) * 1e3:>9.1f} ms"
                  f"  dominant {t.get('dominant') or '?'}{star}")
        return 0
    found = any(t.get("rid") == args.rid
                for t in (data.get("slowest") or [])
                + (data.get("traces") or []))
    for line in render_request_trace(data, args.rid):
        print(line)
    return 0 if found else 1


def cmd_defrag_status(args: argparse.Namespace) -> int:
    """Render the serve daemon's defrag plan ledger: the in-flight
    migration (hold/drain/rebind state), recent completed/aborted
    plans with their chips-freed-per-pod scores, and the remaining
    disruption budget — the placement-repair companion to `grovectl
    explain` (explain says why a gang is stuck; this says what the
    control plane is doing about it). Exit 0 while defrag is enabled,
    1 when disabled (scripts can alert on a forgotten kill switch)."""
    from grove_tpu.defrag.controller import render_defrag_status
    status, data = _http(args.server, "/debug/defrag", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    for line in render_defrag_status(data, time.time()):
        print(line)
    return 0 if data.get("enabled") else 1


def cmd_disruptions(args: argparse.Namespace) -> int:
    """Render the serve daemon's disruption-contract ledger: every live
    DisruptionNotice (reason, barrier state, deadline), in-flight and
    recent spot-reclaim evacuations, and the notice/ack/expiry
    counters — the planned-eviction companion to `grovectl
    defrag-status` (that shows placement repair; this shows the
    checkpoint barriers every planned eviction waits behind,
    docs/design/disruption-contract.md). Exit 0 while the contract is
    enabled, 1 when GROVE_DISRUPTION=0 (scripts can alert on a
    forgotten kill switch)."""
    from grove_tpu.disruption.reclaim import render_disruptions
    status, data = _http(args.server, "/debug/disruption", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    for line in render_disruptions(data, time.time()):
        print(line)
    return 0 if data.get("contract_enabled") else 1


def cmd_leader_status(args: argparse.Namespace) -> int:
    """Render a replica's leadership view (GET /debug/leadership):
    role, fencing epoch (this replica's claim AND the store's — a
    mismatch means the replica was fenced), transitions, and the
    leader hint a standby redirects writes to. Exit 0 when the queried
    replica leads un-fenced, 1 otherwise (scripts can probe 'is this
    the leader' with it)."""
    status, data = _http(args.server, "/debug/leadership", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    role = data.get("role", "?")
    print(f"replica:      {data.get('replica', '?')}")
    print(f"role:         {role}")
    epoch = data.get("epoch", 0)
    store_epoch = data.get("store_epoch")
    line = f"epoch:        {epoch}"
    if store_epoch is not None and store_epoch != epoch:
        line += f"  (store at {store_epoch} — this replica is FENCED)"
    print(line)
    print(f"transitions:  {data.get('transitions', 0)}")
    print(f"since:        {data.get('since_s', 0.0):.1f}s")
    if data.get("leader_hint"):
        print(f"leader:       {data['leader_hint']}")
    if not data.get("ha_enabled", True):
        print("ha:           DISABLED (GROVE_HA=0)")
    fenced = bool(data.get("fenced"))
    return 0 if role == "leader" and not fenced else 1


def cmd_controlplane_status(args: argparse.Namespace) -> int:
    """Render the control-plane observatory (GET /debug/controlplane):
    per-controller sweep attribution with the hottest controller
    starred, write-amplification ledger, hot-object top-K, watch-lag
    SLO verdicts, queue pickup-vs-work split. Exit 0 healthy, 1 on a
    watch-lag SLO breach or write-amp above --max-write-amp (scripted
    'is my control plane thrashing' probe)."""
    from grove_tpu.runtime import sweepobs
    status, data = _http(args.server, "/debug/controlplane", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
        return 1
    print("\n".join(sweepobs.render_controlplane_status(
        data, max_write_amp=args.max_write_amp)))
    problems = sweepobs.status_problems(data,
                                        max_write_amp=args.max_write_amp)
    return 1 if problems else 0


def _serve_standby(args: argparse.Namespace) -> int:
    """``serve --standby --peer <leader-url>``: run as a hot standby —
    wire mirror of the leader kept warm, reads served locally, writes
    refused with a leader hint — and PROMOTE when the leader stops
    answering health probes (the lease fence in store/persist.py
    guards the state dir itself, so a network-split false positive
    blocks on the flock instead of going split-brain). After
    promotion the process re-execs the normal serve path on the same
    port."""
    from grove_tpu.ha.standby import HotStandby, StandbyServer
    from grove_tpu.server import ApiServer

    standby = HotStandby(args.peer, state_dir=args.state_dir,
                         token=os.environ.get("GROVE_API_TOKEN", ""),
                         replica=args.replica or "standby",
                         ca_file=args.ca or "")
    standby.start()
    server = StandbyServer(standby, host=args.host, port=args.port)
    server.start()
    print(f"grove-tpu hot standby on http://{args.host}:{server.port} "
          f"(mirroring {args.peer}; ctrl-c to stop)")
    misses = 0
    try:
        while True:
            time.sleep(1.0)
            status, _ = _http(args.peer, "/healthz", ca=args.ca)
            misses = misses + 1 if status == 0 else 0
            if misses >= 3:
                print(f"leader {args.peer} unreachable x{misses}; "
                      "promoting", file=sys.stderr)
                break
    except KeyboardInterrupt:
        server.stop()
        return 0
    server.stop()                    # free the port for the real server
    config = _serve_config(args)
    cluster = standby.promote(config=config)
    # Same bootstrap-credential rule as the leader path: a promoted
    # control plane with no configured tokens must print one, or
    # failover locks every remote operator out.
    auth = cluster.manager.config.server_auth
    if not auth.tokens and not auth.allow_anonymous_mutations:
        import secrets
        from grove_tpu.admission.authorization import OPERATOR_ACTOR
        bootstrap = secrets.token_urlsafe(24)
        auth.tokens[bootstrap] = OPERATOR_ACTOR
        print(f"api token (generated at promotion): {bootstrap}\n"
              f"  export GROVE_API_TOKEN={bootstrap}")
    api = ApiServer(cluster, host=args.host, port=args.port)
    api.start()
    print(f"promoted: control plane serving on "
          f"http://{args.host}:{api.port} "
          f"(epoch {cluster.manager.store.fencing_epoch()})")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        api.stop()
        cluster.stop()
    return 0


def cmd_apply(args: argparse.Namespace) -> int:
    """Apply a manifest against a running serve daemon."""
    try:
        with open(args.file, "rb") as f:
            body = f.read()
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 1
    path = "/apply?dry_run=1" if getattr(args, "dry_run", False) else "/apply"
    status, out = _http(args.server, path, "POST", body, ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(out)}", file=sys.stderr)
        return 1
    rc = 0
    for r in out:
        suffix = f": {r['error']}" if r.get("error") else ""
        print(f"{r['kind']}/{r['name']} {r['action']}{suffix}")
        if r["action"] in ("invalid", "forbidden"):
            rc = 1        # a dry run is a validation GATE: fail loudly
    return rc


def cmd_patch(args: argparse.Namespace) -> int:
    """JSON-merge-patch a resource on a running serve daemon."""
    import json as _json
    try:
        _json.loads(args.patch)
    except ValueError as e:
        print(f"error: patch is not valid JSON: {e}", file=sys.stderr)
        return 1
    status, out = _http(args.server, f"/api/{args.kind}/{args.name}",
                        "PATCH", args.patch.encode(),
                        content_type="application/merge-patch+json",
                        ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(out)}", file=sys.stderr)
        return 1
    print(f"{args.kind}/{args.name} patched "
          f"(generation {out['meta']['generation']})")
    return 0


def cmd_delete(args: argparse.Namespace) -> int:
    """Delete a resource on a running serve daemon."""
    status, out = _http(args.server, f"/api/{args.kind}/{args.name}",
                        "DELETE", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(out)}", file=sys.stderr)
        return 1
    print(f"{args.kind}/{args.name} deleted")
    return 0


def cmd_cordon(args: argparse.Namespace) -> int:
    """Mark a node (un)schedulable; --drain also fails the node's pods
    so the standard gang self-heal reschedules them elsewhere (kubectl
    cordon/uncordon/drain analog, over the same PATCH verbs)."""
    import json as _json
    want = args.verb == "cordon" or args.drain
    body = _json.dumps({"spec": {"unschedulable": want}}).encode()
    status, out = _http(args.server,
                        f"/api/Node/{args.name}?namespace={args.namespace}",
                        "PATCH", body, ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(out)}", file=sys.stderr)
        return 1
    print(f"Node/{args.name} {'cordoned' if want else 'uncordoned'}")
    if not args.drain:
        return 0
    status, pods = _http(args.server, "/api/Pod?namespace=*", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(pods)}", file=sys.stderr)
        return 1
    mine = [p for p in pods
            if p.get("status", {}).get("node_name") == args.name
            and not p.get("meta", {}).get("deletion_timestamp")
            # terminal pods keep their outcome (kubectl drain skips them
            # too) — rewriting Succeeded to Failed would falsify a
            # finished run and trigger a pointless self-heal
            and p.get("status", {}).get("phase") not in ("Succeeded",
                                                         "Failed")]
    failed = 0
    for p in mine:
        patch = _json.dumps({
            "phase": "Failed",
            "message": f"drained from {args.name}",
            "conditions": [{"type": c.COND_READY, "status": "False",
                            "reason": "Drained"}],
        }).encode()
        st, out = _http(args.server,
                        f"/api/Pod/{p['meta']['name']}/status"
                        f"?namespace={p['meta']['namespace']}",
                        "PATCH", patch, ca=args.ca)
        if st == 200:
            failed += 1
        else:
            print(f"warning: pod {p['meta']['name']}: {_err_text(out)}",
                  file=sys.stderr)
    print(f"drained {failed}/{len(mine)} pods from {args.name} "
          "(gang self-heal reschedules them)")
    return 0


def cmd_logs(args: argparse.Namespace) -> int:
    """Stream a pod's log from a serve daemon (kubectl-logs analog)."""
    path = f"/logs/{args.namespace}/{args.pod}"
    if args.tail is not None:
        path += f"?tail={args.tail}"
    status, body = _http(args.server, path, ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(body)}", file=sys.stderr)
        return 1
    sys.stdout.write(body if isinstance(body, str) else str(body))
    return 0


def cmd_events(args: argparse.Namespace) -> int:
    """List cluster events, newest last (kubectl-get-events analog)."""
    status, body = _http(
        args.server, f"/api/Event?namespace={args.namespace}", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(body)}", file=sys.stderr)
        return 1
    events = sorted(body, key=lambda e: e.get("last_seen", 0.0))
    if args.involved:
        events = [e for e in events
                  if e.get("involved_name") == args.involved]
    now = time.time()

    def age(ts: float) -> str:
        return _age(ts, now)

    rows = [("AGE", "TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE")]
    for e in events:
        rows.append((
            age(e.get("last_seen", 0.0)), e.get("type", ""),
            e.get("reason", ""),
            f"{e.get('involved_kind', '')}/{e.get('involved_name', '')}",
            str(e.get("count", 1)), e.get("message", "")))
    _table(rows)
    return 0


def cmd_trace(args: argparse.Namespace) -> int:
    """Render an object's end-to-end lifecycle trace: the span tree
    across controllers → scheduler → agent with per-phase durations,
    milestones, and the critical path — the "why did this gang take 4s
    to come up?" view. Needs ``profiling.enabled`` on the serve daemon
    (the /debug/traces gate)."""
    from grove_tpu.runtime.trace import ANNOTATION_TRACE_ID, critical_path
    if "/" not in args.target:
        print("error: target must be <kind>/<name> "
              "(e.g. PodCliqueSet/simple1)", file=sys.stderr)
        return 1
    kind, name = args.target.split("/", 1)
    status, obj = _http(args.server, f"/api/{kind}/{name}"
                        f"?namespace={args.namespace}", ca=args.ca)
    if status != 200:
        print(f"error ({status}): {_err_text(obj)}", file=sys.stderr)
        return 1
    tid = ((obj.get("meta", {}) or {}).get("annotations") or {}).get(
        ANNOTATION_TRACE_ID, "")
    if not tid:
        print(f"error: {kind}/{name} carries no {ANNOTATION_TRACE_ID} "
              "annotation (created before tracing, or GROVE_TRACE=0)",
              file=sys.stderr)
        return 1
    status, data = _http(args.server, f"/debug/traces?trace_id={tid}",
                         ca=args.ca)
    if status != 200:
        hint = (" (enable config profiling.enabled on the serve daemon)"
                if status == 404 else "")
        print(f"error ({status}): {_err_text(data)}{hint}",
              file=sys.stderr)
        return 1
    spans = data.get("spans", [])
    milestones = data.get("milestones", [])
    t0 = data.get("starts", {}).get(tid)
    if t0 is None:
        t0 = min((s["start"] for s in spans), default=time.time())
    print(f"trace {tid}  {kind}/{name}  "
          f"(started {_age(t0, time.time())} ago)")

    def ms(dt: float) -> str:
        return f"{dt * 1e3:.1f}ms"

    # Per-gang milestone timeline + phase durations.
    for m in milestones:
        ph = m.get("phases", {})
        parts = [f"{phase} +{ms(ph[phase] - t0)}"
                 for phase in ("gang_created", "scheduled", "started",
                               "ready") if phase in ph]
        print(f"  gang {m['subject']}: " + "  ".join(parts))
        if "ready" in ph:
            print(f"    time-to-scheduled "
                  f"{ms(ph.get('scheduled', ph['ready']) - t0)}  "
                  f"time-to-ready {ms(ph['ready'] - t0)}")
    if not spans:
        print("  (no spans retained — the flight-recorder ring may "
              "have wrapped)")
        return 0

    # Span tree, critical path starred.
    crit = set(critical_path(spans))
    by_parent: dict = {}
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        parent = s["parent_id"] if s["parent_id"] in by_id else ""
        by_parent.setdefault(parent, []).append(s)
    print(f"  spans ({len(spans)}; * = critical path):")

    def render(span: dict, depth: int) -> None:
        mark = "*" if span["span_id"] in crit else " "
        attrs = " ".join(f"{k}={v}"
                         for k, v in sorted(span["attrs"].items()))
        err = f"  ERROR: {span['error']}" if span.get("error") else ""
        print(f"  {mark} {'  ' * depth}{span['name']}  "
              f"+{ms(span['start'] - t0)}  {ms(span['end'] - span['start'])}"
              + (f"  {attrs}" if attrs else "") + err)
        for child in sorted(by_parent.get(span["span_id"], []),
                            key=lambda s: s["start"]):
            render(child, depth + 1)

    for root in sorted(by_parent.get("", []), key=lambda s: s["start"]):
        render(root, 0)
    return 0


def cmd_explain(args: argparse.Namespace) -> int:
    """Render a gang's (or a PodCliqueSet's member gangs') placement
    diagnosis: why the scheduler could not seat it — per-candidate-
    domain verdicts with the closest fit starred, the preemption
    outcome, and node-loss capacity. The kube-scheduler
    per-plugin-failure-message analog for 'why is my gang pending'."""
    from grove_tpu.scheduler.explain import payload_from_obj, \
        render_explain
    if "/" not in args.target:
        print("error: target must be <kind>/<name> "
              "(e.g. podgang/simple1-0 or podcliqueset/simple1)",
              file=sys.stderr)
        return 1
    kind, name = args.target.split("/", 1)
    kind_l = kind.lower()
    now = time.time()
    if kind_l in ("podgang", "pg"):
        status, data = _http(
            args.server, f"/debug/placement/{args.namespace}/{name}",
            ca=args.ca)
        if status != 200:
            print(f"error ({status}): {_err_text(data)}", file=sys.stderr)
            return 1
        for line in render_explain(data, now):
            print(line)
        return 0
    if kind_l in ("podcliqueset", "pcs"):
        from urllib.parse import urlencode
        status, gangs = _http(
            args.server,
            "/api/PodGang?" + urlencode(
                {"namespace": args.namespace,
                 f"l.{c.LABEL_PCS_NAME}": name}),
            ca=args.ca)
        if status != 200:
            print(f"error ({status}): {_err_text(gangs)}",
                  file=sys.stderr)
            return 1
        if not gangs:
            print(f"error: PodCliqueSet/{name} has no PodGangs "
                  f"in namespace {args.namespace!r}", file=sys.stderr)
            return 1
        payloads = [payload_from_obj(g) for g in
                    sorted(gangs, key=lambda g: g["meta"]["name"])]
        pending = sum(1 for p in payloads if p["diagnosis"] is not None)
        print(f"PodCliqueSet/{name}: {len(payloads)} gang(s), "
              f"{pending} with a pending diagnosis")
        for p in payloads:
            for line in render_explain(p, now):
                print(line)
        return 0
    print(f"error: explain supports podgang/<name> and "
          f"podcliqueset/<name>, not {kind!r}", file=sys.stderr)
    return 1


def cmd_agent(args: argparse.Namespace) -> int:
    """Per-host node agent against a remote control plane (HTTP)."""
    import os
    from grove_tpu.agent.remote import RemoteAgent
    from grove_tpu.store.httpclient import HttpClient
    from grove_tpu.runtime.errors import GroveError

    token = args.token or os.environ.get("GROVE_API_TOKEN", "")
    ca = args.ca or os.environ.get("GROVE_API_CA", "")
    client = HttpClient(args.server, token=token, ca_file=ca)
    register = None
    if args.register:
        from grove_tpu.topology.fleet import build_node, node_name
        try:
            gen, topo, slice_name, worker = args.register.split(":")
            register = build_node(gen, topo, slice_name, int(worker),
                                  namespace=args.namespace, fake=False)
        except (ValueError, KeyError) as e:
            print(f"error: bad --register {args.register!r} "
                  f"(want gen:topology:slice:worker): {e}", file=sys.stderr)
            return 1
        if node_name(slice_name, int(worker)) != args.node:
            print(f"error: --register names node "
                  f"{node_name(slice_name, int(worker))!r} but --node is "
                  f"{args.node!r}", file=sys.stderr)
            return 1
    agent = RemoteAgent(client, node_name=args.node, register=register,
                        namespace=args.namespace, tick=args.tick,
                        workdir=args.workdir)
    try:
        agent.start()
    except GroveError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"node agent running: node {args.node} -> {args.server} "
          "(ctrl-c to stop)")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def cmd_render_deploy(args: argparse.Namespace) -> int:
    from grove_tpu.deploy import (
        DeployValues,
        load_values,
        render_bundle,
        validate_values,
        write_bundle,
    )
    from grove_tpu.runtime.errors import ValidationError
    try:
        if args.values:
            values = load_values(args.values)
        else:
            values = DeployValues()
            validate_values(values)
        files = render_bundle(values, args.target)
    except ValidationError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    for path in write_bundle(files, args.out):
        print(path)
    return 0


def cmd_lint(args: argparse.Namespace) -> int:
    """grovelint over the tree (the golangci-lint analog): AST rules
    for the project's earned invariants, JSON report, diff-friendly
    exit codes (docs/design/static-analysis.md)."""
    from grove_tpu.analysis.grovelint import main as lint_main
    forwarded: list[str] = list(args.paths or [])
    if args.json:
        forwarded.append("--json")
    if args.baseline:
        forwarded += ["--baseline", args.baseline]
    if args.write_baseline:
        forwarded += ["--write-baseline", args.write_baseline]
    return lint_main(forwarded)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="grovectl")
    sub = parser.add_subparsers(dest="cmd", required=True)

    default_server = "http://127.0.0.1:8087"
    def add_ca(p):
        p.add_argument("--ca", help="CA certificate to pin for https "
                                    "servers (default $GROVE_API_CA)")

    get = sub.add_parser("get", help="read resources from a serve daemon")
    get.add_argument("kind")
    get.add_argument("name", nargs="?")
    get.add_argument("-l", "--selector",
                     help="label selector key=value[,key=value] "
                          "(kubectl -l analog)")
    get.add_argument("-o", "--output", choices=["json", "table"],
                     default="json",
                     help="table renders the kind's printcolumns "
                          "(kubectl-get analog); json for scripting")
    get.add_argument("--server", default=default_server)
    add_ca(get)
    get.set_defaults(fn=cmd_get)

    desc = sub.add_parser("describe", help="human-oriented single-object "
                          "view: status, conditions, events (kubectl "
                          "describe analog)")
    desc.add_argument("kind")
    desc.add_argument("name")
    desc.add_argument("--namespace", default="default")
    desc.add_argument("--json", action="store_true",
                      help="also dump the raw object JSON")
    desc.add_argument("--server", default=default_server)
    add_ca(desc)
    desc.set_defaults(fn=cmd_describe)

    apply_p = sub.add_parser("apply", help="apply a manifest to a serve daemon")
    apply_p.add_argument("-f", "--file", required=True)
    apply_p.add_argument("--dry-run", action="store_true",
                         help="server-side dry run: full admission "
                              "(defaulting/validation/authorization), "
                              "nothing committed")
    apply_p.add_argument("--server", default=default_server)
    add_ca(apply_p)
    apply_p.set_defaults(fn=cmd_apply)

    patch_p = sub.add_parser(
        "patch", help="JSON-merge-patch a resource on a serve daemon "
                      "(spec/labels/annotations)")
    patch_p.add_argument("kind")
    patch_p.add_argument("name")
    patch_p.add_argument("-p", "--patch", required=True,
                         help='e.g. \'{"spec": {"replicas": 3}}\'')
    patch_p.add_argument("--server", default=default_server)
    add_ca(patch_p)
    patch_p.set_defaults(fn=cmd_patch)

    delete = sub.add_parser("delete", help="delete a resource on a serve daemon")
    delete.add_argument("kind")
    delete.add_argument("name")
    delete.add_argument("--server", default=default_server)
    add_ca(delete)
    delete.set_defaults(fn=cmd_delete)

    tp = sub.add_parser("top", help="per-node/per-slice chip allocation "
                        "from live pod placements (kubectl top analog)")
    tp.add_argument("what", choices=["nodes"], nargs="?", default="nodes")
    tp.add_argument("--server", default=default_server)
    add_ca(tp)
    tp.set_defaults(fn=cmd_top)

    sc = sub.add_parser("scale", help="set replicas on a PodCliqueSet / "
                        "PodCliqueScalingGroup / PodClique (kubectl "
                        "scale analog, via merge patch)")
    sc.add_argument("kind", choices=["PodCliqueSet",
                                     "PodCliqueScalingGroup", "PodClique"])
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)
    sc.add_argument("--namespace", default="default")
    sc.add_argument("--server", default=default_server)
    add_ca(sc)
    sc.set_defaults(fn=cmd_scale)

    ro = sub.add_parser("rollout", help="rolling-update status for a "
                        "PodCliqueSet (kubectl rollout status analog)")
    ro.add_argument("verb", choices=["status"])
    ro.add_argument("name")
    ro.add_argument("--namespace", default="default")
    ro.add_argument("--watch", action="store_true",
                    help="poll until the rollout completes")
    ro.add_argument("--timeout", type=float, default=300.0)
    ro.add_argument("--poll", type=float, default=0.5)
    ro.add_argument("--server", default=default_server)
    add_ca(ro)
    ro.set_defaults(fn=cmd_rollout)

    ds = sub.add_parser(
        "deploy-status",
        help="deploy observatory view of a PodCliqueSet: pods per "
             "lifecycle stage, milestones, store writes per pod "
             "deployed, queue-wait vs work split (exit 0 = Available, "
             "1 = in progress; the write-path companion to rollout "
             "status)")
    ds.add_argument("name")
    ds.add_argument("--namespace", default="default")
    ds.add_argument("--server", default=default_server)
    add_ca(ds)
    ds.set_defaults(fn=cmd_deploy_status)

    ss = sub.add_parser(
        "serving-status",
        help="serving observatory view of a scaling scope: engine SLO "
             "signals (queue depth, KV utilization, TTFT/TPOT "
             "percentiles) vs the autoscaling target (exit 0 = ok, "
             "1 = SLO breached; the serving companion to "
             "deploy-status)")
    ss.add_argument("name")
    ss.add_argument("--namespace", default="default")
    ss.add_argument("--server", default=default_server)
    add_ca(ss)
    ss.set_defaults(fn=cmd_serving_status)

    ep = sub.add_parser(
        "engine-profile",
        help="data-plane observatory view of a serving engine: "
             "device-time phase breakdown, XLA compile table, memory "
             "accounting, roofline estimates (exit 0 = healthy, 1 = "
             "recompile storms recorded; the execution-layer companion "
             "to serving-status)")
    ep.add_argument("name")
    ep.add_argument("--namespace", default="default")
    ep.add_argument("--server", default=default_server)
    add_ca(ep)
    ep.set_defaults(fn=cmd_engine_profile)

    rtr = sub.add_parser(
        "request-trace",
        help="request observatory view of a serving engine: one rid's "
             "span timeline with the dominant phase starred (the "
             "'why was this request slow' answer; no rid lists the "
             "retained traces — slowest-K starred)")
    rtr.add_argument("name")
    rtr.add_argument("rid", nargs="?", type=int, default=None)
    rtr.add_argument("--namespace", default="default")
    rtr.add_argument("--server", default=default_server)
    add_ca(rtr)
    rtr.set_defaults(fn=cmd_request_trace)

    dfs = sub.add_parser(
        "defrag-status",
        help="placement-repair ledger from a serve daemon: in-flight "
             "migration, recent plans, disruption budget (exit 1 when "
             "defrag is disabled)")
    dfs.add_argument("--server", default=default_server)
    add_ca(dfs)
    dfs.set_defaults(fn=cmd_defrag_status)

    dis = sub.add_parser(
        "disruptions",
        help="disruption-contract ledger from a serve daemon: live "
             "eviction notices with barrier state, in-flight/recent "
             "spot-reclaim evacuations (exit 1 when the contract is "
             "disabled)")
    dis.add_argument("--server", default=default_server)
    add_ca(dis)
    dis.set_defaults(fn=cmd_disruptions)

    cps = sub.add_parser(
        "controlplane-status",
        help="control-plane observatory from a serve daemon: per-"
             "controller sweep attribution (hottest starred), write-"
             "amplification ledger with hot objects, watch-lag SLO "
             "(exit 1 on an SLO breach or write-amp above "
             "--max-write-amp)")
    cps.add_argument("--max-write-amp", type=float,
                     default=10.0,
                     help="recent write-calls-per-changed-object above "
                          "which a controller is flagged (default 10)")
    cps.add_argument("--server", default=default_server)
    add_ca(cps)
    cps.set_defaults(fn=cmd_controlplane_status)

    ls = sub.add_parser(
        "leader-status",
        help="leadership view of a replica: role, fencing epoch, "
             "transitions, leader hint (exit 0 = an un-fenced leader)")
    ls.add_argument("--server", default=default_server)
    add_ca(ls)
    ls.set_defaults(fn=cmd_leader_status)

    for verb in ("cordon", "uncordon"):
        cp = sub.add_parser(verb, help=f"{verb} a node "
                            "(kubectl analog; cordon takes --drain)")
        cp.add_argument("name")
        if verb == "cordon":
            cp.add_argument("--drain", action="store_true",
                            help="also fail the node's pods so gang "
                                 "self-heal reschedules them")
        cp.add_argument("--namespace", default="default")
        cp.add_argument("--server", default=default_server)
        add_ca(cp)
        cp.set_defaults(fn=cmd_cordon, verb=verb,
                        **({} if verb == "cordon" else {"drain": False}))

    logs_p = sub.add_parser("logs", help="print a pod's log from a serve "
                                         "daemon (kubectl logs analog)")
    logs_p.add_argument("pod")
    logs_p.add_argument("--namespace", default="default")
    logs_p.add_argument("--tail", type=int)
    logs_p.add_argument("--server", default=default_server)
    add_ca(logs_p)
    logs_p.set_defaults(fn=cmd_logs)

    tr = sub.add_parser(
        "trace", help="render an object's end-to-end lifecycle trace: "
                      "span tree across controllers/scheduler/agent, "
                      "per-phase durations, critical path (needs "
                      "profiling.enabled on the serve daemon)")
    tr.add_argument("target", help="<kind>/<name>, e.g. "
                                   "PodCliqueSet/simple1")
    tr.add_argument("--namespace", default="default")
    tr.add_argument("--server", default=default_server)
    add_ca(tr)
    tr.set_defaults(fn=cmd_trace)

    ex = sub.add_parser(
        "explain", help="why is this gang pending: render the "
                        "scheduler's placement diagnosis (candidate "
                        "domains, preemption outcome, node loss) for a "
                        "podgang or a podcliqueset's member gangs")
    ex.add_argument("target", help="podgang/<name> or "
                                   "podcliqueset/<name>")
    ex.add_argument("--namespace", default="default")
    ex.add_argument("--server", default=default_server)
    add_ca(ex)
    ex.set_defaults(fn=cmd_explain)

    events_p = sub.add_parser("events", help="list cluster events "
                                             "(kubectl get events analog)")
    events_p.add_argument("--namespace", default="default")
    events_p.add_argument("--involved", help="filter by involved object "
                                             "name")
    events_p.add_argument("--server", default=default_server)
    add_ca(events_p)
    events_p.set_defaults(fn=cmd_events)

    serve = sub.add_parser("serve", help="run the control plane as a "
                                         "daemon with an HTTP API")
    serve.add_argument("--fleet", default="v5e:4x4:2")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=8087)
    serve.add_argument("--real", action="store_true")
    serve.add_argument("--config")
    serve.add_argument("--token-file", dest="token_file",
                       help="bearer tokens file, 'token,actor' per line "
                            "(kube --token-auth-file analog; env "
                            "GROVE_TOKEN_FILE)")
    serve.add_argument("--tls", action="store_true",
                       help="serve HTTPS with self-managed certificates "
                            "(config: server_tls)")
    serve.add_argument("--tls-cert-dir", dest="tls_cert_dir",
                       help="certificate directory for --tls "
                            "(implies --tls; default 'certs')")
    serve.add_argument("--tls-san", dest="tls_san", action="append",
                       help="extra subject-alternative-name for the "
                            "server certificate (repeatable; implies "
                            "--tls). The bind host is added "
                            "automatically.")
    serve.add_argument("--state-dir", dest="state_dir",
                       help="durable control-plane state (WAL+snapshot); "
                            "restart resumes every resource")
    serve.add_argument("--takeover", action="store_true",
                       help="when --state-dir is locked by another serve, "
                            "wait as a standby and take over when the "
                            "holder exits (leader-election analog); "
                            "default is to refuse immediately")
    serve.add_argument("--standby", action="store_true",
                       help="run as a HOT standby of --peer: mirror its "
                            "state over the watch stream, serve reads, "
                            "refuse writes with a leader hint, and "
                            "promote (epoch-fenced warm start) when the "
                            "leader dies (grove_tpu/ha)")
    serve.add_argument("--peer", help="the leader's URL for --standby")
    serve.add_argument("--replica",
                       help="this replica's name in leadership gauges "
                            "and /debug/leadership (default $GROVE_REPLICA"
                            " or r0/standby)")
    serve.add_argument("--ca", help="CA certificate to pin for an https "
                                    "--peer (default $GROVE_API_CA)")
    serve.set_defaults(fn=cmd_serve)

    agent_p = sub.add_parser(
        "agent", help="run a per-host node agent (process kubelet + "
                      "heartbeat) against a remote serve daemon")
    agent_p.add_argument("--server", default=default_server)
    agent_p.add_argument("--node", required=True,
                         help="this host's Node name")
    agent_p.add_argument("--register",
                         help="gen:topology:slice:worker — self-register "
                              "the Node if absent")
    agent_p.add_argument("--namespace", default="default")
    agent_p.add_argument("--token", help="bearer token "
                                         "(default $GROVE_API_TOKEN)")
    add_ca(agent_p)
    agent_p.add_argument("--tick", type=float, default=0.25)
    agent_p.add_argument("--workdir")
    agent_p.set_defaults(fn=cmd_agent)

    render = sub.add_parser(
        "render-deploy",
        help="render the deploy bundle (Helm-chart analog): GKE "
             "manifests or a systemd unit set from a values file")
    render.add_argument("--values", help="values YAML (defaults if omitted)")
    render.add_argument("--target", choices=("gke", "systemd"),
                        default="gke")
    render.add_argument("--out", required=True, help="output directory")
    render.set_defaults(fn=cmd_render_deploy)

    lint = sub.add_parser(
        "lint",
        help="grovelint: AST invariant rules over the tree "
             "(exit 0 clean, 1 findings; --baseline gates on NEW "
             "findings only)")
    lint.add_argument("paths", nargs="*",
                      help="files/dirs (default: grove_tpu tests tools "
                           "bench.py)")
    lint.add_argument("--json", action="store_true",
                      help="machine-readable JSON report")
    lint.add_argument("--baseline", help="prior JSON report; only NEW "
                                         "findings fail")
    lint.add_argument("--write-baseline", help="write the JSON report "
                                               "to this path")
    lint.set_defaults(fn=cmd_lint)

    run = sub.add_parser("run", help="run a cluster, apply manifests, report")
    run.add_argument("--fleet", default="v5e:4x4:2",
                     help="fleet spec gen:topology:count[,...]")
    run.add_argument("--apply", help="YAML manifest to apply")
    run.add_argument("--timeout", type=float, default=30.0)
    run.add_argument("--hold", type=float, default=0.0,
                     help="keep the cluster up after reporting")
    run.add_argument("--real", action="store_true",
                     help="run pods as real OS processes (process kubelet) "
                          "instead of synthetic fake-node readiness")
    run.add_argument("--config",
                     help="OperatorConfiguration YAML (component-config)")
    run.set_defaults(fn=cmd_run)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
