"""Llama-family decoder in pure functional JAX — the flagship model.

Design notes (TPU-first):
- Params are a plain pytree with all layers stacked on a leading axis; the
  forward pass is one `lax.scan` over layers → one compiled layer body,
  fast compile, and XLA pipelines HBM prefetch of layer weights.
- bf16 params/activations, f32 for softmax/norm accumulation
  (`preferred_element_type`) — keeps the MXU fed at its native precision.
- No Python control flow on traced values; decode uses static max lengths
  with per-lane `lengths` masking (see grove_tpu/ops/kvcache.py).
- Sharding is applied externally via grove_tpu.parallel.sharding rules;
  model code is mesh-agnostic.

This is the serving workload Grove-the-reference orchestrates but never
implements (the reference runs vLLM/SGLang inside pods — README.md:35-41);
here it is part of the framework so a PodCliqueSet can deploy a complete
disaggregated prefill/decode Llama service with no external engine.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from grove_tpu.ops import kvcache
from grove_tpu.ops.attention import causal_attention, decode_attention
from grove_tpu.ops.kvcache import KVCache
from grove_tpu.ops.norms import rms_norm
from grove_tpu.ops.rope import apply_rope, rope_table

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 5632
    head_dim: int = 128
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def params_bytes(self) -> int:
        c = self
        per_layer = (2 * c.d_model
                     + c.d_model * c.n_heads * c.head_dim
                     + 2 * c.d_model * c.n_kv_heads * c.head_dim
                     + c.n_heads * c.head_dim * c.d_model
                     + 3 * c.d_model * c.d_ff)
        total = (2 * c.vocab_size * c.d_model + c.d_model
                 + c.n_layers * per_layer)
        return total * jnp.dtype(c.dtype).itemsize


CONFIGS: dict[str, LlamaConfig] = {
    # Tiny config for unit tests and multichip dry-runs (divisible by 8 for
    # tp=4/sp=2 virtual meshes).
    "test-tiny": LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                             n_heads=8, n_kv_heads=4, d_ff=128, head_dim=8,
                             max_seq_len=128),
    # ~1.1B — fits a single v5e chip in bf16 with room for KV cache; the
    # single-chip bench model.
    "llama-1b": LlamaConfig(vocab_size=32000, d_model=2048, n_layers=16,
                            n_heads=16, n_kv_heads=8, d_ff=5632, head_dim=128,
                            max_seq_len=2048),
    # Llama-3-8B-shaped (docs/perf projections; needs >1 chip for headroom).
    "llama-8b": LlamaConfig(vocab_size=128256, d_model=4096, n_layers=32,
                            n_heads=32, n_kv_heads=8, d_ff=14336, head_dim=128,
                            max_seq_len=8192),
    # Llama-70B-shaped — the north-star disaggregated serving target
    # (BASELINE.md: v5e-256, tp over ICI).
    "llama-70b": LlamaConfig(vocab_size=128256, d_model=8192, n_layers=80,
                             n_heads=64, n_kv_heads=8, d_ff=28672, head_dim=128,
                             max_seq_len=8192),
}


def draft_config(cfg: LlamaConfig) -> LlamaConfig:
    """Shrink a target config into its speculative-decoding draft.

    The draft shares the tokenizer (vocab), rope geometry, and context
    budget with the target — acceptance math compares token ids, so the
    vocab MUST match — but runs ~1/4 of the width/depth. head_dim is
    kept so the draft reuses the target's paged block geometry (same
    block tables address both pools; only n_kv/layers differ).
    """
    n_heads = max(2, cfg.n_heads // 4)
    n_kv = max(1, cfg.n_kv_heads // 4)
    while n_heads % n_kv:  # GQA grouping needs an even split
        n_kv -= 1
    return dataclasses.replace(
        cfg,
        d_model=max(32, cfg.d_model // 4),
        n_layers=max(1, cfg.n_layers // 4),
        n_heads=n_heads,
        n_kv_heads=n_kv,
        d_ff=max(64, cfg.d_ff // 4),
    )


def norm_init(cfg: LlamaConfig, shape) -> jnp.ndarray:
    return jnp.ones(shape, cfg.dtype)


def dense_init(cfg: LlamaConfig, key, shape, fan_in) -> jnp.ndarray:
    return (jax.random.normal(key, shape, jnp.float32)
            * (fan_in ** -0.5)).astype(cfg.dtype)


def init_params(cfg: LlamaConfig, key: jax.Array,
                include_mlp: bool = True) -> Params:
    """Initialise a parameter pytree (layers stacked on axis 0).

    ``include_mlp=False`` skips the dense MLP leaves (model families that
    replace the MLP — e.g. MoE — must not transiently allocate it; for
    real configs that is a multi-GB throwaway).
    """
    k_embed, k_head, k_layers = jax.random.split(key, 3)
    d, h, kv, hd, ff, L = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                           cfg.head_dim, cfg.d_ff, cfg.n_layers)

    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn_norm": norm_init(cfg, (L, d)),
        "mlp_norm": norm_init(cfg, (L, d)),
        "wq": dense_init(cfg, ks[0], (L, d, h, hd), d),
        "wk": dense_init(cfg, ks[1], (L, d, kv, hd), d),
        "wv": dense_init(cfg, ks[2], (L, d, kv, hd), d),
        "wo": dense_init(cfg, ks[3], (L, h, hd, d), h * hd),
    }
    if include_mlp:
        layers.update({
            "w_gate": dense_init(cfg, ks[4], (L, d, ff), d),
            "w_up": dense_init(cfg, ks[5], (L, d, ff), d),
            "w_down": dense_init(cfg, ks[6], (L, ff, d), ff),
        })
    return {
        "tok_embed": dense_init(cfg, k_embed, (cfg.vocab_size, d), d),
        "lm_head": dense_init(cfg, k_head, (d, cfg.vocab_size), d),
        "final_norm": norm_init(cfg, (d,)),
        "layers": layers,
    }


def _w(w):
    """Materialize a (possibly int8-quantized) weight for a matmul. XLA
    fuses the upcast+scale into the operand read, so quantized weights
    cross HBM as int8 (serving/quant.py)."""
    return w.materialize() if hasattr(w, "materialize") else w


def _qkv(cfg: LlamaConfig, x, lp, cos, sin, positions):
    """Pre-norm + QKV projections + rope. Shared by prefill and decode."""
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wq"]))
    k = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wk"]))
    v = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wv"]))
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    return q, k, v


def _attn_out(x, attn, lp, tp_axis=None):
    out = jnp.einsum("bshk,hkd->bsd", attn, _w(lp["wo"]))
    if tp_axis is not None:
        # Megatron-style manual TP inside shard_map: heads are sharded over
        # tp, so wo produces a partial sum — reduce before the residual.
        out = lax.psum(out, tp_axis)
    return x + out.astype(x.dtype)


def _mlp_block(cfg: LlamaConfig, x, lp, tp_axis=None):
    """Pre-norm SwiGLU MLP with residual. Shared by prefill and decode."""
    hm = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jnp.einsum("bsd,df->bsf", hm, _w(lp["w_gate"]))
    up = jnp.einsum("bsd,df->bsf", hm, _w(lp["w_up"]))
    out = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                     _w(lp["w_down"]))
    if tp_axis is not None:
        # ff hidden dim sharded over tp → w_down yields a partial sum.
        out = lax.psum(out, tp_axis)
    return x + out.astype(x.dtype)


def _layer_prefill(cfg: LlamaConfig, x, lp, cos, sin, positions, q_offset,
                   attn_fn=None, tp_axis=None):
    """One decoder layer over a full sequence. x: [b, s, d_model].

    ``attn_fn(q, k, v)`` overrides the attention implementation (ring
    attention for sequence-parallel long context; pallas flash kernels).
    ``tp_axis`` enables manual tensor parallelism under shard_map: heads
    and ff are axis-sharded and the output projections psum over it.
    """
    q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
    if attn_fn is None:
        attn = causal_attention(q, k, v, q_offset=q_offset)
    else:
        attn = attn_fn(q, k, v)
    x = _attn_out(x, attn, lp, tp_axis=tp_axis)
    x = _mlp_block(cfg, x, lp, tp_axis=tp_axis)
    return x, (k, v)


def embed(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding (shared by dense/ring/pipeline forwards)."""
    te = params["tok_embed"]
    if hasattr(te, "materialize"):  # int8: gather rows, then scale them
        return (te.q[tokens].astype(te.scale.dtype)
                * te.scale[tokens]).astype(cfg.dtype)
    return te[tokens].astype(cfg.dtype)


def head(cfg: LlamaConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + LM head (shared by dense/ring/pipeline forwards)."""
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return jnp.einsum("bsd,dv->bsv", x, _w(params["lm_head"]),
                      preferred_element_type=jnp.float32)


def forward(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            positions: jnp.ndarray | None = None,
            mesh=None, ring: bool = False,
            sp: str | None = None) -> jnp.ndarray:
    """Full forward pass → logits [b, s, vocab]. Training / compile-check path.

    ``sp`` selects the sequence-parallel attention strategy over the sp
    mesh axis (requires ``mesh``): ``"ring"`` — K/V blocks rotate via
    ppermute, O(s/sp) memory, any head count; ``"ulysses"`` — two
    all_to_all exchanges swap seq for head sharding, fewer collectives,
    heads must divide sp. ``ring=True`` is the legacy spelling of
    ``sp="ring"``.
    """
    b, s = tokens.shape
    _pos_arg = positions
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens)

    if ring:
        assert sp in (None, "ring"), f"ring=True conflicts with sp={sp!r}"
        sp = "ring"
    attn_fn = None
    if sp is not None:
        assert mesh is not None, "sequence parallelism needs the mesh"
        # Both SP paths derive causality from shard offsets and assume
        # default contiguous positions; custom positions would silently
        # disagree with the mask.
        assert _pos_arg is None, \
            "sequence parallelism does not support custom positions"
        if sp == "ring":
            from grove_tpu.ops.ringattention import ring_attention
            attn_fn = lambda q, k, v: ring_attention(mesh, q, k, v)  # noqa: E731
        elif sp == "ulysses":
            from grove_tpu.ops.ulysses import ulysses_attention
            attn_fn = lambda q, k, v: ulysses_attention(mesh, q, k, v)  # noqa: E731
        else:
            raise ValueError(f"unknown sp strategy {sp!r} "
                             "(expected 'ring' or 'ulysses')")

    def body(x, lp):
        x, _ = _layer_prefill(cfg, x, lp, cos, sin, positions, 0,
                              attn_fn=attn_fn)
        return x, None

    x, _ = lax.scan(body, x, params["layers"])
    return head(cfg, params, x)


def prefill(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            cache: KVCache,
            lengths: jnp.ndarray | None = None) -> tuple[jnp.ndarray, KVCache]:
    """Prefill: run the prompt, fill the cache, return last-token logits.

    tokens: [b, s], right-padded to a static s; ``lengths`` [b] gives the
    true prompt length per lane (defaults to s for all lanes). Cache lanes
    are overwritten from position 0. Pad positions ≥ length are causally
    invisible to valid tokens and marked invalid in the returned cache, and
    the returned logits are taken at each lane's last *valid* token.

    Returns (logits [b, vocab], cache with lengths set per lane).
    """
    b, s = tokens.shape
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens)

    # TPU → pallas flash kernel; anything else → the XLA formulation.
    # Trace-time choice, baked into the compiled prefill executable.
    from grove_tpu.ops.attention import pick_causal_attention
    attn_fn = pick_causal_attention(s, cfg.head_dim)

    def body(x, xs):
        lp, kc, vc = xs
        x, (k, v) = _layer_prefill(cfg, x, lp, cos, sin, positions, 0,
                                   attn_fn=attn_fn)
        kc = jax.vmap(kvcache.write_row, in_axes=(0, 0, None))(kc, k, 0)
        vc = jax.vmap(kvcache.write_row, in_axes=(0, 0, None))(vc, v, 0)
        return x, (kc, vc)

    x, (k_all, v_all) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # Last valid token per lane (ragged batches: pad rows carry garbage).
    x_last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = jnp.einsum("bd,dv->bv", x_last, _w(params["lm_head"]),
                        preferred_element_type=jnp.float32)
    new_cache = KVCache(k=k_all, v=v_all, lengths=lengths.astype(jnp.int32))
    return logits, new_cache


def prefill_chunk(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                  cache: KVCache, offset: int
                  ) -> tuple[jnp.ndarray, KVCache]:
    """One chunked-prefill window: process tokens [b, c] at absolute
    positions [offset, offset+c) against a cache whose first ``offset``
    rows are already filled. ``offset`` is STATIC (one executable per
    window position — chunked prefill compiles ceil(s/c) programs, the
    standard trade for bounded attention reads). Attention reads only
    cache[:offset+c], so peak activation memory is O(c · ctx) instead
    of the full prompt's O(s²) logits block.

    Returns (hidden states [b, c, d_model] after final norm, cache with
    rows [offset, offset+c) filled) — the driver gathers per-lane
    last-valid rows and applies the LM head once.

    NOTE: driven through ``prefill_chunked``, the CALLER'S input cache
    is DONATED to the first window's executable (bounded memory is the
    feature's point — an undonated cache would transiently double the
    KV footprint per window on TPU). Do not reuse a cache object after
    passing it in; take the returned one.
    """
    b, s_c = tokens.shape
    end = offset + s_c
    positions = jnp.broadcast_to(offset + jnp.arange(s_c), (b, s_c))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens)
    # Same trace-time impl selection as one-shot prefill: the offset-0
    # window is square/causal and flash-eligible; later windows are
    # rectangular (q vs a longer prefix), which the kernel does not
    # tile — pick_causal_attention returns None there and the XLA
    # formulation runs (grove_tpu/ops/attention.py:51).
    from grove_tpu.ops.attention import pick_causal_attention
    flash = pick_causal_attention(s_c, cfg.head_dim, q_offset=offset)

    def body(x, xs):
        lp, kc, vc = xs
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        kc = jax.vmap(kvcache.write_row, in_axes=(0, 0, None))(kc, k, offset)
        vc = jax.vmap(kvcache.write_row, in_axes=(0, 0, None))(vc, v, offset)
        if flash is not None and offset == 0 and end == s_c:
            attn = flash(q, k, v)
        else:
            attn = causal_attention(q, kc[:, :end], vc[:, :end],
                                    q_offset=offset)
        x = _attn_out(x, attn, lp)
        x = _mlp_block(cfg, x, lp)
        return x, (kc, vc)

    x, (k_all, v_all) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x, KVCache(k=k_all, v=v_all,
                      lengths=jnp.full((b,), end, jnp.int32))


def prefill_chunked(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                    cache: KVCache, chunk: int,
                    lengths: jnp.ndarray | None = None
                    ) -> tuple[jnp.ndarray, KVCache]:
    """Bounded-memory prefill: the prompt is processed in ``chunk``-sized
    windows (vLLM-style chunked prefill), each a separate executable
    whose attention reads only the live cache prefix. The input
    ``cache`` is DONATED (see ``prefill_chunk``): use the returned
    cache, never the argument, after this call. Matches ``prefill``
    up to float accumulation order (XLA blocks the windowed matmuls
    differently; greedy decode from the two caches agrees — proven by
    tests/test_model_llama.py). Ragged batches supported: each lane's
    logits are taken at its last VALID position (``lengths``), gathered
    from whichever window that position falls in.

    Returns (logits [b, vocab], cache with lengths set per lane)."""
    b, s = tokens.shape
    assert s % chunk == 0 or s < chunk, \
        f"prompt length {s} must divide into chunks of {chunk}"
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    else:
        lengths = jnp.asarray(lengths, jnp.int32)
    fn = _jitted_prefill_chunk(cfg)
    x_last = jnp.zeros((b, cfg.d_model), cfg.dtype)
    for off in range(0, s, chunk):
        x_chunk, cache = fn(params, tokens[:, off:off + chunk], cache, off)
        c = x_chunk.shape[1]
        # Lanes whose last valid token lands in this window keep its row.
        idx = jnp.clip(lengths - 1 - off, 0, c - 1)
        rows = jnp.take_along_axis(
            x_chunk, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        in_window = (lengths - 1 >= off) & (lengths - 1 < off + c)
        x_last = jnp.where(in_window[:, None], rows, x_last)
    logits = jnp.einsum("bd,dv->bv", x_last, _w(params["lm_head"]),
                        preferred_element_type=jnp.float32)
    return logits, cache._replace(lengths=lengths)


@functools.lru_cache(maxsize=None)
def _jitted_prefill_chunk(cfg: LlamaConfig):
    return jax.jit(functools.partial(prefill_chunk, cfg),
                   static_argnums=(3,), donate_argnums=(2,))


def decode_step(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                cache: KVCache) -> tuple[jnp.ndarray, KVCache]:
    """One decode step. tokens: [b] (last sampled token per lane).

    Returns (logits [b, vocab], cache advanced by one).

    Capacity: callers must not decode a lane past ``cache.max_len`` — the
    cache write clamps silently (see kvcache.write_row); check
    ``cache.has_room()`` before stepping (the serving engine evicts or
    stops lanes that are full).
    """
    b = tokens.shape[0]
    positions = cache.lengths[:, None]  # [b, 1]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens[:, None])  # [b, 1, d]
    new_lengths = cache.lengths + 1

    def body(x, xs):
        lp, kc, vc = xs
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        kc = jax.vmap(kvcache.write_row)(kc, k, cache.lengths)
        vc = jax.vmap(kvcache.write_row)(vc, v, cache.lengths)
        attn = decode_attention(q, kc, vc, new_lengths)
        x = _attn_out(x, attn, lp)
        x = _mlp_block(cfg, x, lp)
        return x, (kc, vc)

    x, (k_all, v_all) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bd,dv->bv", x[:, 0], _w(params["lm_head"]),
                        preferred_element_type=jnp.float32)
    return logits, KVCache(k=k_all, v=v_all, lengths=new_lengths)


# ---- paged (block-table) execution path ------------------------------
# The continuous-batching engine's memory model (serving/kvcache.py):
# K/V live in fixed-size blocks [layers, num_blocks, block_size, n_kv, d]
# and a sequence's tokens are addressed through its block table. These
# kernels take the raw pool arrays (not the PagedKV wrapper) so the
# model stays import-cycle-free and mesh-agnostic — the GSPMD shardings
# are applied by the engine's jit (parallel/sharding.py).


def _paged_gather(cache_blocks: jnp.ndarray,
                  tables: jnp.ndarray) -> jnp.ndarray:
    """Gather a batch's KV sequences out of the block pool.

    cache_blocks: [num_blocks, bs, n_kv, d]; tables: [b, w] int32 →
    [b, w*bs, n_kv, d] in position order (table order IS sequence
    order). Padded table rows point at the null block; the attention
    length mask discards whatever lives there.
    """
    b, w = tables.shape
    nb, bs, n_kv, d = cache_blocks.shape
    return cache_blocks[tables].reshape(b, w * bs, n_kv, d)


def _paged_scatter(cache_blocks: jnp.ndarray, kv: jnp.ndarray,
                   tables: jnp.ndarray, positions: jnp.ndarray,
                   valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Write ``kv`` [b, s, n_kv, d] at token ``positions`` [b, s]
    through the block tables (one flat scatter). Positions past a
    table's real width resolve to the null block (table padding), so
    inactive batch slots write garbage nowhere that matters — the
    price of static shapes, same trade as the lanes engine's
    inactive-lane compute.

    ``valid`` [b, s] bool, when given, reroutes masked-out writes to
    the NULL block explicitly. Required whenever a position may exceed
    the table's backed capacity: ``take_along_axis`` would CLAMP the
    block index into the last real block and the garbage write would
    race live K/V at the same flat slot (the chunk-padding overflow —
    a padded prefill tail past per-sequence capacity corrupted real
    prompt tokens before this mask existed)."""
    nb, bs = cache_blocks.shape[0], cache_blocks.shape[1]
    block = jnp.take_along_axis(tables, positions // bs, axis=1)  # [b, s]
    flat_idx = block * bs + positions % bs
    if valid is not None:
        # Invalid rows land in the null block (block 0, slots cycled by
        # sequence position so collisions stay inside it).
        flat_idx = jnp.where(valid, flat_idx, positions % bs)
    flat = cache_blocks.reshape((nb * bs,) + cache_blocks.shape[2:])
    flat = flat.at[flat_idx.reshape(-1)].set(
        kv.reshape((-1,) + kv.shape[2:]).astype(flat.dtype))
    return flat.reshape(cache_blocks.shape)


# int8 paged KV (GROVE_KV_QUANT=int8): K/V blocks store int8 payloads
# with a per-slot-per-head symmetric scale alongside the pool —
# [num_blocks, bs, n_kv] f32 per layer. Per-SLOT (not per-block) scales
# are forced by incremental writes: a whole-block amax would need the
# other slots' values at write time, which a decode step doesn't have.
# Quantization happens in the scatter, dequantization in the gather, so
# int8 is what crosses HBM; XLA fuses the upcast*scale into the
# attention matmul's operand read (same trade as weight QTensors,
# serving/quant.py).

KV_SCALE_EPS = 1e-8


def _paged_scatter_q(cache_blocks: jnp.ndarray, scales: jnp.ndarray,
                     kv: jnp.ndarray, tables: jnp.ndarray,
                     positions: jnp.ndarray,
                     valid: jnp.ndarray | None = None
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantizing variant of ``_paged_scatter``: write ``kv``
    [b, s, n_kv, d] as int8 rows plus per-(slot, head) scales.
    cache_blocks: int8 [nb, bs, n_kv, d]; scales: [nb, bs, n_kv].
    The scale of a row depends only on that row's values, so a k-wide
    verify chunk quantizes each row exactly as a sequential decode step
    would — speculative/int8 composition stays bitwise."""
    nb, bs = cache_blocks.shape[0], cache_blocks.shape[1]
    f = kv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)  # [b, s, n_kv]
    scale = jnp.maximum(amax, KV_SCALE_EPS) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    block = jnp.take_along_axis(tables, positions // bs, axis=1)  # [b, s]
    flat_idx = block * bs + positions % bs
    if valid is not None:
        flat_idx = jnp.where(valid, flat_idx, positions % bs)
    flat = cache_blocks.reshape((nb * bs,) + cache_blocks.shape[2:])
    flat = flat.at[flat_idx.reshape(-1)].set(q.reshape((-1,) + q.shape[2:]))
    sflat = scales.reshape(nb * bs, scales.shape[2])
    sflat = sflat.at[flat_idx.reshape(-1)].set(
        scale.reshape(-1, scale.shape[2]).astype(scales.dtype))
    return flat.reshape(cache_blocks.shape), sflat.reshape(scales.shape)


def _paged_gather_q(cache_blocks: jnp.ndarray, scales: jnp.ndarray,
                    tables: jnp.ndarray, dtype) -> jnp.ndarray:
    """Dequantizing variant of ``_paged_gather``: int8 rows × scales →
    ``dtype`` [b, w*bs, n_kv, d]."""
    b, w = tables.shape
    nb, bs, n_kv, d = cache_blocks.shape
    vals = cache_blocks[tables].reshape(b, w * bs, n_kv, d)
    s = scales[tables].reshape(b, w * bs, n_kv)
    return (vals.astype(jnp.float32) * s[..., None]).astype(dtype)


def paged_block_copy(dst_k: jnp.ndarray, dst_v: jnp.ndarray,
                     src_k: jnp.ndarray, src_v: jnp.ndarray,
                     src: jnp.ndarray, dst: jnp.ndarray
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Copy a payload's K/V block rows from a SOURCE pool into a
    DESTINATION pool — the disaggregated handoff primitive (a block-id
    remap plus this copy; docs/design/disaggregated-serving.md).
    ``src``/``dst`` are traced int32[W] id vectors with W fixed at the
    engine's max table width, padded with the NULL block: ONE
    shape-static executable moves a whole payload in one dispatch (a
    per-block scalar variant cost a dispatch per cold block — the
    dominant handoff overhead on short suffixes). Pad pairs write the
    source's null-block garbage over the destination's null block,
    which holds garbage by design; duplicate null scatter indices all
    carry that same row, so the scatter stays deterministic where it
    matters. Pools may differ in block count; block geometry must
    match."""
    return (dst_k.at[:, dst].set(src_k[:, src]),
            dst_v.at[:, dst].set(src_v[:, src]))


def paged_block_copy_q(dst_k: jnp.ndarray, dst_v: jnp.ndarray,
                       dst_ks: jnp.ndarray, dst_vs: jnp.ndarray,
                       src_k: jnp.ndarray, src_v: jnp.ndarray,
                       src_ks: jnp.ndarray, src_vs: jnp.ndarray,
                       src: jnp.ndarray, dst: jnp.ndarray
                       ) -> tuple[jnp.ndarray, ...]:
    """int8-KV variant of ``paged_block_copy`` (same null-padded id
    vectors): quantized payload rows AND their per-slot dequant scales
    move together, as-is — the handoff never requantizes (an int8
    block without its scale row dequantizes to garbage)."""
    return (dst_k.at[:, dst].set(src_k[:, src]),
            dst_v.at[:, dst].set(src_v[:, src]),
            dst_ks.at[:, dst].set(src_ks[:, src]),
            dst_vs.at[:, dst].set(src_vs[:, src]))


def decode_step_paged(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                      kv_k: jnp.ndarray, kv_v: jnp.ndarray,
                      tables: jnp.ndarray, lengths: jnp.ndarray,
                      k_scale: jnp.ndarray | None = None,
                      v_scale: jnp.ndarray | None = None
                      ) -> tuple[jnp.ndarray, ...]:
    """One decode step over block tables. tokens: [b]; kv pools:
    [layers, num_blocks, bs, n_kv, d]; tables: [b, w]; lengths: [b] =
    tokens already in cache (the new token writes at that position).

    Returns (logits [b, vocab], new kv_k, new kv_v) — plus the updated
    scale pools when ``k_scale``/``v_scale`` are given (int8 KV).
    Attention reads only the gathered w*bs window — the whole point: w
    is the BUCKETED width of the live sequences, not the engine-wide
    worst case, so a 20-token conversation stops paying a max_len-wide
    HBM read.
    """
    b = tokens.shape[0]
    positions = lengths[:, None]  # [b, 1]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens[:, None])  # [b, 1, d]
    new_lengths = lengths + 1
    quant = k_scale is not None

    def body(x, xs):
        if quant:
            lp, kc, vc, ks, vs = xs
        else:
            lp, kc, vc = xs
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        if quant:
            kc, ks = _paged_scatter_q(kc, ks, k, tables, positions)
            vc, vs = _paged_scatter_q(vc, vs, v, tables, positions)
            kg = _paged_gather_q(kc, ks, tables, cfg.dtype)
            vg = _paged_gather_q(vc, vs, tables, cfg.dtype)
        else:
            kc = _paged_scatter(kc, k, tables, positions)
            vc = _paged_scatter(vc, v, tables, positions)
            kg, vg = _paged_gather(kc, tables), _paged_gather(vc, tables)
        attn = decode_attention(q, kg, vg, new_lengths)
        x = _attn_out(x, attn, lp)
        x = _mlp_block(cfg, x, lp)
        return x, ((kc, vc, ks, vs) if quant else (kc, vc))

    xs = (params["layers"], kv_k, kv_v)
    if quant:
        xs = xs + (k_scale, v_scale)
    x, outs = lax.scan(body, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bd,dv->bv", x[:, 0], _w(params["lm_head"]),
                        preferred_element_type=jnp.float32)
    return (logits,) + tuple(outs)


def prefill_chunk_paged(cfg: LlamaConfig, params: Params,
                        tokens: jnp.ndarray, kv_k: jnp.ndarray,
                        kv_v: jnp.ndarray, tables: jnp.ndarray,
                        offset: jnp.ndarray, logit_idx: jnp.ndarray,
                        n_valid: jnp.ndarray | None = None,
                        k_scale: jnp.ndarray | None = None,
                        v_scale: jnp.ndarray | None = None
                        ) -> tuple[jnp.ndarray, ...]:
    """One chunked-prefill window over block tables.

    tokens: [b, c] at absolute positions [offset, offset+c); ``offset``
    and ``logit_idx`` are TRACED scalars — one executable per
    (c, table-width) shape that every window position reuses. The
    contiguous ``prefill_chunk`` compiles one program per STATIC
    offset; the paged engine interleaves chunks of many prompts with
    decode steps, so per-offset executables would be a recompile storm
    by construction.

    ``n_valid`` (traced scalar; default c) is the count of REAL tokens
    in this chunk — padded tail rows scatter to the null block instead
    of clamping into the sequence's last backed block (see
    ``_paged_scatter``; padded rows are causally invisible to valid
    queries regardless).

    Attention is the plain XLA formulation (a traced offset rules out
    the flash kernel's trace-time tiling decision); the window reads
    only the gathered w*bs prefix, which is the bounded-memory property
    chunking exists for. Returns (logits [b, vocab] taken at row
    ``logit_idx`` — the caller passes the last valid row for the chunk
    that completes the prompt, anything for earlier chunks — plus the
    updated pools).
    """
    b, c = tokens.shape
    positions = jnp.broadcast_to(offset + jnp.arange(c)[None, :], (b, c))
    if n_valid is None:
        n_valid = jnp.int32(c)
    valid = jnp.broadcast_to(jnp.arange(c)[None, :] < n_valid, (b, c))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens)
    quant = k_scale is not None

    def body(x, xs):
        if quant:
            lp, kc, vc, ks, vs = xs
        else:
            lp, kc, vc = xs
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        if quant:
            kc, ks = _paged_scatter_q(kc, ks, k, tables, positions,
                                      valid=valid)
            vc, vs = _paged_scatter_q(vc, vs, v, tables, positions,
                                      valid=valid)
            kg = _paged_gather_q(kc, ks, tables, cfg.dtype)
            vg = _paged_gather_q(vc, vs, tables, cfg.dtype)
        else:
            kc = _paged_scatter(kc, k, tables, positions, valid=valid)
            vc = _paged_scatter(vc, v, tables, positions, valid=valid)
            kg, vg = _paged_gather(kc, tables), _paged_gather(vc, tables)
        attn = causal_attention(q, kg, vg, q_offset=offset)
        x = _attn_out(x, attn, lp)
        x = _mlp_block(cfg, x, lp)
        return x, ((kc, vc, ks, vs) if quant else (kc, vc))

    xs = (params["layers"], kv_k, kv_v)
    if quant:
        xs = xs + (k_scale, v_scale)
    x, outs = lax.scan(body, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    row = jnp.take(x, logit_idx, axis=1)  # [b, d] (clipped gather)
    logits = jnp.einsum("bd,dv->bv", row, _w(params["lm_head"]),
                        preferred_element_type=jnp.float32)
    return (logits,) + tuple(outs)


# ---- speculative decoding kernels ------------------------------------
# Verification of k drafted tokens is a (k+1)-wide chunked prefill with
# PER-POSITION logits and a PER-SEQUENCE causal offset (each sequence
# sits at its own length — the scalar-offset prefill chunk can't express
# that). Position i's logits depend only on cache rows < lengths+i plus
# chunk rows ≤ i, all of which hold exactly what a sequential greedy
# decode would have written — so argmax per position reproduces
# sequential greedy bitwise, which is what makes accept/reject exact
# rather than approximate.


def verify_chunk_paged(cfg: LlamaConfig, params: Params,
                       tokens: jnp.ndarray, kv_k: jnp.ndarray,
                       kv_v: jnp.ndarray, tables: jnp.ndarray,
                       lengths: jnp.ndarray,
                       limit: jnp.ndarray | None = None,
                       k_scale: jnp.ndarray | None = None,
                       v_scale: jnp.ndarray | None = None
                       ) -> tuple[jnp.ndarray, ...]:
    """Target-model verification chunk. tokens: [b, c] — row 0 is the
    last committed token, rows 1..c-1 the draft; row i writes its K/V at
    position lengths+i and its logits predict position lengths+i+1.

    ``limit`` [b] caps writes per sequence (min of max_len and the block
    table's backed capacity): rows at positions ≥ limit scatter to the
    null block. The engine clamps acceptance so committed tokens never
    depend on capped rows.

    Returns (all_logits [b, c, vocab], pools... [+ scale pools when
    quantized]).
    """
    b, c = tokens.shape
    positions = lengths[:, None] + jnp.arange(c)[None, :]  # [b, c]
    valid = None if limit is None else positions < limit[:, None]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = embed(cfg, params, tokens)
    quant = k_scale is not None

    def body(x, xs):
        if quant:
            lp, kc, vc, ks, vs = xs
        else:
            lp, kc, vc = xs
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        if quant:
            kc, ks = _paged_scatter_q(kc, ks, k, tables, positions,
                                      valid=valid)
            vc, vs = _paged_scatter_q(vc, vs, v, tables, positions,
                                      valid=valid)
            kg = _paged_gather_q(kc, ks, tables, cfg.dtype)
            vg = _paged_gather_q(vc, vs, tables, cfg.dtype)
        else:
            kc = _paged_scatter(kc, k, tables, positions, valid=valid)
            vc = _paged_scatter(vc, v, tables, positions, valid=valid)
            kg, vg = _paged_gather(kc, tables), _paged_gather(vc, tables)
        attn = causal_attention(q, kg, vg, q_offset=lengths)
        x = _attn_out(x, attn, lp)
        x = _mlp_block(cfg, x, lp)
        return x, ((kc, vc, ks, vs) if quant else (kc, vc))

    xs = (params["layers"], kv_k, kv_v)
    if quant:
        xs = xs + (k_scale, v_scale)
    x, outs = lax.scan(body, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    all_logits = jnp.einsum("bcd,dv->bcv", x, _w(params["lm_head"]),
                            preferred_element_type=jnp.float32)
    return (all_logits,) + tuple(outs)


def spec_step_paged(cfg: LlamaConfig, dcfg: LlamaConfig, params: Params,
                    dparams: Params, tokens: jnp.ndarray,
                    kv_k: jnp.ndarray, kv_v: jnp.ndarray,
                    draft_k: jnp.ndarray | None, draft_v: jnp.ndarray | None,
                    tables: jnp.ndarray, lengths: jnp.ndarray,
                    limit: jnp.ndarray, spec_k: int,
                    k_scale: jnp.ndarray | None = None,
                    v_scale: jnp.ndarray | None = None,
                    self_draft: bool = False) -> tuple[jnp.ndarray, ...]:
    """One fused speculative decode step: draft spec_k tokens with the
    draft model (greedy, on its own paged pool addressed by the SAME
    block tables), verify all of them plus the input token in one
    (spec_k+1)-wide target chunk, and accept the longest agreeing
    prefix + one bonus token — all inside a single dispatch, so the
    whole thing is one executable per (batch, width) bucket.

    Greedy acceptance: row i of the verify chunk emits the target's
    argmax after consuming [..., tokens, d_1..d_i]; a draft token d_i+1
    is accepted iff it equals that argmax. m = longest agreeing prefix;
    the committed tokens are d_1..d_m plus the target's argmax at row m
    (the "bonus"), which is exactly the token sequential greedy would
    emit — rejection costs nothing because rows past m sit ABOVE the
    new length (causally invisible) and are overwritten by the next
    dispatch's writes at those positions: rollback is pure bookkeeping,
    no block copies.

    ``limit`` [b] = per-sequence write cap (min(max_len, backed block
    capacity)); acceptance is clamped so new_lengths ≤ limit and every
    committed token's K/V row is real. Padded batch rows carry limit 0:
    their writes land in the null block and their lengths don't move.

    ``self_draft``: the drafter IS the target model (dcfg/dparams are
    cfg/params). A separate draft pool would then be a bitwise mirror
    of the target pool, so the scan drafts directly against the TARGET
    pool: its writes at positions lengths..lengths+k-1 are exactly what
    the verify chunk rewrites (chunked and sequential scatters agree
    bitwise), the verify chunk additionally covers the bonus position,
    and both the duplicate pool and the draft replay pass disappear —
    draft_k/draft_v must be None and are not returned.

    Returns (out_tokens [b, spec_k+1] int32, committed prefix padded
    with -1; next_tokens [b]; new_lengths [b]; target pools [+ scale
    pools when quantized]; draft pools unless self_draft).
    """
    b = tokens.shape[0]
    dcos, dsin = rope_table(dcfg.max_seq_len, dcfg.head_dim, dcfg.rope_theta)
    quant = k_scale is not None
    # Self-draft against a quantized target pool drafts THROUGH the
    # int8 path — the same dequantized history sequential greedy reads,
    # so draft/target agreement stays exact.
    dquant = quant and self_draft

    def draft_step(carry, _):
        if dquant:
            tok, dk, dv, dks, dvs, ln = carry
        else:
            tok, dk, dv, ln = carry
        positions = ln[:, None]  # [b, 1]
        dvalid = positions < limit[:, None]
        x = embed(dcfg, dparams, tok[:, None])

        def body(x, xs):
            if dquant:
                lp, kc, vc, ks, vs = xs
                q, k, v = _qkv(dcfg, x, lp, dcos, dsin, positions)
                kc, ks = _paged_scatter_q(kc, ks, k, tables, positions,
                                          valid=dvalid)
                vc, vs = _paged_scatter_q(vc, vs, v, tables, positions,
                                          valid=dvalid)
                kg = _paged_gather_q(kc, ks, tables, dcfg.dtype)
                vg = _paged_gather_q(vc, vs, tables, dcfg.dtype)
            else:
                lp, kc, vc = xs
                q, k, v = _qkv(dcfg, x, lp, dcos, dsin, positions)
                kc = _paged_scatter(kc, k, tables, positions, valid=dvalid)
                vc = _paged_scatter(vc, v, tables, positions, valid=dvalid)
                kg, vg = _paged_gather(kc, tables), _paged_gather(vc, tables)
            attn = decode_attention(q, kg, vg, ln + 1)
            x = _attn_out(x, attn, lp)
            x = _mlp_block(dcfg, x, lp)
            return x, ((kc, vc, ks, vs) if dquant else (kc, vc))

        xs = (dparams["layers"], dk, dv)
        if dquant:
            xs = xs + (dks, dvs)
        x, pools = lax.scan(body, x, xs)
        x = rms_norm(x, dparams["final_norm"], dcfg.norm_eps)
        lg = jnp.einsum("bd,dv->bv", x[:, 0], _w(dparams["lm_head"]),
                        preferred_element_type=jnp.float32)
        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return (nxt,) + tuple(pools) + (ln + 1,), nxt

    if self_draft:
        scan_pools = (kv_k, kv_v) + ((k_scale, v_scale) if quant else ())
    else:
        scan_pools = (draft_k, draft_v)
    carry, drafts = lax.scan(
        draft_step, (tokens.astype(jnp.int32),) + scan_pools + (lengths,),
        None, length=spec_k)
    drafts = jnp.transpose(drafts)  # [b, spec_k]
    if self_draft:
        # Thread the drafted-over target pool into verification: the
        # verify chunk rewrites those slots with identical values, so
        # this only preserves donation-friendly single ownership.
        if quant:
            kv_k, kv_v, k_scale, v_scale = carry[1:5]
        else:
            kv_k, kv_v = carry[1:3]
    else:
        draft_k, draft_v = carry[1:3]

    chunk = jnp.concatenate([tokens[:, None].astype(jnp.int32), drafts],
                            axis=1)  # [b, spec_k+1]
    if not self_draft:
        # Replay the whole chunk through the DRAFT model too: the
        # sequential scan above wrote draft K/V only for its own inputs
        # (positions lengths..lengths+k-1), but a full acceptance
        # commits through lengths+k — without this pass the draft pool
        # would hold a permanent hole at every last-draft position and
        # acceptance would degrade (verification never reads the draft
        # pool, so this is a draft-accuracy repair, not a correctness
        # one). Chunked and sequential writes are bitwise-identical for
        # the overlapping positions, so the replay only fills the hole.
        # The replayed logits are unused and XLA dead-code-eliminates
        # that lm_head.
        d_outs = verify_chunk_paged(dcfg, dparams, chunk, draft_k,
                                    draft_v, tables, lengths, limit=limit)
        draft_k, draft_v = d_outs[1], d_outs[2]
    outs = verify_chunk_paged(cfg, params, chunk, kv_k, kv_v, tables,
                              lengths, limit=limit,
                              k_scale=k_scale, v_scale=v_scale)
    all_logits = outs[0]
    tgt = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)  # [b, k+1]
    agree = (drafts == tgt[:, :-1]).astype(jnp.int32)        # [b, k]
    m = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)          # [b]
    # Clamp: committed token i's K/V lives at lengths+i, which must be
    # < limit; the bonus token needs no K/V row yet (it is next tick's
    # input). limit ≤ lengths means a full/padded row: commit nothing.
    m = jnp.minimum(m, jnp.maximum(limit - lengths - 1, 0))
    idx = jnp.arange(spec_k + 1)[None, :]
    drafts_p = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    bonus = jnp.take_along_axis(tgt, m[:, None], axis=1)     # [b, 1]
    out_tokens = jnp.where(idx == m[:, None], bonus, drafts_p)
    out_tokens = jnp.where(idx <= m[:, None], out_tokens, -1)
    new_lengths = jnp.minimum(lengths + m + 1,
                              jnp.maximum(limit, lengths))
    next_tokens = bonus[:, 0]
    ret = (out_tokens, next_tokens, new_lengths) + tuple(outs[1:])
    return ret if self_draft else ret + (draft_k, draft_v)


def next_token_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy (shared by all model families)."""
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def loss_fn(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            mesh=None, ring: bool = False,
            sp: str | None = None) -> jnp.ndarray:
    """Next-token cross-entropy (training path for the multichip dry-run)."""
    return next_token_loss(forward(cfg, params, tokens, mesh=mesh, ring=ring,
                                   sp=sp), tokens)
